"""Multi-host bootstrap: the JAX distributed runtime as process coordination.

TPU-native replacement for the reference's cluster plumbing (SURVEY §5.8:
ZooKeeper coordinates Kafka consumers and Spark-on-YARN executors; here the
JAX distributed runtime coordinates hosts, and XLA collectives over ICI/DCN
replace Spark shuffle/broadcast). Configure with::

    oryx.distributed {
      coordinator = "host0:8476"   # null = single-host (default)
      num-processes = 4            # total hosts in the job
      process-id = 0               # this host's rank
    }

On TPU pods the three values can usually be auto-detected from the
environment, in which case ``coordinator`` may be set with the other two left
null. ``initialize_from_config`` is idempotent and a no-op when no
coordinator is configured, so single-host deployments never pay for it; the
CLI calls it before constructing any layer.

After initialization, ``jax.devices()`` spans every host's chips and a
``ComputeContext`` mesh built from it shards programs across the whole pod —
the same code path as single-host, which is the point.
"""

from __future__ import annotations

import logging

log = logging.getLogger(__name__)

_initialized = False


def initialize_from_config(config) -> bool:
    """Join the multi-host job described by ``oryx.distributed.*``.

    Returns True when the distributed runtime was (or already is)
    initialized, False for single-host configs.
    """
    global _initialized
    if _initialized:
        return True
    coordinator = config.get_string("oryx.distributed.coordinator", None)
    if not coordinator:
        return False
    num_processes = config.get_int("oryx.distributed.num-processes", None)
    process_id = config.get_int("oryx.distributed.process-id", None)

    import jax

    log.info(
        "joining distributed job: coordinator=%s processes=%s rank=%s",
        coordinator, num_processes, process_id,
    )
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )
    _initialized = True
    log.info(
        "distributed runtime up: process %d/%d, %d global devices",
        jax.process_index(), jax.process_count(), len(jax.devices()),
    )
    return True


def is_initialized() -> bool:
    return _initialized
