"""Device mesh + compute context: the framework's execution substrate.

TPU-native replacement for the reference's Spark context plumbing
(lambda/AbstractSparkLayer.java:142-173 buildStreamingContext): instead of a
JavaStreamingContext wired to YARN executors, each layer gets a ComputeContext
holding a jax.sharding.Mesh built from config
(``oryx.{batch,speed}.streaming.config``: platform, mesh-shape, mesh-axes).

Conventions:
  * axis "data" shards batches (Spark RDD data-parallel equivalent);
  * axis "model" shards factor/parameter matrices (MLlib block-partitioned
    ALS equivalent); models add more axes as needed via shard_map/pjit;
  * single-device configs get a trivial 1-device mesh so model code is always
    written against a mesh and scales without change.
"""

from __future__ import annotations

import numpy as np


class ComputeContext:
    """Mesh + config handle passed to batch updates and model managers."""

    def __init__(self, config, tier: str = "batch"):
        import jax

        self.config = config
        self.tier = tier
        compute_key = f"oryx.{tier}.streaming.config"
        ccfg = config.get_config(compute_key) if config.has(compute_key) else None
        platform = ccfg.get_string("platform", None) if ccfg else None
        devices = jax.devices(platform) if platform else jax.devices()
        shape = ccfg.get_list("mesh-shape", None) if ccfg else None
        axes = tuple(ccfg.get_list("mesh-axes", ["data", "model"])) if ccfg else ("data", "model")
        if shape is None:
            shape = [len(devices)] + [1] * (len(axes) - 1)
        n_used = int(np.prod(shape))
        if n_used > len(devices):
            raise ValueError(f"mesh shape {shape} needs {n_used} devices, have {len(devices)}")
        dev_array = np.asarray(devices[:n_used]).reshape(shape)
        self.mesh = jax.sharding.Mesh(dev_array, axes)

    @property
    def num_devices(self) -> int:
        return self.mesh.size

    def sharding(self, *spec_axes: "str | None"):
        """NamedSharding over this mesh for the given per-dimension axis names."""
        import jax

        return jax.sharding.NamedSharding(self.mesh, jax.sharding.PartitionSpec(*spec_axes))

    def replicated(self):
        import jax

        return jax.sharding.NamedSharding(self.mesh, jax.sharding.PartitionSpec())


def make_mesh(n_devices: int | None = None, axes: tuple[str, ...] = ("data",), shape=None):
    """Standalone mesh helper for tests/entry points."""
    import jax

    devices = jax.devices()
    if n_devices is None:
        n_devices = len(devices)
    if shape is None:
        shape = (n_devices,) + (1,) * (len(axes) - 1)
    dev_array = np.asarray(devices[: int(np.prod(shape))]).reshape(shape)
    return jax.sharding.Mesh(dev_array, axes)
