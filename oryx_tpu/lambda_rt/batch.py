"""Batch layer: persist data, retrain, publish models on a long interval.

Equivalent of the reference's BatchLayer + BatchUpdateFunction +
SaveToHDFSFunction + UpdateOffsetsFn + DeleteOldDataFn
(framework/oryx-lambda/.../batch/BatchLayer.java:48-206,
BatchUpdateFunction.java:86-153, SaveToHDFSFunction.java, DeleteOldDataFn.java).

Per generation interval the layer: (1) calls the user BatchLayerUpdate with
the new-data slice and all past data (re-read from the DataStore, the
always-recomputable checkpoint), handing it a sync model producer on the
update topic; (2) persists the new slice as a timestamped segment; (3) writes
back consumed offsets; (4) TTL-GCs old data and model dirs.
"""

from __future__ import annotations

from typing import Sequence

from oryx_tpu.api.batch import BatchLayerUpdate
from oryx_tpu.api.keymessage import KeyMessage
from oryx_tpu.common import metrics as metrics_mod
from oryx_tpu.common import spans
from oryx_tpu.lambda_rt.layer import AbstractLayer
from oryx_tpu.store.datastore import DataStore, ModelStore
from oryx_tpu.transport.topic import TopicProducerImpl

log = spans.get_logger(__name__)

# step duration/items ride the StepTracer→registry bridge (oryx_step_* with
# tier="batch"); these add what the tracer cannot see — generations run and
# input volume handed to the user update
_GENERATIONS = metrics_mod.default_registry().counter(
    "oryx_batch_generations_total",
    "Batch generations run (empty-input generations included)",
)
_GENERATION_ITEMS = metrics_mod.default_registry().counter(
    "oryx_batch_generation_items_total",
    "Input items handed to the batch update across generations",
)


class BatchLayer(AbstractLayer):
    def __init__(self, config):
        super().__init__(config, "batch")
        storage = config.get_config("oryx.batch.storage")
        self.data_store = DataStore(storage.get_string("data-dir"))
        self.model_store = ModelStore(storage.get_string("model-dir"))
        self.max_age_data_hours = storage.get_int("max-age-data-hours", -1)
        self.max_age_model_hours = storage.get_int("max-age-model-hours", -1)
        self._update_instance: BatchLayerUpdate | None = None

    def start(self, interval_sec: float | None = None) -> None:
        self.assert_topics()
        self._update_instance = self.load_update_instance()
        log.info("starting batch layer; interval=%ss", interval_sec or self.generation_interval_sec)
        start_offset = self.input_start_offset()
        self.spawn(
            "OryxBatchLayer",
            lambda: self.run_microbatches(self._on_generation, interval_sec, start_offset),
        )

    def load_update_instance(self) -> BatchLayerUpdate:
        return self.load_manager_instance("oryx.batch.update-class", BatchLayerUpdate)

    def _on_generation(self, timestamp_ms: int, new_data: Sequence[KeyMessage]) -> None:
        _GENERATIONS.inc()
        if not new_data:
            log.info("no new data at generation %d", timestamp_ms)
        else:
            _GENERATION_ITEMS.inc(len(new_data))
            # 1. user update with past data + sync model producer
            past_data = list(self.data_store.read_all())
            context = self.get_context()
            # data identity for preemption-tolerant checkpoints: the input
            # positions this generation read through (checkpoint.fingerprint
            # folds them in, so a restarted generation — same uncommitted
            # offsets, same slice — resumes its own state and nothing else)
            context.input_offsets = self.current_input_offsets
            # freshness identity for the published model's provenance stamp
            # (lineage.make_stamp reads these off the context)
            context.input_watermark_ms = self.current_input_watermark_ms
            context.input_max_event_ms = self.current_input_max_event_ms
            producer = TopicProducerImpl(self.update_broker, self.update_topic)
            try:
                self._update_instance.run_update(
                    context,
                    timestamp_ms,
                    new_data,
                    past_data,
                    str(self.model_store.path),
                    producer,
                )
            finally:
                producer.close()
            # 2. persist the interval's data (skip empty, SaveToHDFSFunction)
            self.data_store.write_segment(timestamp_ms, list(new_data))
        # 3. offsets are stored by run_microbatches after return
        # 4. TTL GC (DeleteOldDataFn ×2, BatchLayer.java:135-146)
        self.data_store.delete_older_than(self.max_age_data_hours)
        self.model_store.delete_older_than(self.max_age_model_hours)
