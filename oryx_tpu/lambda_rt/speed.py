"""Speed layer: incremental model updates on a short interval.

Equivalent of the reference's SpeedLayer + SpeedLayerUpdate
(framework/oryx-lambda/.../speed/SpeedLayer.java:52-194,
SpeedLayerUpdate.java:51-63). Two concurrent activities:

  * an update-consumer thread replaying the update topic from ``earliest``
    into the SpeedModelManager (MODEL/MODEL-REF refresh + its own and the
    batch layer's "UP" messages — the speed layer hears its own updates,
    ALSSpeedModelManager.java:74-81);
  * a microbatch pump that calls build_updates on each input slice and
    publishes each update with key "UP" (async producer semantics).
"""

from __future__ import annotations

import json
from typing import Sequence

from oryx_tpu.api.keymessage import KeyMessage
from oryx_tpu.api.speed import SpeedModelManager
from oryx_tpu.common import lineage
from oryx_tpu.common import metrics as metrics_mod
from oryx_tpu.common import spans
from oryx_tpu.lambda_rt.layer import AbstractLayer
from oryx_tpu.transport.topic import ConsumeDataIterator, TopicProducerImpl, get_broker

log = spans.get_logger(__name__)

# microbatch duration/items ride the StepTracer→registry bridge (oryx_step_*
# with tier="speed"); this counts the layer's OUTPUT — "UP" updates published
_UPDATES_PUBLISHED = metrics_mod.default_registry().counter(
    "oryx_speed_updates_published_total",
    "Incremental model updates published by the speed layer",
)


class SpeedLayer(AbstractLayer):
    def __init__(self, config):
        super().__init__(config, "speed")
        self.model_manager: SpeedModelManager | None = None
        self._update_iterator: ConsumeDataIterator | None = None
        self._producer: TopicProducerImpl | None = None

    def start(self, interval_sec: float | None = None) -> None:
        self.assert_topics()
        self.model_manager = self.load_manager_instance(
            "oryx.speed.model-manager-class", SpeedModelManager
        )
        self._update_iterator = ConsumeDataIterator(
            get_broker(self.update_broker), self.update_topic, "earliest"
        )
        self._producer = TopicProducerImpl(self.update_broker, self.update_topic)
        log.info("starting speed layer; interval=%ss", interval_sec or self.generation_interval_sec)
        # update-consumer thread (SpeedLayer.java:116-123); messages bearing
        # a traceparent header (e.g. a batch-tier publish traced back to an
        # ingress request) are processed under a span continuing that trace
        traced_updates = spans.trace_consumed(
            self._update_iterator, "speed.consume_update",
            route="update-topic", attributes={"topic": self.update_topic},
        )
        self.spawn(
            "OryxSpeedLayerUpdateConsumerThread",
            lambda: self.model_manager.consume(traced_updates),
        )
        # per-microbatch updates (SpeedLayerUpdate)
        start_offset = self.input_start_offset()
        self.spawn(
            "OryxSpeedLayer",
            lambda: self.run_microbatches(self._on_microbatch, interval_sec, start_offset),
        )

    def _on_microbatch(self, timestamp_ms: int, new_data: Sequence[KeyMessage]) -> None:
        if not new_data:
            return
        updates = self.model_manager.build_updates(new_data)
        # fold-in provenance: each delta carries the input offsets/watermark
        # it incorporated, so the serving-side freshness watermark advances
        # BETWEEN batch generations (lineage.delta_consumed reads this)
        headers = None
        if self.config.get_bool("oryx.lineage.enabled", True):
            headers = {lineage.WATERMARK_HEADER: json.dumps({
                "offsets": {str(p): int(o) for p, o in
                            (self.current_input_offsets or {}).items()},
                "watermark_ms": self.current_input_watermark_ms,
            }, separators=(",", ":"))}
        for update in updates:
            self._producer.send("UP", update, headers=headers)
            _UPDATES_PUBLISHED.inc()

    def close(self) -> None:
        if self._update_iterator is not None:
            self._update_iterator.close()
        if self.model_manager is not None:
            self.model_manager.close()
        super().close()
