"""Shared layer base: config parsing, topic wiring, generation clock.

Equivalent of the reference's AbstractSparkLayer
(framework/oryx-lambda/.../AbstractSparkLayer.java:57-224): where that builds a
JavaStreamingContext + Kafka direct DStream, this builds a ComputeContext
(jax mesh) + a microbatch pump over the input topic that resumes from stored
offsets keyed by ``oryx.id`` (buildInputDStream:208-211).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Sequence

from oryx_tpu.api.keymessage import KeyMessage
from oryx_tpu.common import classutils
from oryx_tpu.common import compilecache
from oryx_tpu.common import metrics as metrics_mod
from oryx_tpu.common import spans
from oryx_tpu.common.tracing import StepTracer
from oryx_tpu.parallel.mesh import ComputeContext
from oryx_tpu.transport import topic as tp

log = spans.get_logger(__name__)

#: Per-generation cap on input-message continuation spans/links: a huge
#: replayed batch must not turn one generation into 10^6 span records (the
#: dropped remainder is still counted in the generation span's attributes).
MAX_TRACED_INPUTS_PER_GENERATION = 128


class AbstractLayer:
    def __init__(self, config, tier: str):
        self.config = config
        self.tier = tier
        metrics_mod.configure(config)  # batch/speed never build an HTTP app
        spans.configure(config)
        # batch/speed tiers recompile their training programs on every
        # process restart; the shared persistent compilation cache (and the
        # compile counter) applies to them exactly as to serving replicas
        compilecache.configure(config)
        self.tracer = StepTracer(config, tier)
        self.id = config.get_string("oryx.id", None)
        self.input_broker = config.get_string("oryx.input-topic.broker")
        self.input_topic = config.get_string("oryx.input-topic.message.topic")
        self.update_broker = config.get_string("oryx.update-topic.broker")
        self.update_topic = config.get_string("oryx.update-topic.message.topic")
        self.generation_interval_sec = config.get_float(
            f"oryx.{tier}.streaming.generation-interval-sec"
        )
        self._group = f"OryxGroup-{tier}-{self.id}" if self.id else None
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        self._failure: BaseException | None = None
        self._context: ComputeContext | None = None

    # -- context ------------------------------------------------------------
    def get_context(self) -> ComputeContext:
        if self._context is None:
            self._context = ComputeContext(self.config, self.tier)
        return self._context

    # -- topics -------------------------------------------------------------
    def assert_topics(self) -> None:
        """Topics must exist before starting (AbstractSparkLayer.java:178-185);
        memory: brokers auto-create since there is no external setup CLI yet."""
        for broker_url, name in (
            (self.input_broker, self.input_topic),
            (self.update_broker, self.update_topic),
        ):
            broker = tp.get_broker(broker_url)
            if not broker.topic_exists(name):
                if broker_url.startswith("memory:"):
                    broker.create_topic(name)
                else:
                    raise tp.TopicException(
                        f"topic {name} does not exist on {broker_url}; run topic-setup"
                    )

    def input_start_offset(self) -> dict[int, int]:
        """Per-partition resume positions: stored offsets for this oryx.id,
        else latest (AbstractSparkLayer.java:208-211)."""
        broker = tp.get_broker(self.input_broker)
        offsets: dict[int, int] = {}
        for p in range(broker.num_partitions(self.input_topic)):
            stored = broker.get_offset(self._group, self.input_topic, p) if self._group else None
            offsets[p] = stored if stored is not None else broker.size(self.input_topic, p)
        return offsets

    def store_input_offset(self, offsets: dict[int, int]) -> None:
        """Write back consumed per-partition offsets (UpdateOffsetsFn.java)."""
        if self._group:
            broker = tp.get_broker(self.input_broker)
            for p, off in offsets.items():
                broker.set_offset(self._group, self.input_topic, off, p)

    # -- microbatch pump ----------------------------------------------------
    def run_microbatches(
        self,
        on_batch: Callable[[int, Sequence[KeyMessage]], None],
        interval_sec: float | None = None,
        start_offset: "dict[int, int] | None" = None,
    ) -> None:
        """Every generation interval, hand the new input slice (across all
        input partitions) to on_batch — the foreachRDD loop. Runs until stop;
        an on_batch exception is fatal to the layer (reference fatal-on-error
        semantics).

        ``start_offset`` should be resolved synchronously in start() so input
        produced after start() returns is never skipped by a slow-to-schedule
        pump thread."""
        interval = interval_sec if interval_sec is not None else self.generation_interval_sec
        broker = tp.get_broker(self.input_broker)
        offsets = dict(start_offset) if start_offset is not None else self.input_start_offset()
        while not self._stop.is_set():
            self._stop.wait(interval)
            if self._stop.is_set():
                break
            batch: list[KeyMessage] = []
            for p in range(broker.num_partitions(self.input_topic)):
                offset = offsets.get(p, 0)
                end = broker.size(self.input_topic, p)
                while offset < end:
                    chunk = broker.read(self.input_topic, offset, end - offset, partition=p)
                    if not chunk:
                        break
                    batch.extend(km for km in chunk if km is not tp.CORRUPT_RECORD)
                    offset += len(chunk)
                offsets[p] = offset
            timestamp_ms = int(time.time() * 1000)
            # trace continuation across the input-topic hop: each traced
            # message gets a span parented into ITS ingress trace (so the
            # HTTP trace that produced the event sees this tier process it),
            # and the generation itself is a root span fan-in-LINKED to
            # every traced message — the exact dual of the coalescer
            traced = []
            if spans.enabled():
                traced = [
                    km.headers[spans.TRACEPARENT] for km in batch
                    if km.headers and spans.TRACEPARENT in km.headers
                ]
            n_traced = len(traced)
            traced = traced[:MAX_TRACED_INPUTS_PER_GENERATION]
            msg_spans = [
                spans.start_span(
                    f"{self.tier}.consume_input", parent=tp_,
                    attributes={"route": f"{self.tier}-input",
                                "batch_items": len(batch)},
                )
                for tp_ in traced
            ]
            try:
                with spans.span(
                    f"{self.tier}.generation", parent=None,
                    links=[s.context for s in msg_spans],
                    attributes={"route": f"{self.tier}.generation",
                                "items": len(batch), "traced_inputs": n_traced},
                ):
                    with self.tracer.step("generation", n_items=len(batch)):
                        on_batch(timestamp_ms, batch)
            finally:
                for s in msg_spans:
                    spans.finish_span(s)
            self.store_input_offset(offsets)

    # -- threads / lifecycle ------------------------------------------------
    def spawn(self, name: str, fn: Callable[[], None]) -> threading.Thread:
        def run():
            try:
                fn()
            except Exception as e:  # noqa: BLE001
                if not self._stop.is_set():
                    log.exception("fatal error in %s; closing layer", name)
                    self._failure = e
                    self._stop.set()

        t = threading.Thread(target=run, name=name, daemon=True)
        self._threads.append(t)
        t.start()
        return t

    def load_manager_instance(self, class_key: str, expected_type=None):
        """Reflectively load the configured user class, (config) ctor first
        (BatchLayer.loadUpdateInstance:172-204 / SpeedLayer:160-192)."""
        name = self.config.get_string(class_key)
        if not name:
            raise ValueError(f"no class configured at {class_key}")
        return classutils.load_instance_of(name, expected_type, self.config)

    def await_termination(self, timeout: float | None = None) -> None:
        self._stop.wait(timeout)
        for t in self._threads:
            t.join(timeout=5)
        if self._failure is not None:
            raise self._failure

    def close(self) -> None:
        self._stop.set()
        self.tracer.close()
        for t in self._threads:
            t.join(timeout=5)

    @property
    def stopped(self) -> bool:
        return self._stop.is_set()
