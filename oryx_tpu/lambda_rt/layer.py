"""Shared layer base: config parsing, topic wiring, generation clock.

Equivalent of the reference's AbstractSparkLayer
(framework/oryx-lambda/.../AbstractSparkLayer.java:57-224): where that builds a
JavaStreamingContext + Kafka direct DStream, this builds a ComputeContext
(jax mesh) + a microbatch pump over the input topic that resumes from stored
offsets keyed by ``oryx.id`` (buildInputDStream:208-211).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Sequence

from oryx_tpu.api.keymessage import KeyMessage
from oryx_tpu.common import blackbox
from oryx_tpu.common import classutils
from oryx_tpu.common import compilecache
from oryx_tpu.common import faults
from oryx_tpu.common import metrics as metrics_mod
from oryx_tpu.common import profiling
from oryx_tpu.common import resilience
from oryx_tpu.common import slo
from oryx_tpu.common import spans
from oryx_tpu.common import tsdb
from oryx_tpu.common.tracing import StepTracer
from oryx_tpu.parallel.mesh import ComputeContext
from oryx_tpu.transport import netbroker
from oryx_tpu.transport import topic as tp

log = spans.get_logger(__name__)

#: Per-generation cap on input-message continuation spans/links: a huge
#: replayed batch must not turn one generation into 10^6 span records (the
#: dropped remainder is still counted in the generation span's attributes).
MAX_TRACED_INPUTS_PER_GENERATION = 128

_QUARANTINED = metrics_mod.default_registry().counter(
    "oryx_quarantined_generations_total",
    "Microbatch generations abandoned after exhausting retries (offsets "
    "advanced past the poison input; the layer kept running)",
    ("tier",),
)
_CORRUPT = metrics_mod.default_registry().counter(
    "oryx_corrupt_records_total",
    "Corrupt input-topic records dropped by the microbatch pump",
    ("tier",),
)
_LAYER_FAILURES = metrics_mod.default_registry().counter(
    "oryx_layer_failures_total",
    "Fatal layer-thread failures (the layer closed because of one)",
    ("tier",),
)


class AbstractLayer:
    def __init__(self, config, tier: str):
        self.config = config
        self.tier = tier
        metrics_mod.configure(config)  # batch/speed never build an HTTP app
        spans.configure(config)
        # batch/speed tiers recompile their training programs on every
        # process restart; the shared persistent compilation cache (and the
        # compile counter) applies to them exactly as to serving replicas
        compilecache.configure(config)
        resilience.configure(config)
        faults.configure(config)
        # flight recorder + SLO engine: batch/speed tiers record the same
        # operational events (quarantines, retry exhaustion, checkpoint
        # failures) and evaluate the same oryx.slo.* objectives as serving
        # replicas — no tier is observability-dark
        blackbox.configure(config)
        slo.configure(config)
        # time-series sampler (oryx.tsdb.*): batch/speed tiers record the
        # same curated signal history — their blackbox dumps carry the
        # pre-incident window exactly like a serving replica's
        tsdb.configure(config)
        netbroker.configure(config)  # tcp:// client timeouts/frame caps
        tp.configure(config)  # file-broker fsync durability policy
        # trainer cost accounting + memory gauges report through the same
        # /metrics surface as serving replicas (scraped or snapshotted by
        # bench_batch) — peaks and gauges configure here too
        profiling.configure(config)
        # factor-arena sizing: the speed tier's model stores are arena
        # users exactly like serving's, and must honor the same
        # oryx.serving.arena.* knobs (the module is pure numpy — no jax
        # import rides in with it)
        from oryx_tpu.models.als import vectors as als_vectors

        als_vectors.configure(config)
        # sanitizer thresholds (oryx.sanitize.*; a threshold tune when
        # ORYX_SANITIZE installed the sanitizer at import, a no-op else)
        from oryx_tpu.tools import sanitize

        sanitize.configure(config)
        self.tracer = StepTracer(config, tier)
        self.id = config.get_string("oryx.id", None)
        self.input_broker = config.get_string("oryx.input-topic.broker")
        self.input_topic = config.get_string("oryx.input-topic.message.topic")
        self.update_broker = config.get_string("oryx.update-topic.broker")
        self.update_topic = config.get_string("oryx.update-topic.message.topic")
        self.generation_interval_sec = config.get_float(
            f"oryx.{tier}.streaming.generation-interval-sec"
        )
        # reference parity knob: the original Spark semantics made any
        # on_batch exception fatal to the layer; default off — transient
        # generations retry, poison generations quarantine
        self.fatal_on_error = config.get_bool(
            f"oryx.{tier}.streaming.fatal-on-error", False
        )
        gen_policy = resilience.RetryPolicy.from_config(
            config, retryable=lambda e: True
        )
        gen_policy.max_attempts = 1 + max(
            0, config.get_int("oryx.resilience.generation.max-retries", 2)
        )
        # generation retries are bounded by ATTEMPTS only: inheriting the
        # transport policy's max-elapsed wall budget (sized for broker ops)
        # would classify the FIRST failure of any generation that ran past
        # it — batch generations legitimately run for minutes — as
        # exhausted, silently disabling max-retries where it matters most
        gen_policy.max_elapsed_sec = float("inf")
        self._generation_policy = gen_policy
        self._group = f"OryxGroup-{tier}-{self.id}" if self.id else None
        # per-partition input positions AFTER reading the current
        # generation's slice — the data-identity half of a trainer
        # checkpoint's fingerprint. Stable across a crash-restart: offsets
        # are only committed after a generation completes, so a re-run
        # generation reads the same slice and lands on the same values.
        self.current_input_offsets: "dict[int, int] | None" = None
        # freshness watermark: the wall time the current generation's input
        # poll STARTED — every event appended before it is in the slice
        # (each partition reads to its size() at poll time), so "data
        # through T is incorporated" holds exactly. Cumulative like the
        # offsets: it covers everything consumed so far, not one slice.
        self.current_input_watermark_ms: "int | None" = None
        # upper bound on the newest consumed event's arrival wall time
        # (poll-start of the last non-empty slice)
        self.current_input_max_event_ms: "int | None" = None
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        self._failure: BaseException | None = None
        self._failure_raised = False
        self._context: ComputeContext | None = None

    # -- context ------------------------------------------------------------
    def get_context(self) -> ComputeContext:
        if self._context is None:
            self._context = ComputeContext(self.config, self.tier)
        return self._context

    # -- topics -------------------------------------------------------------
    def assert_topics(self) -> None:
        """Topics must exist before starting (AbstractSparkLayer.java:178-185);
        memory: brokers auto-create since there is no external setup CLI yet."""
        for broker_url, name in (
            (self.input_broker, self.input_topic),
            (self.update_broker, self.update_topic),
        ):
            broker = tp.get_broker(broker_url)
            if not broker.topic_exists(name):
                if broker_url.startswith("memory:"):
                    broker.create_topic(name)
                else:
                    raise tp.TopicException(
                        f"topic {name} does not exist on {broker_url}; run topic-setup"
                    )

    def input_start_offset(self) -> dict[int, int]:
        """Per-partition resume positions: stored offsets for this oryx.id,
        else latest (AbstractSparkLayer.java:208-211)."""
        broker = tp.get_broker(self.input_broker)
        offsets: dict[int, int] = {}
        for p in range(broker.num_partitions(self.input_topic)):
            stored = (
                self._offset_op(
                    lambda p=p: broker.get_offset(self._group, self.input_topic, p)
                )
                if self._group else None
            )
            offsets[p] = stored if stored is not None else broker.size(self.input_topic, p)
        return offsets

    def store_input_offset(self, offsets: dict[int, int]) -> None:
        """Write back consumed per-partition offsets (UpdateOffsetsFn.java)."""
        if self._group:
            broker = tp.get_broker(self.input_broker)
            for p, off in offsets.items():
                self._offset_op(
                    lambda p=p, off=off: broker.set_offset(
                        self._group, self.input_topic, off, p
                    )
                )

    def _offset_op(self, fn):
        """One offset-store read/write under the shared transport retry
        contract (tp.offset_op — the same wrapper the serving layer's
        committed-resume commits ride)."""
        return tp.offset_op(fn, stop=self._stop)

    # -- microbatch pump ----------------------------------------------------
    def run_microbatches(
        self,
        on_batch: Callable[[int, Sequence[KeyMessage]], None],
        interval_sec: float | None = None,
        start_offset: "dict[int, int] | None" = None,
    ) -> None:
        """Every generation interval, hand the new input slice (across all
        input partitions) to on_batch — the foreachRDD loop. Runs until stop.

        Failure semantics (docs/robustness.md): an on_batch exception is
        retried with backoff up to ``oryx.resilience.generation.max-retries``
        times (transient faults — a flaky broker, a briefly-wedged device —
        recover in place), then the generation is QUARANTINED: offsets
        advance past it, ``oryx_quarantined_generations_total`` counts it,
        the generation span records the error, and the layer lives on. With
        ``oryx.<tier>.streaming.fatal-on-error`` the first exception kills
        the layer (reference parity). Input-poll failures past the transport
        retry budget skip the tick without advancing offsets.

        ``start_offset`` should be resolved synchronously in start() so input
        produced after start() returns is never skipped by a slow-to-schedule
        pump thread."""
        interval = interval_sec if interval_sec is not None else self.generation_interval_sec
        broker = tp.get_broker(self.input_broker)
        offsets = dict(start_offset) if start_offset is not None else self.input_start_offset()
        while not self._stop.is_set():
            self._stop.wait(interval)
            if self._stop.is_set():
                break
            batch: list[KeyMessage] = []
            n_corrupt = 0
            first_corrupt: "tuple[int, int] | None" = None
            # stage offset advances in a COPY: a poll failure on a LATER
            # partition must discard the half-built batch and the earlier
            # partitions' advances TOGETHER — advancing the shared dict
            # in place would silently skip the already-read messages on
            # the re-poll (batch dropped, offsets kept)
            new_offsets = dict(offsets)
            poll_start_ms = int(time.time() * 1000)
            try:
                for p in range(broker.num_partitions(self.input_topic)):
                    offset = new_offsets.get(p, 0)
                    end = broker.size(self.input_topic, p)
                    while offset < end:
                        chunk = self._poll_input(broker, p, offset, end - offset)
                        if not chunk:
                            break
                        for i, km in enumerate(chunk):
                            if km is tp.CORRUPT_RECORD:
                                n_corrupt += 1
                                if first_corrupt is None:
                                    first_corrupt = (p, offset + i)
                            else:
                                batch.append(km)
                        offset += len(chunk)
                    new_offsets[p] = offset
            except Exception:  # noqa: BLE001 — poll failure past retry budget
                # transient input-poll failure that outlasted the transport
                # retries: skip this tick WITHOUT advancing offsets — the
                # next tick re-polls the same positions. Killing the layer
                # over a pollable fault is the fragility this path removes.
                log.warning(
                    "input poll failed past the retry budget; re-polling next "
                    "generation", exc_info=True,
                )
                continue
            offsets = new_offsets
            self.current_input_offsets = dict(offsets)
            self.current_input_watermark_ms = poll_start_ms
            if batch:
                # newest-event upper bound: the newest consumed event landed
                # between the previous poll and this one
                self.current_input_max_event_ms = poll_start_ms
            if n_corrupt:
                # one rate-limited (per-generation) line, not one per record:
                # a corrupted log segment would otherwise flood the logger
                _CORRUPT.labels(self.tier).inc(n_corrupt)
                log.warning(
                    "dropped %d corrupt record(s) this generation "
                    "(first at partition %d offset %d)",
                    n_corrupt, first_corrupt[0], first_corrupt[1],
                )
            timestamp_ms = int(time.time() * 1000)
            # trace continuation across the input-topic hop: each traced
            # message gets a span parented into ITS ingress trace (so the
            # HTTP trace that produced the event sees this tier process it),
            # and the generation itself is a root span fan-in-LINKED to
            # every traced message — the exact dual of the coalescer
            traced = []
            if spans.enabled():
                traced = [
                    km.headers[spans.TRACEPARENT] for km in batch
                    if km.headers and spans.TRACEPARENT in km.headers
                ]
            n_traced = len(traced)
            traced = traced[:MAX_TRACED_INPUTS_PER_GENERATION]
            msg_spans = [
                spans.start_span(
                    f"{self.tier}.consume_input", parent=tp_,
                    attributes={"route": f"{self.tier}-input",
                                "batch_items": len(batch)},
                )
                for tp_ in traced
            ]
            try:
                with spans.span(
                    f"{self.tier}.generation", parent=None,
                    links=[s.context for s in msg_spans],
                    attributes={"route": f"{self.tier}.generation",
                                "items": len(batch), "traced_inputs": n_traced},
                ) as gen_span:
                    with self.tracer.step("generation", n_items=len(batch)):
                        self._run_generation(
                            on_batch, timestamp_ms, batch, gen_span
                        )
            finally:
                for s in msg_spans:
                    spans.finish_span(s)
            self.store_input_offset(offsets)

    def _run_generation(self, on_batch, timestamp_ms: int,
                        batch: "list[KeyMessage]", gen_span) -> None:
        """One generation through the transient-vs-poison machinery; raises
        only on fatal-on-error (or during shutdown) — a quarantined
        generation returns normally so the caller advances offsets."""
        site = f"{self.tier}.generation"

        def attempt():
            # chaos hook: an armed "<tier>.generation" schedule fails the
            # generation through the exact path a poison input or a wedged
            # device would take — the quarantine machinery absorbs it
            faults.maybe_fail(site)
            on_batch(timestamp_ms, batch)

        if self.fatal_on_error:
            # reference parity: no retry, first raise kills the layer
            attempt()
            return
        try:
            self._generation_policy.call(site, attempt, stop=self._stop)
        except Exception as e:  # noqa: BLE001 — quarantine after retries
            if self._stop.is_set():
                raise  # shutting down: spawn's guard discards it
            _QUARANTINED.labels(self.tier).inc()
            # flight-recorder edge + dump trigger: an abandoned generation
            # is exactly what the postmortem of a bad model asks about
            blackbox.record_event(
                "quarantine", severity="error", dump=True,
                tier=self.tier, items=len(batch),
                error=f"{type(e).__name__}: {e}",
            )
            gen_span.record_exception(e)
            gen_span.set_attribute("quarantined", True)
            gen_span.set_attribute("items", len(batch))
            log.error(
                "quarantining generation after retries: advancing past %d "
                "input item(s)", len(batch), exc_info=True,
            )

    def _poll_input(self, broker, partition: int, offset: int, n: int):
        """One input-slice read, retried through transient broker failures."""

        def _read():
            faults.maybe_fail("broker.read")
            return broker.read(self.input_topic, offset, n, partition=partition)

        return resilience.default_policy().call(
            "broker.read", _read, retryable=tp.transient_transport_error,
            stop=self._stop,
        )

    # -- threads / lifecycle ------------------------------------------------
    def spawn(self, name: str, fn: Callable[[], None]) -> threading.Thread:
        def run():
            try:
                fn()
            except Exception as e:  # noqa: BLE001
                if not self._stop.is_set():
                    log.exception("fatal error in %s; closing layer", name)
                    _LAYER_FAILURES.labels(self.tier).inc()
                    self._failure = e
                    self._stop.set()

        t = threading.Thread(target=run, name=name, daemon=True)
        self._threads.append(t)
        t.start()
        return t

    def load_manager_instance(self, class_key: str, expected_type=None):
        """Reflectively load the configured user class, (config) ctor first
        (BatchLayer.loadUpdateInstance:172-204 / SpeedLayer:160-192)."""
        name = self.config.get_string(class_key)
        if not name:
            raise ValueError(f"no class configured at {class_key}")
        return classutils.load_instance_of(name, expected_type, self.config)

    def await_termination(self, timeout: float | None = None) -> None:
        """Block until stop; a layer failure is raised exactly ONCE — callers
        polling await_termination in a supervision loop see it the first
        time and a clean return after (it is also already surfaced through
        oryx_layer_failures_total and the spawn-side log line)."""
        self._stop.wait(timeout)
        for t in self._threads:
            t.join(timeout=5)
        if self._failure is not None and not self._failure_raised:
            self._failure_raised = True
            raise self._failure

    def close(self) -> None:
        self._stop.set()
        self.tracer.close()
        for t in self._threads:
            t.join(timeout=5)

    @property
    def stopped(self) -> bool:
        return self._stop.is_set()
