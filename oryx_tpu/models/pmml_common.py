"""App-level PMML glue shared by k-means and RDF models.

Equivalent of the reference's AppPMMLUtils schema builders
(app/oryx-app-common/.../pmml/AppPMMLUtils.java:131-259): MiningSchema with
active/supplementary/predicted usage and optional importances, DataDictionary
with per-categorical-feature Value lists ordered by encoding, PMML REAL Array
encoding, and the reverse readers used to validate a received model against
the configured InputSchema.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np
import xml.etree.ElementTree as ET

from oryx_tpu.models.schema import CategoricalValueEncodings, InputSchema
from oryx_tpu.pmml import pmmlutils


def format_number(v: float) -> str:
    """Render like Java's Double.toString for round values (1.0 not 1)."""
    f = float(v)
    if f == int(f) and abs(f) < 1e16:
        return f"{int(f)}.0"
    return repr(f)


def to_pmml_array(parent: ET.Element, values: Sequence[float]) -> ET.Element:
    """<Array type="REAL" n="..."> space-joined numbers (AppPMMLUtils.toArray)."""
    arr = pmmlutils.subelement(parent, "Array", {"type": "REAL", "n": len(values)})
    arr.text = pmmlutils.join_pmml_delimited([format_number(v) for v in values])
    return arr


def parse_array(el: ET.Element) -> np.ndarray:
    return np.asarray(
        [float(t) for t in pmmlutils.parse_pmml_delimited(el.text or "")],
        dtype=np.float64,
    )


def build_mining_schema(
    parent: ET.Element,
    schema: InputSchema,
    importances: "np.ndarray | None" = None,
) -> ET.Element:
    """(AppPMMLUtils.buildMiningSchema:131-176)"""
    if importances is not None and len(importances) != schema.num_predictors:
        raise ValueError("importances size must match number of predictors")
    ms = pmmlutils.subelement(parent, "MiningSchema")
    for i, name in enumerate(schema.feature_names):
        attrib: dict = {"name": name}
        if schema.is_target(name):
            attrib["usageType"] = "predicted"
            attrib["optype"] = (
                "continuous" if schema.is_numeric(name) else "categorical"
            )
        elif schema.is_numeric(name):
            attrib["usageType"] = "active"
            attrib["optype"] = "continuous"
        elif schema.is_categorical(name):
            attrib["usageType"] = "active"
            attrib["optype"] = "categorical"
        else:
            attrib["usageType"] = "supplementary"
        if attrib.get("usageType") == "active" and importances is not None:
            attrib["importance"] = format_number(
                importances[schema.feature_to_predictor_index(i)]
            )
        pmmlutils.subelement(ms, "MiningField", attrib)
    return ms


def build_data_dictionary(
    parent: ET.Element,
    schema: InputSchema,
    encodings: "CategoricalValueEncodings | None" = None,
) -> ET.Element:
    """(AppPMMLUtils.buildDataDictionary:198-230)"""
    dd = pmmlutils.subelement(
        parent, "DataDictionary", {"numberOfFields": schema.num_features}
    )
    for i, name in enumerate(schema.feature_names):
        attrib: dict = {"name": name}
        if schema.is_numeric(name):
            attrib.update(optype="continuous", dataType="double")
        elif schema.is_categorical(name):
            attrib.update(optype="categorical", dataType="string")
        field = pmmlutils.subelement(dd, "DataField", attrib)
        if schema.is_categorical(name) and encodings is not None:
            e2v = encodings.get_encoding_value_map(i)
            for enc in sorted(e2v):
                pmmlutils.subelement(field, "Value", {"value": e2v[enc]})
    return dd


def get_feature_names(container: ET.Element, child_tag: str) -> list[str]:
    """Feature names in order from a DataDictionary (DataField) or MiningSchema
    (MiningField) (AppPMMLUtils.getFeatureNames:237-258)."""
    return [
        el.get("name")
        for el in pmmlutils.find_all(container, child_tag)
    ]


def read_data_dictionary_encodings(dd: ET.Element) -> CategoricalValueEncodings:
    """DataDictionary Value lists → encodings (AppPMMLUtils.buildCategoricalValueEncodings)."""
    distinct: dict[int, list[str]] = {}
    for i, field in enumerate(pmmlutils.find_all(dd, "DataField")):
        values = [v.get("value") for v in pmmlutils.find_all(field, "Value")]
        if values:
            distinct[i] = values
    return CategoricalValueEncodings(distinct)


def validate_feature_names(pmml: ET.Element, schema: InputSchema, what: str) -> None:
    """Common part of validatePMMLVsSchema (KMeansPMMLUtils.java:47-65)."""
    dd = pmmlutils.find(pmml, "DataDictionary")
    if dd is None:
        raise ValueError(f"{what}: PMML has no DataDictionary")
    names = get_feature_names(dd, "DataField")
    if names != schema.feature_names:
        raise ValueError(
            f"{what}: feature names in schema don't match names in PMML: "
            f"{schema.feature_names} vs {names}"
        )
    ms = pmmlutils.find(pmml, "MiningSchema")
    if ms is None:
        raise ValueError(f"{what}: PMML has no MiningSchema")
    ms_names = get_feature_names(ms, "MiningField")
    if ms_names != schema.feature_names:
        raise ValueError(f"{what}: MiningSchema names don't match schema")


def features_from_tokens(tokens: Sequence[str], schema: InputSchema) -> np.ndarray:
    """Datum tokens → dense numeric predictor vector (KMeansUtils.featuresFromTokens:62-71).

    Rows with more tokens than the schema has features are rejected, like the
    reference's ArrayIndexOutOfBoundsException → bad-input path."""
    if len(tokens) > schema.num_features:
        raise IndexError(
            f"{len(tokens)} tokens but schema has {schema.num_features} features"
        )
    features = np.zeros(schema.num_predictors, dtype=np.float64)
    for i in range(len(tokens)):
        if schema.is_active(i) and not schema.is_target(i):
            features[schema.feature_to_predictor_index(i)] = float(tokens[i])
    return features
