"""Input schema: which CSV columns are IDs / numeric / categorical / target.

Equivalent of the reference's InputSchema + CategoricalValueEncodings
(app/oryx-app-common/.../schema/InputSchema.java:37-100,
CategoricalValueEncodings.java:33-100): feature names come from
``oryx.input-schema.feature-names`` or are generated ``"0".."n-1"`` from
``num-features``; id/ignored features are subtracted to get active features;
exactly one of numeric-features / categorical-features is given and the other
is the active remainder; the optional target must be active. Both k-means and
RDF parse datum lines through this.
"""

from __future__ import annotations

from typing import Mapping, Sequence


class InputSchema:
    def __init__(self, config):
        feature_names = list(config.get_list("oryx.input-schema.feature-names", []))
        if not feature_names:
            num_features = config.get_int("oryx.input-schema.num-features", 0)
            if num_features <= 0:
                raise ValueError("Neither feature-names nor num-features is set")
            feature_names = [str(i) for i in range(num_features)]
        if len(set(feature_names)) != len(feature_names):
            raise ValueError(f"Feature names must be unique: {feature_names}")
        self.feature_names: list[str] = feature_names

        id_features = set(config.get_list("oryx.input-schema.id-features", []))
        ignored = set(config.get_list("oryx.input-schema.ignored-features", []))
        for col, what in ((id_features, "id"), (ignored, "ignored")):
            unknown = col - set(feature_names)
            if unknown:
                raise ValueError(f"unknown {what} features: {sorted(unknown)}")
        self.id_features = id_features
        active = set(feature_names) - id_features - ignored
        self.active_features = active

        numeric = config.get_list("oryx.input-schema.numeric-features", None)
        categorical = config.get_list("oryx.input-schema.categorical-features", None)
        if numeric is None:
            if categorical is None:
                raise ValueError("Neither numeric-features nor categorical-features was set")
            categorical = set(categorical)
            if not categorical <= active:
                raise ValueError(f"categorical features {sorted(categorical)} not all active")
            numeric = active - categorical
        else:
            numeric = set(numeric)
            if not numeric <= active:
                raise ValueError(f"numeric features {sorted(numeric)} not all active")
            categorical = active - numeric
        self.numeric_features = set(numeric)
        self.categorical_features = set(categorical)

        self.target_feature: "str | None" = config.get(
            "oryx.input-schema.target-feature", None
        )
        if self.target_feature is not None and self.target_feature not in active:
            raise ValueError(
                f"Target feature is not known, an ID, or ignored: {self.target_feature}"
            )
        self.target_feature_index = (
            feature_names.index(self.target_feature) if self.target_feature else -1
        )

        # feature index ↔ predictor index (active non-target features, in order)
        self._all_to_predictor: dict[int, int] = {}
        self._predictor_to_all: dict[int, int] = {}
        predictor = 0
        for i, name in enumerate(feature_names):
            if name in active and i != self.target_feature_index:
                self._all_to_predictor[i] = predictor
                self._predictor_to_all[predictor] = i
                predictor += 1

    # -- accessors (InputSchema.java getters) --------------------------------
    @property
    def num_features(self) -> int:
        return len(self.feature_names)

    @property
    def num_predictors(self) -> int:
        return len(self._all_to_predictor)

    def is_active(self, index: int) -> bool:
        return self.feature_names[index] in self.active_features

    def is_id(self, name_or_index) -> bool:
        return self._name(name_or_index) in self.id_features

    def is_numeric(self, name_or_index) -> bool:
        return self._name(name_or_index) in self.numeric_features

    def is_categorical(self, name_or_index) -> bool:
        return self._name(name_or_index) in self.categorical_features

    def is_target(self, name_or_index) -> bool:
        return (
            self.target_feature is not None
            and self._name(name_or_index) == self.target_feature
        )

    def has_target(self) -> bool:
        return self.target_feature is not None

    def is_classification(self) -> bool:
        """Categorical target = classification (InputSchema.isClassification)."""
        return self.has_target() and self.is_categorical(self.target_feature)

    def feature_to_predictor_index(self, feature_index: int) -> int:
        return self._all_to_predictor[feature_index]

    def predictor_to_feature_index(self, predictor_index: int) -> int:
        return self._predictor_to_all[predictor_index]

    def _name(self, name_or_index) -> str:
        if isinstance(name_or_index, int):
            return self.feature_names[name_or_index]
        return name_or_index


class CategoricalValueEncodings:
    """Two-way value↔int mapping per categorical feature index
    (CategoricalValueEncodings.java:33-100). Order of distinct values matters —
    it defines the encoding."""

    def __init__(self, distinct_values: Mapping[int, Sequence[str]]):
        self._value_to_encoding: dict[int, dict[str, int]] = {}
        self._encoding_to_value: dict[int, dict[int, str]] = {}
        for index, values in distinct_values.items():
            v2e = {v: i for i, v in enumerate(values)}
            if len(v2e) != len(list(values)):
                raise ValueError(f"duplicate values for feature {index}")
            self._value_to_encoding[index] = v2e
            self._encoding_to_value[index] = {i: v for v, i in v2e.items()}

    def get_value_encoding_map(self, index: int) -> dict[str, int]:
        return self._value_to_encoding[index]

    def get_encoding_value_map(self, index: int) -> dict[int, str]:
        return self._encoding_to_value[index]

    def get_value_count(self, index: int) -> int:
        return len(self._value_to_encoding[index])

    def get_category_counts(self) -> dict[int, int]:
        return {k: len(v) for k, v in self._value_to_encoding.items()}

    def __repr__(self) -> str:  # pragma: no cover
        return repr(self._value_to_encoding)
