"""TPU-native k-means training.

Replaces Spark MLlib's ``KMeans.train`` (behind KMeansUpdate.buildModel,
app/oryx-app-mllib/.../kmeans/KMeansUpdate.java:107-122) with jit'd JAX
programs shaped for the MXU:

  * distance evaluation is the ``||x||² − 2·X·Cᵀ + ||c||²`` expansion, so the
    dominant cost of every Lloyd sweep is one (N,d)×(d,k) matmul;
  * centroid recomputation is a one-hot matmul ``Aᵀ·X`` (A = (N,k) assignment
    indicator), not a scatter — again MXU work, and under a sharded data axis
    XLA turns the reduction into a psum over the mesh;
  * iterations run under ``lax.scan`` (static trip count — the reference's
    MLlib convergence check is replaced by a fixed iteration budget from
    ``oryx.kmeans.iterations``);
  * the ``runs`` restarts (``oryx.kmeans.runs``) are a ``vmap`` over seeds —
    candidate-restart parallelism on device rather than sequential reruns —
    and the run with the lowest cost wins;
  * init: ``random`` samples k points; ``k-means||`` maps to a scan-based
    k-means++ (sequential D² sampling — the same seeding MLlib's k-means‖
    approximates, exact here because a TPU sweep over N points is one matmul).

Empty clusters keep their previous center (MLlib behavior) in the
lambda-tier trainer; :func:`fit_index_centroids` (the serving IVF index's
entry point) instead RESEEDS empty clusters to the points currently worst
served, because a dead cell in an inverted-file index is pure wasted probe
width.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

INIT_RANDOM = "random"
INIT_KMEANS_PARALLEL = "k-means||"


def _sq_dists(points, centers):
    """(N, k) squared Euclidean distances; one MXU matmul."""
    sq = (
        (points * points).sum(axis=1, keepdims=True)
        - 2.0 * points @ centers.T
        + (centers * centers).sum(axis=1)[None, :]
    )
    return jnp.maximum(sq, 0.0)


def _init_random(key, points, k: int):
    idx = jax.random.choice(key, points.shape[0], (k,), replace=False)
    return points[idx]


def _init_plus_plus(key, points, k: int):
    """D²-weighted sequential seeding under lax.scan (k-means++)."""
    n = points.shape[0]
    key, first = jax.random.split(key)
    centers = jnp.zeros((k, points.shape[1]), dtype=points.dtype)
    centers = centers.at[0].set(points[jax.random.randint(first, (), 0, n)])
    min_d2 = _sq_dists(points, centers[:1])[:, 0]

    def body(carry, j):
        centers, min_d2, key = carry
        key, sub = jax.random.split(key)
        total = min_d2.sum()
        # degenerate case (all points coincide with centers): uniform draw
        probs = jnp.where(total > 0, min_d2 / jnp.maximum(total, 1e-30), 1.0 / n)
        idx = jax.random.categorical(sub, jnp.log(probs + 1e-30))
        c = points[idx]
        centers = centers.at[j].set(c)
        d2_new = ((points - c[None, :]) ** 2).sum(axis=1)
        return (centers, jnp.minimum(min_d2, d2_new), key), None

    (centers, _, _), _ = jax.lax.scan(body, (centers, min_d2, key), jnp.arange(1, k))
    return centers


@functools.partial(jax.jit, static_argnames=("k", "iterations", "init"))
def _kmeans_single_run(key, points, weights, k: int, iterations: int, init: str):
    if init == INIT_RANDOM:
        centers = _init_random(key, points, k)
    else:
        centers = _init_plus_plus(key, points, k)

    def lloyd(centers, _):
        d2 = _sq_dists(points, centers)
        a = jax.nn.one_hot(d2.argmin(axis=1), k, dtype=points.dtype)
        a = a * weights[:, None]  # padding rows carry zero weight
        counts = a.sum(axis=0)  # (k,)
        sums = a.T @ points  # (k, d) — MXU; psum'd by XLA when sharded
        new_centers = sums / jnp.maximum(counts, 1.0)[:, None]
        centers = jnp.where((counts > 0)[:, None], new_centers, centers)
        return centers, None

    centers, _ = jax.lax.scan(lloyd, centers, None, length=iterations)
    d2 = _sq_dists(points, centers)
    assign = d2.argmin(axis=1)
    min_d2 = jnp.take_along_axis(d2, assign[:, None], axis=1)[:, 0] * weights
    cost = min_d2.sum()
    counts = (jax.nn.one_hot(assign, k, dtype=points.dtype) * weights[:, None]).sum(0)
    return centers, counts, cost


@functools.partial(jax.jit, static_argnames=("k", "init"))
def _init_centers(key, points, k: int, init: str):
    if init == INIT_RANDOM:
        return _init_random(key, points, k)
    return _init_plus_plus(key, points, k)


def _kmeans_pallas_run(key, points, weights, k, iterations, init, interpret):
    """One restart with the fused Pallas Lloyd kernel (ops/pallas_kernels):
    distances, argmin, and sum/count/cost accumulation in one pass per sweep —
    the (N, k) intermediates never touch HBM. Points/weights are padded once
    for the whole run; only the (small) centers re-pad per sweep."""
    from oryx_tpu.ops import pallas_kernels as pk

    centers = _init_centers(key, points, k, init)
    n, d = points.shape
    n_pad = pk._pad_dim(max(n, 1), pk.TILE_N)
    d_pad = pk._pad_dim(d, pk._LANE)
    k_pad = pk._pad_dim(k, 8)
    pts = jnp.zeros((n_pad, d_pad), jnp.float32).at[:n, :d].set(points)
    wts = jnp.zeros((n_pad, 1), jnp.float32).at[:n, 0].set(weights)

    def pad_centers(c):
        ctr = jnp.zeros((k_pad, d_pad), jnp.float32).at[:k, :d].set(c)
        if k_pad > k:
            ctr = ctr.at[k:, 0].set(pk.FAR_AWAY)
        return ctr

    counts = cost = None
    for i in range(iterations + 1):
        sums, counts_p, cost_p = pk._call(
            pts, wts, pad_centers(centers), interpret=interpret
        )
        counts, cost = counts_p[0, :k], cost_p[0, 0]
        if i < iterations:  # final sweep only reads counts/cost
            new_centers = sums[:k, :d] / jnp.maximum(counts, 1.0)[:, None]
            centers = jnp.where((counts > 0)[:, None], new_centers, centers)
    return centers, counts, cost


@functools.partial(jax.jit, static_argnames=("k", "iterations"))
def _lloyd_from(points, centers, k: int, iterations: int):
    """``iterations`` Lloyd sweeps from GIVEN centers; returns the final
    (centers, counts, assign). Factored out of ``_kmeans_single_run`` so the
    empty-cluster reseeding loop can resume sweeps from patched centers."""
    weights = jnp.ones((points.shape[0],), dtype=points.dtype)

    def lloyd(centers, _):
        d2 = _sq_dists(points, centers)
        a = jax.nn.one_hot(d2.argmin(axis=1), k, dtype=points.dtype)
        counts = a.sum(axis=0)
        sums = a.T @ points
        new_centers = sums / jnp.maximum(counts, 1.0)[:, None]
        centers = jnp.where((counts > 0)[:, None], new_centers, centers)
        return centers, None

    centers, _ = jax.lax.scan(lloyd, centers, None, length=iterations)
    d2 = _sq_dists(points, centers)
    assign = d2.argmin(axis=1)
    counts = (jax.nn.one_hot(assign, k, dtype=points.dtype) * weights[:, None]).sum(0)
    return centers, counts, assign


def _reseed_empty(points: np.ndarray, centers: np.ndarray,
                  counts: np.ndarray, assign: np.ndarray) -> np.ndarray:
    """Move each empty cluster's center onto the point FARTHEST from its
    assigned center (distinct points, worst-served first) — the standard
    empty-cluster repair. Returns patched centers; no-op when none empty."""
    empty = np.flatnonzero(counts == 0)
    if empty.size == 0:
        return centers
    d2 = ((points - centers[assign]) ** 2).sum(axis=1)
    order = np.argsort(-d2, kind="stable")
    centers = centers.copy()
    for j, c in enumerate(empty[: len(order)]):
        centers[c] = points[order[j]]
    return centers


def fit_index_centroids(
    points: np.ndarray,
    k: int,
    iterations: int = 20,
    seed: int = 0,
    reseed_rounds: int = 4,
) -> "tuple[np.ndarray, np.ndarray, np.ndarray]":
    """Deterministic bounded k-means fit for the serving IVF index
    (models/als/ivf.py): k-means++ init from a FIXED seed, at most
    ``iterations`` Lloyd sweeps, then up to ``reseed_rounds`` empty-cluster
    repairs (reseed to worst-served points + 2 more sweeps each) so a
    planted-structure fit cannot emit dead cells while distinct points
    remain. Returns (centers (k,d) f32, counts (k,) i64, assign (n,) i32) —
    the assignment rides along so the index build skips a second pass.

    Unlike :func:`kmeans_train` this takes no PRNG plumbing and runs no
    restarts: the index rebuild path needs reproducibility (the incremental
    -maintenance-equals-rebuild invariant is tested bit-exactly) more than
    it needs the last percent of quantization error."""
    points = np.ascontiguousarray(np.asarray(points, dtype=np.float32))
    n = len(points)
    if n == 0:
        raise ValueError("no points")
    k = max(1, min(int(k), n))
    pts = jnp.asarray(points)
    key = jax.random.PRNGKey(int(seed))
    centers = _init_centers(key, pts, k, INIT_KMEANS_PARALLEL)
    centers, counts, assign = _lloyd_from(pts, centers, k, int(iterations))
    centers_np, counts_np, assign_np = jax.device_get((centers, counts, assign))
    for _ in range(max(0, int(reseed_rounds))):
        if (counts_np > 0).all():
            break
        patched = _reseed_empty(points, np.asarray(centers_np, dtype=np.float32),
                                counts_np, assign_np)
        centers, counts, assign = _lloyd_from(pts, jnp.asarray(patched), k, 2)
        centers_np, counts_np, assign_np = jax.device_get(
            (centers, counts, assign)
        )
    return (
        np.asarray(centers_np, dtype=np.float32),
        np.asarray(counts_np, dtype=np.int64),
        np.asarray(assign_np, dtype=np.int32),
    )


def kmeans_train(
    points: np.ndarray,
    k: int,
    iterations: int = 30,
    runs: int = 1,
    init: str = INIT_KMEANS_PARALLEL,
    key=None,
    use_pallas: "bool | None" = None,
    interpret: bool = False,
):
    """Train on (N, d) points; returns (centers (k,d) np, counts (k,) np).

    ``runs`` restarts execute as one vmapped program; best-cost run wins
    (MLlib KMeans ``runs`` semantics). On TPU (or with ``use_pallas=True``)
    each Lloyd sweep instead runs the fused Pallas kernel, restarts
    sequentially.
    """
    from oryx_tpu.common import rand

    points = np.asarray(points, dtype=np.float32)
    n = len(points)
    if n == 0:
        raise ValueError("no points")
    k = min(k, n)
    if key is None:
        key = rand.get_key()
    pts = jnp.asarray(points)
    weights = jnp.ones((n,), dtype=jnp.float32)
    keys = jax.random.split(key, max(runs, 1))
    if use_pallas is None:
        use_pallas = jax.default_backend() == "tpu"
    if use_pallas:
        results = [
            _kmeans_pallas_run(kk, pts, weights, k, iterations, init, interpret)
            for kk in keys
        ]
        centers = jnp.stack([r[0] for r in results])
        counts = jnp.stack([r[1] for r in results])
        costs = jnp.stack([r[2] for r in results])
    else:
        centers, counts, costs = jax.vmap(
            lambda kk: _kmeans_single_run(kk, pts, weights, k, iterations, init)
        )(keys)
    # pick the winner on device and fetch both result arrays in ONE
    # explicit transfer (argmin + two np.asarray calls were three syncs)
    best = jnp.argmin(costs)
    centers_np, counts_np = jax.device_get((centers[best], counts[best]))
    return (
        centers_np.astype(np.float64),
        counts_np.astype(np.int64),
    )
