"""k-means internal evaluation metrics, vectorized.

Equivalent of the reference's four KMeansEvalStrategy implementations
(app/oryx-app-mllib/.../kmeans/SilhouetteCoefficient.java:30-120,
DaviesBouldinIndex.java:27-66, DunnIndex.java:27-60, SumSquaredError.java:25-36,
AbstractKMeansEvaluation.java:35-75). Per-point cluster metrics (count, mean
and squared distance to the assigned centroid) come from one batched
assignment; the silhouette's pairwise dissimilarities are a single (S,S)
distance matrix on a capped sample (the reference samples to ≤100k points and
loops; here the cap keeps the O(S²) matrix device-friendly).

Directions follow the reference (KMeansUpdate.evaluate:150-177): silhouette
and Dunn are higher-better; Davies-Bouldin and SSE are lower-better and are
negated by the caller.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np

from oryx_tpu.models.kmeans.model import ClusterInfo, assign, distances_to_centers

SILHOUETTE_MAX_SAMPLE = 8192  # reference MAX_SAMPLE_SIZE=100000 with host loops


def _centers(clusters: Sequence[ClusterInfo]) -> np.ndarray:
    return np.stack([c.center for c in clusters])


def _cluster_metrics(points: np.ndarray, centers: np.ndarray):
    """Per-cluster (count, mean dist, sum sq dist) — fetchClusterMetrics."""
    idx, dist = assign(points, centers)
    k = len(centers)
    counts = np.bincount(idx, minlength=k).astype(np.float64)
    sum_dist = np.bincount(idx, weights=dist, minlength=k)
    sum_sq = np.bincount(idx, weights=dist * dist, minlength=k)
    with np.errstate(invalid="ignore"):
        mean_dist = np.where(counts > 0, sum_dist / np.maximum(counts, 1), 0.0)
    return idx, counts, mean_dist, sum_sq


def sum_squared_error(clusters: Sequence[ClusterInfo], points: np.ndarray) -> float:
    """Total squared distance to assigned centroids; lower is better."""
    _, _, _, sum_sq = _cluster_metrics(points, _centers(clusters))
    return float(sum_sq.sum())


def davies_bouldin_index(clusters: Sequence[ClusterInfo], points: np.ndarray) -> float:
    """Mean over clusters of max_{j≠i} (scatter_i+scatter_j)/d(c_i,c_j);
    lower is better."""
    centers = _centers(clusters)
    _, _, mean_dist, _ = _cluster_metrics(points, centers)
    k = len(centers)
    if k < 2:
        return 0.0
    center_d = distances_to_centers(centers, centers)
    scatter_sum = mean_dist[:, None] + mean_dist[None, :]  # (k, k)
    with np.errstate(divide="ignore", invalid="ignore"):
        ratio = scatter_sum / center_d
    np.fill_diagonal(ratio, 0.0)
    # coincident centers ⇒ infinite ratio must PROPAGATE so the degenerate
    # model ranks worst (reference DaviesBouldinIndex.java keeps Infinity);
    # only a 0/0 (both scatters zero too) is treated as no-contribution
    ratio = np.nan_to_num(ratio, nan=0.0)
    return float(ratio.max(axis=1).mean())


def dunn_index(clusters: Sequence[ClusterInfo], points: np.ndarray) -> float:
    """min inter-center distance / max mean intra-cluster distance;
    higher is better."""
    centers = _centers(clusters)
    _, _, mean_dist, _ = _cluster_metrics(points, centers)
    max_intra = mean_dist.max()
    if len(centers) < 2 or max_intra == 0:
        return 0.0
    center_d = distances_to_centers(centers, centers)
    iu = np.triu_indices(len(centers), k=1)
    return float(center_d[iu].min() / max_intra)


def silhouette_coefficient(
    clusters: Sequence[ClusterInfo],
    points: np.ndarray,
    max_sample: int = SILHOUETTE_MAX_SAMPLE,
    rng: "np.random.Generator | None" = None,
) -> float:
    """Mean silhouette over sampled points, in [-1, 1]; higher is better.
    Singleton clusters contribute 0 per point (SilhouetteCoefficient.java:63-66)."""
    points = np.asarray(points, dtype=np.float64)
    if len(points) > max_sample:
        if rng is None:
            from oryx_tpu.common import rand

            rng = rand.get_random()
        points = points[rng.choice(len(points), max_sample, replace=False)]
    centers = _centers(clusters)
    idx, _ = assign(points, centers)
    n, k = len(points), len(centers)
    if n == 0:
        return 0.0
    one_hot = np.zeros((n, k))
    one_hot[np.arange(n), idx] = 1.0
    counts = one_hot.sum(axis=0)  # (k,)
    # (S, k) total distance to each cluster's points, in row blocks so the
    # full S×S pairwise matrix never materializes (O(block·S) transient)
    sums_to_cluster = np.empty((n, k))
    block = 1024
    for start in range(0, n, block):
        d = distances_to_centers(points[start : start + block], points)
        sums_to_cluster[start : start + block] = d @ one_hot
    own = counts[idx]
    # a: mean distance to *other* points of own cluster (n−1 divisor)
    with np.errstate(divide="ignore", invalid="ignore"):
        a = sums_to_cluster[np.arange(n), idx] / np.maximum(own - 1, 1)
        mean_other = sums_to_cluster / np.maximum(counts, 1)[None, :]
    mean_other[:, counts == 0] = np.inf
    mean_other[np.arange(n), idx] = np.inf
    b = mean_other.min(axis=1)
    s = np.where(
        (own > 1) & np.isfinite(b),
        (b - a) / np.maximum(np.maximum(a, b), 1e-30),
        0.0,
    )
    return float(s.mean())
