"""k-means serving tier: in-memory cluster model behind the REST endpoints.

Equivalent of the reference's KMeansServingModel / KMeansServingModelManager
(app/oryx-app-serving/.../kmeans/model/KMeansServingModel.java:34-87,
KMeansServingModelManager.java:40-89): the model is the cluster list guarded
by a lock; ``UP [id, center, count]`` replaces one cluster's center/count;
``MODEL``/``MODEL-REF`` swaps in a new validated cluster list. Assignment
queries run vectorized against the stacked centroid matrix.
"""

from __future__ import annotations

import logging
import threading

import numpy as np

from oryx_tpu.api.serving import AbstractServingModelManager, ServingModel
from oryx_tpu.common import textutils
from oryx_tpu.ml.mlupdate import read_pmml_from_update_key_message
from oryx_tpu.models.kmeans import pmml_codec
from oryx_tpu.models.kmeans.model import ClusterInfo, assign
from oryx_tpu.models.schema import InputSchema

log = logging.getLogger(__name__)


class KMeansServingModel(ServingModel):
    def __init__(self, clusters, input_schema: InputSchema):
        self._lock = threading.RLock()
        self._clusters: list[ClusterInfo] = list(clusters)
        self.input_schema = input_schema

    def nearest_cluster(self, vector: np.ndarray) -> tuple[int, float]:
        """(cluster ID, distance) of the closest cluster
        (KMeansServingModel.nearestClusterID:50)."""
        with self._lock:
            centers = np.stack([c.center for c in self._clusters])
            ids = [c.id for c in self._clusters]
        idx, dist = assign(np.atleast_2d(vector), centers)
        return ids[int(idx[0])], float(dist[0])

    def update(self, cluster_id: int, center: np.ndarray, count: int) -> None:
        """Replace one cluster's center and count (update:74)."""
        with self._lock:
            for i, c in enumerate(self._clusters):
                if c.id == cluster_id:
                    self._clusters[i] = ClusterInfo(cluster_id, center, count)
                    return
        log.warning("no cluster with ID %s to update", cluster_id)

    @property
    def clusters(self) -> list[ClusterInfo]:
        with self._lock:
            return list(self._clusters)

    def get_fraction_loaded(self) -> float:
        return 1.0


class KMeansServingModelManager(AbstractServingModelManager):
    def __init__(self, config):
        super().__init__(config)
        self.input_schema = InputSchema(config)
        self.model: KMeansServingModel | None = None

    # -- update-topic consumption (consumeKeyMessage:51-83) ------------------
    def consume_key_message(self, key: str, message: str) -> None:
        if key == "UP":
            if self.model is None:
                return  # no model to interpret with yet
            update = textutils.read_json(message)
            self.model.update(
                int(update[0]),
                np.asarray(update[1], dtype=np.float64),
                int(update[2]),
            )
        elif key in ("MODEL", "MODEL-REF"):
            pmml = read_pmml_from_update_key_message(key, message)
            pmml_codec.validate_pmml_vs_schema(pmml, self.input_schema)
            self.model = KMeansServingModel(pmml_codec.read(pmml), self.input_schema)
            log.info("new model loaded (%d clusters)", len(self.model.clusters))
        else:
            raise ValueError(f"bad key: {key}")

    def get_model(self):
        return self.model
