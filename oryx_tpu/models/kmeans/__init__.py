"""k-means clustering vertical: TPU trainer, eval metrics, PMML codec,
speed + serving models (reference app/* kmeans components, SURVEY §2.8-2.11)."""
