"""k-means speed tier: centroid drift from the microbatch stream.

Equivalent of the reference's KMeansSpeedModel / KMeansSpeedModelManager
(app/oryx-app/.../kmeans/KMeansSpeedModel.java,
KMeansSpeedModelManager.java:50-121): ``MODEL``/``MODEL-REF`` replaces the
cluster list (validated against the schema); its own ``UP`` messages are
ignored; ``build_updates`` assigns every microbatch point to its nearest
cluster in one vectorized pass, reduces per-cluster (sum, count), folds the
per-cluster mean into the local running centroid, and emits
``[clusterID, center, count]`` JSON updates.
"""

from __future__ import annotations

import logging

import numpy as np

from oryx_tpu.api.speed import AbstractSpeedModelManager, SpeedModel
from oryx_tpu.common import textutils
from oryx_tpu.ml.mlupdate import read_pmml_from_update_key_message
from oryx_tpu.models import pmml_common
from oryx_tpu.models.kmeans import pmml_codec
from oryx_tpu.models.kmeans.model import ClusterInfo, assign
from oryx_tpu.models.schema import InputSchema

log = logging.getLogger(__name__)


class KMeansSpeedModel(SpeedModel):
    """Cluster list by ID (KMeansSpeedModel.java)."""

    def __init__(self, clusters):
        self._clusters: dict[int, ClusterInfo] = {c.id: c for c in clusters}

    def get_cluster(self, cluster_id: int) -> ClusterInfo:
        return self._clusters[cluster_id]

    def set_cluster(self, cluster_id: int, cluster: ClusterInfo) -> None:
        self._clusters[cluster_id] = cluster

    @property
    def clusters(self) -> list[ClusterInfo]:
        return list(self._clusters.values())

    def get_fraction_loaded(self) -> float:
        return 1.0


class KMeansSpeedModelManager(AbstractSpeedModelManager):
    def __init__(self, config):
        self.config = config
        self.input_schema = InputSchema(config)
        self.model: KMeansSpeedModel | None = None

    # -- update-topic consumption (consumeKeyMessage:55-75) ------------------
    def consume_key_message(self, key: str, message: str) -> None:
        if key == "UP":
            return  # hearing our own updates
        if key in ("MODEL", "MODEL-REF"):
            pmml = read_pmml_from_update_key_message(key, message)
            pmml_codec.validate_pmml_vs_schema(pmml, self.input_schema)
            self.model = KMeansSpeedModel(pmml_codec.read(pmml))
            log.info("new model loaded (%d clusters)", len(self.model.clusters))
        else:
            raise ValueError(f"bad key: {key}")

    # -- microbatch centroid updates (buildUpdates:77-119) -------------------
    def build_updates(self, new_data):
        model = self.model
        if model is None:
            return []
        vectors = []
        for km in new_data:
            tokens = textutils.parse_possibly_json(km.message)
            try:
                vectors.append(
                    pmml_common.features_from_tokens(tokens, self.input_schema)
                )
            except (ValueError, IndexError):
                log.warning("Bad input: %s", km.message)
        if not vectors:
            return []
        points = np.stack(vectors)
        clusters = model.clusters
        centers = np.stack([c.center for c in clusters])
        idx, _ = assign(points, centers)
        updates = []
        for pos in np.unique(idx):
            members = points[idx == pos]
            cluster = clusters[int(pos)]
            cluster.update(members.mean(axis=0), len(members))
            model.set_cluster(cluster.id, cluster)
            updates.append(
                textutils.join_json(
                    [cluster.id, [float(v) for v in cluster.center], cluster.count]
                )
            )
        return updates
