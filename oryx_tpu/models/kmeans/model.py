"""k-means model structures: clusters, distances, assignment.

Equivalent of the reference's ClusterInfo / DistanceFn / KMeansUtils
(app/oryx-app-common/.../kmeans/ClusterInfo.java, EuclideanDistanceFn.java,
KMeansUtils.java:36-85). Assignment is vectorized: distances to all centers
come from one ``||x||² − 2·X·Cᵀ + ||c||²`` expansion, so a batch of points
against the centroid matrix is a single MXU matmul instead of the reference's
per-point loop over clusters.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np


class ClusterInfo:
    """id + center + running count, with running-mean update
    (ClusterInfo.java update())."""

    def __init__(self, id_: int, center: np.ndarray, count: int):
        self.id = int(id_)
        self.center = np.asarray(center, dtype=np.float64)
        self.count = int(count)

    def update(self, vector: np.ndarray, count: int = 1) -> None:
        """Fold ``count`` new points with mean ``vector`` into the running
        centroid mean."""
        total = self.count + count
        self.center = (self.center * self.count + np.asarray(vector) * count) / total
        self.count = total

    def __repr__(self) -> str:  # pragma: no cover
        return f"ClusterInfo({self.id}, count={self.count})"


def check_unique_ids(clusters: Sequence[ClusterInfo]) -> None:
    """(KMeansUtils.checkUniqueIDs:77-85)"""
    ids = [c.id for c in clusters]
    if len(set(ids)) != len(ids):
        raise ValueError(f"duplicate cluster IDs: {ids}")


def euclidean_distance(a: np.ndarray, b: np.ndarray) -> float:
    """(EuclideanDistanceFn.java)"""
    return float(np.linalg.norm(np.asarray(a, dtype=np.float64) - b))


def distances_to_centers(points: np.ndarray, centers: np.ndarray) -> np.ndarray:
    """(N, k) Euclidean distances via the matmul expansion."""
    points = np.atleast_2d(np.asarray(points, dtype=np.float64))
    sq = (
        (points * points).sum(axis=1, keepdims=True)
        - 2.0 * points @ centers.T
        + (centers * centers).sum(axis=1)[None, :]
    )
    return np.sqrt(np.maximum(sq, 0.0))


def assign(points: np.ndarray, centers: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Nearest-center index and distance per point."""
    d = distances_to_centers(points, centers)
    idx = d.argmin(axis=1)
    return idx, d[np.arange(len(d)), idx]


def closest_cluster(
    clusters: Sequence[ClusterInfo], point: np.ndarray
) -> tuple[ClusterInfo, float]:
    """(KMeansUtils.closestCluster) — returns (cluster, distance)."""
    if not clusters:
        raise ValueError("no clusters")
    centers = np.stack([c.center for c in clusters])
    idx, dist = assign(point, centers)
    return clusters[int(idx[0])], float(dist[0])
