"""k-means PMML ClusteringModel codec.

Equivalent of the reference's KMeansPMMLUtils + KMeansUpdate.pmmlClusteringModel
(app/oryx-app-common/.../kmeans/KMeansPMMLUtils.java:47-82,
app/oryx-app-mllib/.../kmeans/KMeansUpdate.java:184-221): a PMML 4.3
ClusteringModel (center-based, squaredEuclidean ComparisonMeasure), one
ClusteringField per active feature, one Cluster per centroid with id, size,
and a REAL Array center. Round-trips models written by the reference.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np

from oryx_tpu.models import pmml_common
from oryx_tpu.models.kmeans.model import ClusterInfo, check_unique_ids
from oryx_tpu.models.schema import InputSchema
from oryx_tpu.pmml import pmmlutils


def clustering_model_to_pmml(
    clusters: Sequence[ClusterInfo], schema: InputSchema
):
    """Build the full PMML document (kMeansModelToPMML:184-221)."""
    pmml = pmmlutils.build_skeleton_pmml()
    pmml_common.build_data_dictionary(pmml, schema)
    model = pmmlutils.subelement(
        pmml,
        "ClusteringModel",
        {
            "functionName": "clustering",
            "modelClass": "centerBased",
            "numberOfClusters": len(clusters),
        },
    )
    pmml_common.build_mining_schema(model, schema)
    cm = pmmlutils.subelement(
        model, "ComparisonMeasure", {"kind": "distance"}
    )
    pmmlutils.subelement(cm, "squaredEuclidean")
    for i in range(schema.num_features):
        if schema.is_active(i):
            pmmlutils.subelement(
                model,
                "ClusteringField",
                {"field": schema.feature_names[i], "isCenterField": "true"},
            )
    for c in clusters:
        cl = pmmlutils.subelement(
            model, "Cluster", {"id": str(c.id), "size": int(c.count)}
        )
        pmml_common.to_pmml_array(cl, c.center)
    return pmml


def read(pmml) -> list[ClusterInfo]:
    """PMML → clusters (KMeansPMMLUtils.read:71-82)."""
    model = pmmlutils.find(pmml, "ClusteringModel")
    if model is None:
        raise ValueError("PMML does not contain a ClusteringModel")
    clusters = []
    for cl in pmmlutils.find_all(model, "Cluster"):
        arr = pmmlutils.find(cl, "Array")
        center = pmml_common.parse_array(arr) if arr is not None else np.zeros(0)
        clusters.append(
            ClusterInfo(int(cl.get("id")), center, int(cl.get("size", "0")))
        )
    check_unique_ids(clusters)
    return clusters


def validate_pmml_vs_schema(pmml, schema: InputSchema) -> None:
    """(KMeansPMMLUtils.validatePMMLVsSchema:47-65)"""
    model = pmmlutils.find(pmml, "ClusteringModel")
    if model is None:
        raise ValueError("PMML does not contain a ClusteringModel")
    if model.get("functionName") != "clustering":
        raise ValueError("model function must be clustering")
    pmml_common.validate_feature_names(pmml, schema, "k-means")
