"""k-means batch update: the MLUpdate implementation for clustering.

Equivalent of the reference's KMeansUpdate (app/oryx-app-mllib/.../kmeans/
KMeansUpdate.java:60-234): hyperparameter k from ``oryx.kmeans.hyperparams.k``;
datum lines parsed through InputSchema into dense numeric vectors; TPU
training (train.kmeans_train — Lloyd sweeps under lax.scan, vmapped restarts);
evaluation over train+test via the strategy from
``oryx.kmeans.evaluation-strategy`` (lower-better metrics negated,
KMeansUpdate.evaluate:150-177); PMML ClusteringModel artifact with per-cluster
sizes counted from the training assignment.
"""

from __future__ import annotations

import logging
from pathlib import Path

import numpy as np

from oryx_tpu.common import rand, textutils
from oryx_tpu.ml import param as hp
from oryx_tpu.ml.mlupdate import MLUpdate
from oryx_tpu.models import pmml_common
from oryx_tpu.models.kmeans import evaluate as kmeval
from oryx_tpu.models.kmeans import pmml_codec
from oryx_tpu.models.kmeans import train as kmtrain
from oryx_tpu.models.kmeans.model import ClusterInfo
from oryx_tpu.models.schema import InputSchema

log = logging.getLogger(__name__)

EVAL_STRATEGIES = ("SILHOUETTE", "DAVIES_BOULDIN", "DUNN", "SSE")


class KMeansUpdate(MLUpdate):
    def __init__(self, config):
        super().__init__(config)
        self.initialization_strategy = config.get_string(
            "oryx.kmeans.initialization-strategy"
        )
        self.evaluation_strategy = config.get_string("oryx.kmeans.evaluation-strategy")
        self.runs = config.get_int("oryx.kmeans.runs")
        self.iterations = config.get_int("oryx.kmeans.iterations")
        self.hyper_params = [hp.from_config(config, "oryx.kmeans.hyperparams.k")]
        self.input_schema = InputSchema(config)
        if self.iterations <= 0 or self.runs <= 0:
            raise ValueError("iterations and runs must be positive")
        if self.initialization_strategy not in (
            kmtrain.INIT_RANDOM,
            kmtrain.INIT_KMEANS_PARALLEL,
        ):
            raise ValueError(f"bad init strategy: {self.initialization_strategy}")
        if self.evaluation_strategy not in EVAL_STRATEGIES:
            raise ValueError(f"bad eval strategy: {self.evaluation_strategy}")
        # unsupervised, numeric-only (KMeansUpdate.java:83-87)
        if self.input_schema.has_target():
            raise ValueError("k-means is unsupervised; remove target-feature")
        if self.input_schema.categorical_features:
            raise ValueError("k-means supports only numeric features")

    def get_hyper_parameter_values(self):
        return list(self.hyper_params)

    def _to_points(self, data) -> np.ndarray:
        vectors = []
        for km in data:
            tokens = textutils.parse_possibly_json(km.message)
            try:
                vectors.append(
                    pmml_common.features_from_tokens(tokens, self.input_schema)
                )
            except (ValueError, IndexError):
                log.warning("Bad input: %s", km.message)
        if not vectors:
            return np.zeros((0, self.input_schema.num_predictors))
        return np.stack(vectors)

    # -- train (buildModel:107-122) -----------------------------------------
    def build_model(self, context, train_data, hyper_parameters, candidate_path: Path):
        k = int(hyper_parameters[0])
        if k <= 0:
            raise ValueError(f"k must be positive: {k}")
        points = self._to_points(train_data)
        if len(points) == 0:
            return None
        centers, counts = kmtrain.kmeans_train(
            points,
            k,
            iterations=self.iterations,
            runs=self.runs,
            init=self.initialization_strategy,
            key=rand.get_key(),
        )
        clusters = [
            ClusterInfo(i, centers[i], int(counts[i])) for i in range(len(centers))
        ]
        return pmml_codec.clustering_model_to_pmml(clusters, self.input_schema)

    # -- eval (evaluate:139-177) --------------------------------------------
    def evaluate(self, context, model, model_parent_path, test_data, train_data):
        pmml_codec.validate_pmml_vs_schema(model, self.input_schema)
        clusters = pmml_codec.read(model)
        # reference evaluates on train ∪ test (KMeansUpdate.evaluate:146-147)
        points = self._to_points(list(train_data) + list(test_data))
        if len(points) == 0:
            return None
        strategy = self.evaluation_strategy
        if strategy == "DAVIES_BOULDIN":
            val = -kmeval.davies_bouldin_index(clusters, points)
        elif strategy == "DUNN":
            val = kmeval.dunn_index(clusters, points)
        elif strategy == "SILHOUETTE":
            val = kmeval.silhouette_coefficient(clusters, points)
        else:  # SSE
            val = -kmeval.sum_squared_error(clusters, points)
        log.info("%s = %s", strategy, val)
        return val
