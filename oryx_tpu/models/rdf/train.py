"""TPU random-decision-forest trainer: binned, level-wise, histogram-based.

Capability equivalent of the reference's MLlib RandomForest training invoked
by RDFUpdate (app/oryx-app-mllib/.../rdf/RDFUpdate.java:126-176:
``RandomForest.trainClassifier/trainRegressor`` with numTrees,
featureSubsetStrategy="auto", impurity, maxDepth, maxBins=maxSplitCandidates)
— but designed XLA-first rather than translated: trees grow level-by-level
with static shapes, and each level is ONE jitted program over the whole
node frontier:

  - features are pre-binned on host (numeric → quantile thresholds, at most
    ``max_split_candidates - 1`` of them; categorical → the encoding itself),
    so device work is integer gathers + segment-sums, no per-node sorting;
  - the (node, feature, bin, channel) histogram is a ``segment_sum`` vmapped
    over features — the classic accelerator formulation of tree growth;
  - split gain for every (node, feature, candidate) is evaluated at once via
    cumulative sums over the bin axis; categorical bins are first ordered by
    a target statistic (Breiman's ordered-prefix trick) with
    ``argsort``/``take_along_axis`` so the same prefix scan finds subset
    splits;
  - per-node random feature subsets (sqrt(P) classification, P/3 regression:
    the MLlib "auto" policy) enter as a mask, not control flow.

The growth loop itself is host Python (one iteration per depth level — at
most ``max_depth + 1`` jit invocations whose shapes repeat across trees, so
compilation is amortized). Bagging uses per-tree Poisson(1) example weights
when num_trees > 1, like MLlib's bootstrap.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass
from functools import partial
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

log = logging.getLogger(__name__)

CLASSIFICATION = "classification"
REGRESSION = "regression"

_EPS = 1e-12


# ---------------------------------------------------------------------------
# Trained-tree structure handed to the PMML codec
# ---------------------------------------------------------------------------


@dataclass
class TrainedSplit:
    predictor_index: int
    threshold: Optional[float]  # numeric: positive/right = value > threshold
    left_categories: Optional[list]  # categorical: encodings routed left/negative
    default_right: bool  # missing values follow the bigger child


@dataclass
class TrainedNode:
    id: str
    count: float  # examples reaching this node (unbagged re-walk)
    split: Optional[TrainedSplit] = None
    negative: "Optional[TrainedNode]" = None
    positive: "Optional[TrainedNode]" = None
    # leaf payload: classification → per-class counts; regression → (mean, n)
    class_counts: Optional[np.ndarray] = None
    mean: Optional[float] = None
    n: Optional[float] = None

    @property
    def is_leaf(self) -> bool:
        return self.split is None


# ---------------------------------------------------------------------------
# Host-side binning
# ---------------------------------------------------------------------------


def bin_features(
    X: np.ndarray,
    is_categorical: np.ndarray,
    n_categories: np.ndarray,
    max_split_candidates: int,
) -> tuple[np.ndarray, list, int]:
    """Quantile-bin numeric columns; categorical columns keep their encoding.

    Returns (bins int32 (N,P), per-feature thresholds (None for categorical),
    B = max bin count over features).
    """
    n, p = X.shape
    bins = np.zeros((n, p), dtype=np.int32)
    thresholds: list = []
    max_bins = 2
    for j in range(p):
        if is_categorical[j]:
            thresholds.append(None)
            bins[:, j] = X[:, j].astype(np.int32)
            max_bins = max(max_bins, int(n_categories[j]))
        else:
            col = X[:, j]
            qs = (
                np.quantile(col, np.linspace(0, 1, max_split_candidates + 1)[1:-1])
                if n > 1
                else np.zeros(0)
            )
            t = np.unique(qs)
            # drop a threshold equal to the max: nothing would go right of it
            if t.size and t[-1] >= col.max():
                t = t[:-1]
            thresholds.append(t)
            # side="left": bin ≤ s ⇔ value ≤ t[s], matching _finalize_tree and
            # the PMML greaterThan wire convention (value == threshold → left,
            # as in reference RDFUpdate.java:545)
            bins[:, j] = np.searchsorted(t, col, side="left").astype(np.int32)
            max_bins = max(max_bins, t.size + 1)
    return bins, thresholds, max_bins


# ---------------------------------------------------------------------------
# One level of frontier growth — the jitted hot path
# ---------------------------------------------------------------------------


@partial(jax.jit, static_argnames=("n_nodes", "n_bins", "task", "impurity"))
def _level_step(
    bins,  # (N, P) int32
    channels,  # (N, C) f32: bag-weighted one-hot class rows, or [w, w*y, w*y^2]
    node_assign,  # (N,) int32, -1 = inactive (already in a finished leaf)
    feature_mask,  # (n_nodes, P) bool — random per-node feature subset
    cat_mask,  # (P,) bool
    *,
    n_nodes: int,
    n_bins: int,
    task: str,
    impurity: str,
):
    """Evaluate every (node, feature, candidate-split) of one depth level.

    Returns per node: best gain, best feature, a (B,) left-bin mask over
    ORIGINAL bin indices, left/right weight mass, and the node's channel
    totals (the leaf statistics).
    """
    n_features = bins.shape[1]

    active = node_assign >= 0
    safe_node = jnp.where(active, node_assign, 0)
    w_channels = jnp.where(active[:, None], channels, 0.0)

    def per_feature_hist(bins_p):
        seg = safe_node * n_bins + bins_p
        return jax.ops.segment_sum(w_channels, seg, num_segments=n_nodes * n_bins)

    hist = jax.vmap(per_feature_hist, in_axes=1, out_axes=0)(bins)
    # (P, n_nodes*B, C) → (n_nodes, P, B, C)
    hist = hist.reshape(n_features, n_nodes, n_bins, -1).transpose(1, 0, 2, 3)

    totals = hist[:, 0, :, :].sum(axis=1)  # (n_nodes, C) node aggregates

    def weight_of(h):  # example-weight mass of a histogram slice
        if task == CLASSIFICATION:
            return h.sum(axis=-1)
        return h[..., 0]

    # order bins: numeric = natural order; categorical = by target statistic
    bin_w = weight_of(hist)  # (n_nodes, P, B)
    if task == CLASSIFICATION:
        maj = jnp.argmax(totals, axis=1)  # node majority class
        maj_counts = jnp.take_along_axis(
            hist,
            jnp.broadcast_to(maj[:, None, None, None], hist.shape[:3] + (1,)),
            axis=3,
        )[..., 0]
        stat = maj_counts / jnp.maximum(bin_w, _EPS)
    else:
        stat = hist[..., 1] / jnp.maximum(hist[..., 0], _EPS)  # per-bin mean y
    natural = jnp.broadcast_to(
        jnp.arange(n_bins, dtype=stat.dtype), stat.shape
    )
    order_key = jnp.where(cat_mask[None, :, None], stat, natural)
    order = jnp.argsort(order_key, axis=2, stable=True)  # (n_nodes, P, B)
    sorted_hist = jnp.take_along_axis(hist, order[..., None], axis=2)

    left = jnp.cumsum(sorted_hist, axis=2)  # prefix sums over ordered bins
    right = totals[:, None, None, :] - left

    def impurity_times_n(h):
        """n * impurity(h) — weight-scaled so child terms just add."""
        if task == CLASSIFICATION:
            nw = h.sum(axis=-1)
            p = h / jnp.maximum(nw, _EPS)[..., None]
            if impurity == "gini":
                return nw * (1.0 - (p * p).sum(axis=-1))
            return nw * (-(p * jnp.where(p > 0, jnp.log(p), 0.0)).sum(axis=-1))
        # variance impurity: sum w*y^2 - (sum w*y)^2 / sum w
        return h[..., 2] - h[..., 1] ** 2 / jnp.maximum(h[..., 0], _EPS)

    parent = impurity_times_n(totals)  # (n_nodes,)
    gain = parent[:, None, None] - impurity_times_n(left) - impurity_times_n(right)

    nl = weight_of(left)
    nr = weight_of(right)
    valid = (nl > 0) & (nr > 0) & feature_mask[:, :, None]
    # the final prefix (everything left) is never valid since nr == 0 there
    gain = jnp.where(valid, gain, -jnp.inf)

    flat_gain = gain.reshape(n_nodes, -1)
    best = jnp.argmax(flat_gain, axis=1)
    best_gain = jnp.take_along_axis(flat_gain, best[:, None], axis=1)[:, 0]
    best_feature = best // n_bins
    best_s = best % n_bins

    # left mask over ORIGINAL bins: rank of bin in the chosen feature's order ≤ s
    order_f = jnp.take_along_axis(
        order, jnp.broadcast_to(best_feature[:, None, None], (n_nodes, 1, n_bins)), axis=1
    )[:, 0, :]
    inv = jnp.argsort(order_f, axis=1)  # rank of each original bin
    left_mask = inv <= best_s[:, None]

    count_l = jnp.take_along_axis(nl.reshape(n_nodes, -1), best[:, None], axis=1)[:, 0]
    count_r = jnp.take_along_axis(nr.reshape(n_nodes, -1), best[:, None], axis=1)[:, 0]
    return best_gain, best_feature, left_mask, count_l, count_r, totals


@jax.jit
def _route(bins, node_assign, split_flag, best_feature, left_masks):
    """Send each active example to its child for the next level: left → 2i,
    right → 2i + 1; examples in now-terminal nodes go inactive (-1)."""
    active = node_assign >= 0
    safe = jnp.where(active, node_assign, 0)
    f = best_feature[safe]
    b = jnp.take_along_axis(bins, f[:, None], axis=1)[:, 0]
    goes_left = left_masks[safe, b]
    child = 2 * safe + jnp.where(goes_left, 0, 1)
    return jnp.where(active & split_flag[safe], child, -1)


# ---------------------------------------------------------------------------
# Forest driver
# ---------------------------------------------------------------------------


def forest_train(
    X: np.ndarray,
    y: np.ndarray,
    is_categorical: Sequence[bool],
    n_categories: Sequence[int],
    *,
    task: str,
    n_classes: int = 0,
    num_trees: int,
    max_depth: int,
    max_split_candidates: int,
    impurity: str = "entropy",
    min_node_size: int = 1,
    min_info_gain_nats: float = 0.0,
    rng: "np.random.Generator",
) -> tuple[list[TrainedNode], np.ndarray]:
    """Train a forest; returns (tree roots, per-predictor importances).

    Node record counts come from an unbagged re-walk of the training data,
    and importances are each predictor's share of all examples passing
    through nodes that split on it (RDFUpdate.treeNodeExampleCounts:267,
    predictorExampleCounts:310, countsToImportances:547-553).
    """
    n, p = X.shape
    if n == 0:
        raise ValueError("no training examples")
    is_categorical = np.asarray(is_categorical, dtype=bool)
    n_categories = np.asarray(n_categories, dtype=np.int64)
    if task == CLASSIFICATION and n_classes < 2:
        raise ValueError("classification needs >= 2 classes")
    if task == REGRESSION:
        impurity = "variance"
    elif impurity not in ("gini", "entropy"):
        raise ValueError(f"bad impurity: {impurity}")
    if min_node_size < 1:
        raise ValueError("min-node-size must be at least 1")
    if min_info_gain_nats < 0:
        raise ValueError("min-info-gain-nats must be non-negative")

    bins_np, thresholds, n_bins = bin_features(
        X, is_categorical, n_categories, max_split_candidates
    )
    bins = jnp.asarray(bins_np)
    cat_mask = jnp.asarray(is_categorical)

    if task == CLASSIFICATION:
        base_channels = jax.nn.one_hot(
            jnp.asarray(y.astype(np.int32)), n_classes, dtype=jnp.float32
        )
    else:
        yj = jnp.asarray(y, dtype=jnp.float32)
        base_channels = jnp.stack([jnp.ones_like(yj), yj, yj * yj], axis=1)

    # per-node feature-subset size: MLlib "auto" (all features if one tree)
    if num_trees == 1:
        subset = p
    elif task == CLASSIFICATION:
        subset = max(1, int(np.sqrt(p)))
    else:
        subset = max(1, p // 3)

    trees: list[TrainedNode] = []
    predictor_counts = np.zeros(p, dtype=np.float64)

    for _ in range(num_trees):
        bag = (
            rng.poisson(1.0, size=n).astype(np.float32)
            if num_trees > 1
            else np.ones(n, dtype=np.float32)
        )
        channels = base_channels * jnp.asarray(bag)[:, None]
        levels = _grow_tree(
            bins,
            channels,
            cat_mask,
            rng,
            n_bins=n_bins,
            n_features=p,
            subset_size=subset,
            max_depth=max_depth,
            task=task,
            impurity=impurity,
            min_node_size=min_node_size,
            min_info_gain_nats=min_info_gain_nats,
        )
        root, pred_counts = _finalize_tree(
            levels, bins_np, thresholds, is_categorical, n_categories, task
        )
        trees.append(root)
        predictor_counts += pred_counts
    total = predictor_counts.sum()
    importances = predictor_counts / total if total > 0 else np.zeros(p)
    return trees, importances


def _grow_tree(
    bins, channels, cat_mask, rng, *, n_bins, n_features, subset_size, max_depth,
    task, impurity, min_node_size=1, min_info_gain_nats=0.0,
):
    """Level-wise growth; returns per-level split decisions as host arrays."""
    n = bins.shape[0]
    node_assign = jnp.zeros(n, dtype=jnp.int32)
    levels = []
    for depth in range(max_depth + 1):
        n_nodes = 1 << depth
        mask_np = np.zeros((n_nodes, n_features), dtype=bool)
        for i in range(n_nodes):
            mask_np[i, rng.choice(n_features, size=subset_size, replace=False)] = True
        gain, feat, left_mask, cl, cr, totals = _level_step(
            bins,
            channels,
            node_assign,
            jnp.asarray(mask_np),
            cat_mask,
            n_nodes=n_nodes,
            n_bins=n_bins,
            task=task,
            impurity=impurity,
        )
        # ONE explicit batched fetch per level instead of six piecemeal
        # np.asarray syncs — the split decision is host control flow by
        # design (level-wise growth), but it only needs one device
        # round-trip to make it
        gain, feat_np, left_mask_np, cl_np, cr_np, totals_np = jax.device_get(
            (gain, feat, left_mask, cl, cr, totals)
        )
        # a node splits if it found positive gain, more depth is allowed, and
        # the reference's pre-prune knobs pass: per-example gain at least
        # min-info-gain-nats, both children at least min-node-size examples
        # (oryx.rdf.hyperparams.*, RDFUpdate.java minNodeSize/minInfoGainNats)
        node_w = totals_np.sum(axis=1) if task == CLASSIFICATION else totals_np[:, 0]
        norm_gain = gain / np.maximum(node_w, _EPS)
        split = (
            np.isfinite(gain)
            & (gain > _EPS)
            & (depth < max_depth)
            & (norm_gain >= min_info_gain_nats)
            & (cl_np >= min_node_size)
            & (cr_np >= min_node_size)
        )
        levels.append(
            dict(
                split=split,
                feature=feat_np,
                left_mask=left_mask_np,
                count_l=cl_np,
                count_r=cr_np,
                totals=totals_np,
            )
        )
        if not split.any():
            break
        node_assign = _route(
            bins,
            node_assign,
            jnp.asarray(split),
            jnp.asarray(feat),
            jnp.asarray(levels[-1]["left_mask"]),
        )
    return levels


def _finalize_tree(levels, bins_np, thresholds, is_categorical, n_categories, task):
    """Host pass: re-walk the unbagged data for per-node record counts and
    per-predictor example counts, then build the TrainedNode tree."""
    n, p = bins_np.shape
    assign = np.zeros(n, dtype=np.int64)
    active = np.ones(n, dtype=bool)
    node_counts_per_level = []
    pred_counts = np.zeros(p, dtype=np.float64)
    rows = np.arange(n)
    for level in levels:
        n_nodes = len(level["split"])
        counts = np.bincount(assign[active], minlength=n_nodes).astype(np.float64)
        node_counts_per_level.append(counts)
        for i in np.nonzero(level["split"])[0]:
            pred_counts[level["feature"][i]] += counts[i]
        # route the still-active examples whose node split
        safe = np.clip(assign, 0, n_nodes - 1)
        splits_here = level["split"][safe] & active
        feat = level["feature"][safe]
        goes_left = level["left_mask"][safe, bins_np[rows, feat]]
        assign = np.where(splits_here, 2 * assign + np.where(goes_left, 0, 1), assign)
        active = splits_here

    def build(depth: int, idx: int, node_id: str) -> TrainedNode:
        level = levels[depth]
        counts = node_counts_per_level[depth]
        count = float(counts[idx]) if idx < len(counts) else 0.0
        totals = level["totals"][idx]
        if not level["split"][idx] or depth + 1 >= len(levels):
            return _leaf(node_id, count, totals, task)
        f = int(level["feature"][idx])
        lm = level["left_mask"][idx]
        default_right = bool(level["count_r"][idx] > level["count_l"][idx])
        if is_categorical[f]:
            left_cats = [b for b in range(int(n_categories[f])) if lm[b]]
            split = TrainedSplit(f, None, left_cats, default_right)
        else:
            t = thresholds[f]
            s = int(lm.sum()) - 1  # bins ≤ s go left ⇔ value ≤ t[s]
            thr = float(t[s]) if s < len(t) else float(np.inf)
            split = TrainedSplit(f, thr, None, default_right)
        return TrainedNode(
            node_id,
            count,
            split=split,
            negative=build(depth + 1, 2 * idx, node_id + "-"),
            positive=build(depth + 1, 2 * idx + 1, node_id + "+"),
        )

    return build(0, 0, "r"), pred_counts


def _leaf(node_id: str, count: float, totals: np.ndarray, task: str) -> TrainedNode:
    if task == CLASSIFICATION:
        cc = np.asarray(totals, dtype=np.float64)
        if cc.sum() <= 0:
            cc = np.ones_like(cc)  # node never saw bagged weight: uniform
        return TrainedNode(node_id, count, class_counts=cc)
    w, wy = float(totals[0]), float(totals[1])
    mean = wy / w if w > 0 else 0.0
    return TrainedNode(node_id, count, mean=mean, n=max(w, 0.0))
