"""RDF speed tier: per-leaf target statistics from the microbatch stream.

Equivalent of the reference's RDFSpeedModel / RDFSpeedModelManager
(app/oryx-app/.../rdf/RDFSpeedModel.java, RDFSpeedModelManager.java:57-148):
``MODEL``/``MODEL-REF`` replaces the forest (validated against the schema);
its own ``UP`` messages are ignored; ``build_updates`` routes every new
example to its terminal node in each tree and emits one aggregate update per
(tree, node): ``[treeID, nodeID, {encoding: count}]`` JSON for
classification, ``[treeID, nodeID, mean, count]`` for regression.
"""

from __future__ import annotations

import logging
from collections import defaultdict

import numpy as np

from oryx_tpu.api.speed import AbstractSpeedModelManager, SpeedModel
from oryx_tpu.common import textutils
from oryx_tpu.ml.mlupdate import read_pmml_from_update_key_message
from oryx_tpu.models.classreg import example_from_tokens
from oryx_tpu.models.rdf import pmml_codec
from oryx_tpu.models.rdf.tree import DecisionForest
from oryx_tpu.models.schema import CategoricalValueEncodings, InputSchema

log = logging.getLogger(__name__)


class RDFSpeedModel(SpeedModel):
    """Forest + encodings (RDFSpeedModel.java)."""

    def __init__(self, forest: DecisionForest, encodings: CategoricalValueEncodings):
        self.forest = forest
        self.encodings = encodings

    def get_fraction_loaded(self) -> float:
        return 1.0


class RDFSpeedModelManager(AbstractSpeedModelManager):
    def __init__(self, config):
        self.config = config
        self.input_schema = InputSchema(config)
        self.model: RDFSpeedModel | None = None

    # -- update-topic consumption (consumeKeyMessage:68-91) ------------------
    def consume_key_message(self, key: str, message: str) -> None:
        if key == "UP":
            return  # hearing our own updates
        if key in ("MODEL", "MODEL-REF"):
            pmml = read_pmml_from_update_key_message(key, message)
            pmml_codec.validate_pmml_vs_schema(pmml, self.input_schema)
            forest, encodings = pmml_codec.read(pmml)
            self.model = RDFSpeedModel(forest, encodings)
            log.info("new model loaded (%d trees)", len(forest.trees))
        else:
            raise ValueError(f"bad key: {key}")

    # -- microbatch leaf statistics (buildUpdates:93-148) --------------------
    def build_updates(self, new_data):
        model = self.model
        if model is None:
            return []
        schema = self.input_schema
        examples = []
        for km in new_data:
            try:
                tokens = textutils.parse_possibly_json(km.message)
                examples.append(
                    example_from_tokens(tokens, schema, model.encodings)
                )
            except (ValueError, KeyError, IndexError):
                log.warning("Bad input: %s", km.message)
        if not examples:
            return []

        # (treeID, nodeID) → list of targets
        targets = defaultdict(list)
        for example in examples:
            if example.target is None:
                continue
            for tree_id, tree in enumerate(model.forest.trees):
                terminal = tree.find_terminal(example)
                targets[(tree_id, terminal.id)].append(example.target)

        updates = []
        if schema.is_classification():
            for (tree_id, node_id), feats in targets.items():
                counts: dict[int, int] = defaultdict(int)
                for f in feats:
                    counts[f.encoding] += 1
                updates.append(
                    textutils.join_json([tree_id, node_id, dict(counts)])
                )
        else:
            for (tree_id, node_id), feats in targets.items():
                values = np.asarray([f.value for f in feats])
                updates.append(
                    textutils.join_json(
                        [tree_id, node_id, float(values.mean()), len(values)]
                    )
                )
        return updates
