"""RDF batch update: the MLUpdate implementation for decision forests.

Equivalent of the reference's RDFUpdate (app/oryx-app-mllib/.../rdf/
RDFUpdate.java:91-558): num-trees from ``oryx.rdf.num-trees``; hyperparams
max-split-candidates / max-depth / impurity / min-node-size /
min-info-gain-nats from ``oryx.rdf.hyperparams.*``;
categorical value encodings built from the distinct values in the training
data (getDistinctValues:208-227, sorted here for determinism); training via
the TPU histogram forest trainer (train.forest_train); per-node record counts
and per-predictor importances from an unbagged re-walk
(treeNodeExampleCounts:267, predictorExampleCounts:310); evaluation =
accuracy for classification, −RMSE for regression (Evaluation.java:31-52).
"""

from __future__ import annotations

import logging
from pathlib import Path

import numpy as np

from oryx_tpu.common import rand, textutils
from oryx_tpu.ml import param as hp
from oryx_tpu.ml.mlupdate import MLUpdate
from oryx_tpu.models.classreg import example_from_tokens
from oryx_tpu.models.rdf import pmml_codec
from oryx_tpu.models.rdf import train as rdftrain
from oryx_tpu.models.schema import CategoricalValueEncodings, InputSchema

log = logging.getLogger(__name__)


class RDFUpdate(MLUpdate):
    def __init__(self, config):
        super().__init__(config)
        self.num_trees = config.get_int("oryx.rdf.num-trees")
        if self.num_trees < 1:
            raise ValueError("num-trees must be at least 1")
        self.hyper_params = [
            hp.from_config(config, "oryx.rdf.hyperparams.max-split-candidates"),
            hp.from_config(config, "oryx.rdf.hyperparams.max-depth"),
            hp.from_config(config, "oryx.rdf.hyperparams.impurity"),
            hp.from_config(config, "oryx.rdf.hyperparams.min-node-size"),
            hp.from_config(config, "oryx.rdf.hyperparams.min-info-gain-nats"),
        ]
        self.input_schema = InputSchema(config)
        if not self.input_schema.has_target():
            raise ValueError("RDF requires a target-feature")

    def get_hyper_parameter_values(self):
        return list(self.hyper_params)

    # -- parsing helpers ----------------------------------------------------
    def _parse(self, data) -> list[list[str]]:
        rows = []
        for km in data:
            try:
                rows.append(textutils.parse_possibly_json(km.message))
            except ValueError:
                log.warning("Bad input: %s", km.message)
        return rows

    def _distinct_values(self, rows) -> CategoricalValueEncodings:
        """(getDistinctValues:208-227) — sorted for deterministic encodings."""
        schema = self.input_schema
        distinct: dict[int, set] = {
            i: set() for i in range(schema.num_features) if schema.is_categorical(i)
        }
        for row in rows:
            for i, values in distinct.items():
                values.add(row[i])
        return CategoricalValueEncodings(
            {i: sorted(v) for i, v in distinct.items()}
        )

    def _to_matrix(self, rows, encodings) -> tuple[np.ndarray, np.ndarray]:
        """Rows → dense (X, y) with categorical values encoded
        (parseToLabeledPointRDD:230-264)."""
        schema = self.input_schema
        X = np.zeros((len(rows), schema.num_predictors), dtype=np.float64)
        y = np.zeros(len(rows), dtype=np.float64)
        keep = np.ones(len(rows), dtype=bool)
        for r, row in enumerate(rows):
            try:
                for i in range(schema.num_features):
                    if schema.is_numeric(i):
                        encoded = float(row[i])
                    elif schema.is_categorical(i):
                        encoded = encodings.get_value_encoding_map(i)[row[i]]
                    else:
                        continue
                    if schema.is_target(i):
                        y[r] = encoded
                    else:
                        X[r, schema.feature_to_predictor_index(i)] = encoded
            except (ValueError, KeyError, IndexError):
                log.warning("Bad input: %s", row)
                keep[r] = False
        return X[keep], y[keep]

    def _predictor_layout(self, encodings):
        schema = self.input_schema
        is_cat = np.zeros(schema.num_predictors, dtype=bool)
        n_cat = np.zeros(schema.num_predictors, dtype=np.int64)
        for i in range(schema.num_features):
            if schema.is_active(i) and not schema.is_target(i):
                p = schema.feature_to_predictor_index(i)
                if schema.is_categorical(i):
                    is_cat[p] = True
                    n_cat[p] = encodings.get_value_count(i)
        return is_cat, n_cat

    # -- train (buildModel:113-176) -----------------------------------------
    def build_model(self, context, train_data, hyper_parameters, candidate_path: Path):
        max_split_candidates = int(hyper_parameters[0])
        max_depth = int(hyper_parameters[1])
        impurity = str(hyper_parameters[2])
        # pre-prune knobs ride the hyperparam vector like the reference's
        # (RDFUpdate.java minNodeSize/minInfoGainNats); absent entries (older
        # 3-element callers) keep the trainer's permissive defaults
        min_node_size = int(hyper_parameters[3]) if len(hyper_parameters) > 3 else 1
        min_info_gain = float(hyper_parameters[4]) if len(hyper_parameters) > 4 else 0.0
        if max_split_candidates < 2:
            raise ValueError("max-split-candidates must be at least 2")
        if max_depth <= 0:
            raise ValueError("max-depth must be at least 1")

        rows = self._parse(train_data)
        if not rows:
            return None
        encodings = self._distinct_values(rows)
        X, y = self._to_matrix(rows, encodings)
        if len(X) == 0:
            return None
        is_cat, n_cat = self._predictor_layout(encodings)

        schema = self.input_schema
        if schema.is_classification():
            task = rdftrain.CLASSIFICATION
            n_classes = encodings.get_value_count(schema.target_feature_index)
        else:
            task = rdftrain.REGRESSION
            n_classes = 0
            impurity = "variance"

        trees, importances = rdftrain.forest_train(
            X,
            y,
            is_cat,
            n_cat,
            task=task,
            n_classes=n_classes,
            num_trees=self.num_trees,
            max_depth=max_depth,
            max_split_candidates=max_split_candidates,
            impurity=impurity,
            min_node_size=min_node_size,
            min_info_gain_nats=min_info_gain,
            rng=rand.get_random(),
        )
        return pmml_codec.forest_to_pmml(
            trees,
            importances,
            schema,
            encodings,
            max_depth=max_depth,
            max_split_candidates=max_split_candidates,
            impurity=impurity,
        )

    # -- eval (evaluate:178-205) --------------------------------------------
    def evaluate(self, context, model, model_parent_path, test_data, train_data):
        pmml_codec.validate_pmml_vs_schema(model, self.input_schema)
        forest, encodings = pmml_codec.read(model)
        examples = []
        for row in self._parse(test_data):
            try:
                examples.append(example_from_tokens(row, self.input_schema, encodings))
            except (ValueError, KeyError, IndexError):
                log.warning("Bad test input: %s", row)
        if not examples:
            return 0.0
        if self.input_schema.is_classification():
            correct = sum(
                1
                for ex in examples
                if forest.predict(ex).most_probable_category_encoding
                == ex.target.encoding
            )
            accuracy = correct / len(examples)
            log.info("Accuracy: %s", accuracy)
            return accuracy
        mse = float(
            np.mean(
                [
                    (forest.predict(ex).prediction - ex.target.value) ** 2
                    for ex in examples
                ]
            )
        )
        rmse = float(np.sqrt(mse))
        log.info("RMSE: %s", rmse)
        return -rmse
