"""Decision tree & forest structures for serving and speed tiers.

Equivalent of the reference's rdf trees and decisions
(app/oryx-app-common/.../rdf/tree/{DecisionTree,DecisionForest,DecisionNode,
TerminalNode,TreeNode}.java, rdf/decision/{NumericDecision,
CategoricalDecision}.java): node IDs are root-path strings of ``+``/``-``
("r", "r+", "r-+", ... DecisionTree.findByID:66-85); a NumericDecision sends
an example right when ``value >= threshold`` (NumericDecision.java:104), a
CategoricalDecision when the category's bit is in the active set
(CategoricalDecision.java:82); missing features follow the decision's
``default_decision`` (the more-populated child, RDFUpdate defaultChild logic);
forest prediction is a weighted vote over per-tree terminal predictions.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from oryx_tpu.models.classreg import (
    CATEGORICAL,
    NUMERIC,
    Example,
    vote_on_feature,
)


class NumericDecision:
    """Example goes right ("positive") iff feature >= threshold."""

    feature_type = NUMERIC

    def __init__(self, feature_number: int, threshold: float, default_decision: bool):
        self.feature_number = feature_number
        self.threshold = float(threshold)
        self.default_decision = bool(default_decision)

    def is_positive(self, example: Example) -> bool:
        feature = example.get_feature(self.feature_number)
        if feature is None:
            return self.default_decision
        return feature.value >= self.threshold

    def __repr__(self) -> str:  # pragma: no cover
        return f"(#{self.feature_number} >= {self.threshold})"


class CategoricalDecision:
    """Example goes right iff its category encoding is in the active set."""

    feature_type = CATEGORICAL

    def __init__(
        self,
        feature_number: int,
        active_categories: Sequence[int],
        default_decision: bool,
    ):
        self.feature_number = feature_number
        self.active_categories = frozenset(int(c) for c in active_categories)
        self.default_decision = bool(default_decision)

    def is_positive(self, example: Example) -> bool:
        feature = example.get_feature(self.feature_number)
        if feature is None:
            return self.default_decision
        return feature.encoding in self.active_categories

    def __repr__(self) -> str:  # pragma: no cover
        return f"(#{self.feature_number} in {sorted(self.active_categories)})"


class TerminalNode:
    """Leaf carrying a mutable prediction (TerminalNode.java)."""

    def __init__(self, node_id: str, prediction):
        self.id = node_id
        self.prediction = prediction

    @property
    def is_terminal(self) -> bool:
        return True

    def __repr__(self) -> str:  # pragma: no cover
        return f"{self.id}={self.prediction!r}"


class DecisionNode:
    """Internal node: decision + negative(left)/positive(right) children."""

    def __init__(self, node_id: str, decision, negative, positive):
        self.id = node_id
        self.decision = decision
        self.negative = negative
        self.positive = positive

    @property
    def is_terminal(self) -> bool:
        return False

    def __repr__(self) -> str:  # pragma: no cover
        return f"{self.id}:{self.decision!r}"


class DecisionTree:
    """One tree; prediction = walk to terminal (DecisionTree.java:39-85)."""

    def __init__(self, root):
        self.root = root

    def find_terminal(self, example: Example) -> TerminalNode:
        node = self.root
        while not node.is_terminal:
            node = node.positive if node.decision.is_positive(example) else node.negative
        return node

    def predict(self, example: Example):
        return self.find_terminal(example).prediction

    def find_by_id(self, node_id: str) -> "Optional[object]":
        """Walk the +/- path encoded in the ID itself (findByID:66-85)."""
        if not node_id.startswith("r"):
            raise ValueError(f"bad node ID: {node_id}")
        node = self.root
        for c in node_id[1:]:
            if node.is_terminal:
                return None
            if c == "+":
                node = node.positive
            elif c == "-":
                node = node.negative
            else:
                raise ValueError(f"bad node ID: {node_id}")
        return node

    def __repr__(self) -> str:  # pragma: no cover
        return f"DecisionTree({self.root!r})"


class DecisionForest:
    """Weighted trees + per-feature importances (DecisionForest.java:34-88)."""

    def __init__(
        self,
        trees: Sequence[DecisionTree],
        weights: Sequence[float],
        feature_importances: Sequence[float],
    ):
        if not trees:
            raise ValueError("empty forest")
        if len(trees) != len(weights):
            raise ValueError("trees and weights differ in length")
        self.trees = list(trees)
        self.weights = np.asarray(weights, dtype=np.float64)
        self.feature_importances = np.asarray(feature_importances, dtype=np.float64)

    def predict(self, example: Example):
        votes = [tree.predict(example) for tree in self.trees]
        return vote_on_feature(votes, self.weights)

    def __repr__(self) -> str:  # pragma: no cover
        return f"DecisionForest[numTrees:{len(self.trees)}]"
