"""Random decision forest vertical: TPU histogram trainer, PMML codec,
speed and serving tiers (reference: app/oryx-app-{common,mllib,app,serving}
rdf packages)."""
