"""RDF PMML codec: TreeModel / MiningModel+Segmentation round trip.

Equivalent of the reference's RDFPMMLUtils + RDFUpdate.rdfModelToPMML
(app/oryx-app-common/.../rdf/RDFPMMLUtils.java:73-279,
app/oryx-app-mllib/.../rdf/RDFUpdate.java:368-553). Wire conventions kept
byte-compatible with the reference:

  - one tree → a bare ``TreeModel``; many → ``MiningModel`` with a
    ``Segmentation`` of weight-1 segments (weightedMajorityVote for
    classification, weightedAverage for regression);
  - node IDs are root-path strings ("r", "r+", "r-", ...); the positive/right
    child carries the predicate and comes first, the negative/left child is
    ``<True/>``;
  - numeric split → ``SimplePredicate greaterThan threshold`` (the reader
    converts to the ≥-convention by adding one ulp); categorical split →
    ``SimpleSetPredicate isNotIn`` over the left/negative value set;
  - ``defaultChild`` points at the more-populated child and drives
    missing-value routing; ``recordCount`` carries the training example count;
  - classification leaves carry ``ScoreDistribution`` (recordCount +
    confidence); regression leaves carry ``score`` + recordCount;
  - model extensions: maxDepth, maxSplitCandidates, impurity.
"""

from __future__ import annotations

import math
from typing import Sequence

import numpy as np
import xml.etree.ElementTree as ET

from oryx_tpu.models import pmml_common
from oryx_tpu.models.classreg import CategoricalPrediction, NumericPrediction
from oryx_tpu.models.rdf import train as rdftrain
from oryx_tpu.models.rdf.tree import (
    CategoricalDecision,
    DecisionForest,
    DecisionNode,
    DecisionTree,
    NumericDecision,
    TerminalNode,
)
from oryx_tpu.models.schema import CategoricalValueEncodings, InputSchema
from oryx_tpu.pmml import pmmlutils


# ---------------------------------------------------------------------------
# Write: trained trees → PMML
# ---------------------------------------------------------------------------


def forest_to_pmml(
    trees: Sequence[rdftrain.TrainedNode],
    importances: np.ndarray,
    schema: InputSchema,
    encodings: CategoricalValueEncodings,
    *,
    max_depth: int,
    max_split_candidates: int,
    impurity: str,
) -> ET.Element:
    """(RDFUpdate.rdfModelToPMML:368-421)"""
    classification = schema.is_classification()
    pmml = pmmlutils.build_skeleton_pmml()
    pmml_common.build_data_dictionary(pmml, schema, encodings)
    function = "classification" if classification else "regression"
    if len(trees) == 1:
        model = pmmlutils.subelement(pmml, "TreeModel", _tree_model_attrs(function))
        pmml_common.build_mining_schema(model, schema, importances)
        _write_tree(model, trees[0], schema, encodings, classification)
    else:
        model = pmmlutils.subelement(pmml, "MiningModel", {"functionName": function})
        pmml_common.build_mining_schema(model, schema, importances)
        method = "weightedMajorityVote" if classification else "weightedAverage"
        seg = pmmlutils.subelement(model, "Segmentation", {"multipleModelMethod": method})
        for tree_id, root in enumerate(trees):
            segment = pmmlutils.subelement(seg, "Segment", {"id": tree_id, "weight": "1.0"})
            pmmlutils.subelement(segment, "True")
            tm = pmmlutils.subelement(segment, "TreeModel", _tree_model_attrs(function))
            pmml_common.build_mining_schema(tm, schema, importances)
            _write_tree(tm, root, schema, encodings, classification)
    pmmlutils.add_extension(pmml, "maxDepth", max_depth)
    pmmlutils.add_extension(pmml, "maxSplitCandidates", max_split_candidates)
    pmmlutils.add_extension(pmml, "impurity", impurity)
    return pmml


def _tree_model_attrs(function: str) -> dict:
    return {
        "functionName": function,
        "splitCharacteristic": "binarySplit",
        "missingValueStrategy": "defaultChild",
    }


def _write_tree(parent, root: rdftrain.TrainedNode, schema, encodings, classification):
    _write_node(parent, root, None, schema, encodings, classification)


def _write_node(parent, node: rdftrain.TrainedNode, arriving_split, schema, encodings, classification):
    """arriving_split = (TrainedSplit, is_positive) decision that led here;
    the predicate belongs to the child, not the node's own split
    (RDFUpdate.toTreeModel:426-500)."""
    el = pmmlutils.subelement(
        parent, "Node", {"id": node.id, "recordCount": pmml_common.format_number(node.count)}
    )
    _write_predicate(el, arriving_split, schema, encodings)
    if node.is_leaf:
        if classification:
            target_idx = schema.target_feature_index
            e2v = encodings.get_encoding_value_map(target_idx)
            counts = node.class_counts
            for enc in sorted(e2v):
                record_count = float(counts[enc]) if enc < len(counts) else 0.0
                if record_count > 0.0:
                    total = float(counts.sum())
                    dist = pmmlutils.subelement(
                        el,
                        "ScoreDistribution",
                        {
                            "value": e2v[enc],
                            "recordCount": pmml_common.format_number(record_count),
                        },
                    )
                    dist.set("confidence", pmml_common.format_number(record_count / total))
        else:
            el.set("score", pmml_common.format_number(node.mean))
    else:
        default_child = node.id + ("+" if node.split.default_right else "-")
        el.set("defaultChild", default_child)
        # positive/right first — it carries the predicate and evaluates first
        _write_node(el, node.positive, (node.split, True), schema, encodings, classification)
        _write_node(el, node.negative, (node.split, False), schema, encodings, classification)


def _write_predicate(el, arriving_split, schema, encodings):
    """(RDFUpdate.buildPredicate:505-545)"""
    if arriving_split is None or not arriving_split[1]:
        pmmlutils.subelement(el, "True")
        return
    split = arriving_split[0]
    feature_index = schema.predictor_to_feature_index(split.predictor_index)
    field = schema.feature_names[feature_index]
    if split.left_categories is not None:
        e2v = encodings.get_encoding_value_map(feature_index)
        negative_values = [e2v[c] for c in split.left_categories]
        pred = pmmlutils.subelement(
            el,
            "SimpleSetPredicate",
            {"field": field, "booleanOperator": "isNotIn"},
        )
        arr = pmmlutils.subelement(
            pred, "Array", {"type": "string", "n": len(negative_values)}
        )
        arr.text = pmmlutils.join_pmml_delimited(negative_values)
    else:
        pmmlutils.subelement(
            el,
            "SimplePredicate",
            {
                "field": field,
                "operator": "greaterThan",
                "value": pmml_common.format_number(split.threshold),
            },
        )


# ---------------------------------------------------------------------------
# Read: PMML → DecisionForest (RDFPMMLUtils.read:112-160)
# ---------------------------------------------------------------------------


def read(pmml: ET.Element) -> tuple[DecisionForest, CategoricalValueEncodings]:
    dd = pmmlutils.find(pmml, "DataDictionary")
    if dd is None:
        raise ValueError("PMML has no DataDictionary")
    feature_names = pmml_common.get_feature_names(dd, "DataField")
    encodings = pmml_common.read_data_dictionary_encodings(dd)

    mining_model = _direct_child(pmml, "MiningModel")
    tree_model = _direct_child(pmml, "TreeModel")
    model = mining_model if mining_model is not None else tree_model
    if model is None:
        raise ValueError("PMML has neither MiningModel nor TreeModel")
    ms = pmmlutils.find(model, "MiningSchema")
    target_index = _find_target_index(ms, feature_names)
    if target_index is None:
        raise ValueError("no predicted MiningField")

    if mining_model is not None:
        segmentation = pmmlutils.find(mining_model, "Segmentation")
        method = segmentation.get("multipleModelMethod")
        if method not in ("weightedAverage", "weightedMajorityVote"):
            raise ValueError(f"bad multipleModelMethod: {method}")
        segments = pmmlutils.find_all(segmentation, "Segment")
        if not segments:
            raise ValueError("no segments")
        trees, weights = [], []
        for segment in segments:
            weights.append(float(segment.get("weight", "1")))
            root_el = _root_node(pmmlutils.find(segment, "TreeModel"))
            trees.append(
                DecisionTree(_translate(root_el, encodings, feature_names, target_index))
            )
    else:
        trees = [
            DecisionTree(_translate(_root_node(model), encodings, feature_names, target_index))
        ]
        weights = [1.0]

    importances = np.zeros(len(feature_names))
    for i, field in enumerate(pmmlutils.find_all(ms, "MiningField")):
        imp = field.get("importance")
        if imp is not None:
            importances[i] = float(imp)
    return DecisionForest(trees, weights, importances), encodings


def _direct_child(pmml, tag):
    for el in pmml:
        if el.tag.rsplit("}", 1)[-1] == tag:
            return el
    return None


def _root_node(tree_model):
    for el in tree_model:
        if el.tag.rsplit("}", 1)[-1] == "Node":
            return el
    raise ValueError("TreeModel has no root Node")


def _find_target_index(ms, feature_names):
    for i, field in enumerate(pmmlutils.find_all(ms, "MiningField")):
        if field.get("usageType") == "predicted":
            return feature_names.index(field.get("name"))
    return None


def _children(el, tag):
    return [c for c in el if c.tag.rsplit("}", 1)[-1] == tag]


def _node_predicate(el):
    for c in el:
        tag = c.tag.rsplit("}", 1)[-1]
        if tag in ("True", "False", "SimplePredicate", "SimpleSetPredicate"):
            return tag, c
    return None, None


def _translate(el, encodings, feature_names, target_index):
    """(RDFPMMLUtils.translateFromPMML:176-279)"""
    node_id = el.get("id")
    children = _children(el, "Node")
    if not children:
        dists = _children(el, "ScoreDistribution")
        if dists:
            v2e = encodings.get_value_encoding_map(target_index)
            counts = np.zeros(len(v2e))
            for dist in dists:
                counts[v2e[dist.get("value")]] = float(dist.get("recordCount"))
            prediction = CategoricalPrediction(counts)
        else:
            prediction = NumericPrediction(
                float(el.get("score")), int(round(float(el.get("recordCount", "0"))))
            )
        return TerminalNode(node_id, prediction)

    if len(children) != 2:
        raise ValueError(f"node {node_id} must have exactly 2 children")
    tag1, _ = _node_predicate(children[0])
    if tag1 == "True":
        negative, positive = children[0], children[1]
    else:
        negative, positive = children[1], children[0]
    neg_tag, _ = _node_predicate(negative)
    if neg_tag != "True":
        raise ValueError("one child must carry a True predicate")

    pred_tag, pred = _node_predicate(positive)
    default_decision = positive.get("id") == el.get("defaultChild")

    if pred_tag == "SimplePredicate":
        operator = pred.get("operator")
        if operator not in ("greaterOrEqual", "greaterThan"):
            raise ValueError(f"bad operator: {operator}")
        threshold = float(pred.get("value"))
        if operator == "greaterThan":
            # NumericDecision is >=; implement "> t" as ">= t + ulp"
            threshold = threshold + math.ulp(threshold)
        feature_number = feature_names.index(pred.get("field"))
        decision = NumericDecision(feature_number, threshold, default_decision)
    elif pred_tag == "SimpleSetPredicate":
        operator = pred.get("booleanOperator")
        if operator not in ("isIn", "isNotIn"):
            raise ValueError(f"bad operator: {operator}")
        feature_number = feature_names.index(pred.get("field"))
        v2e = encodings.get_value_encoding_map(feature_number)
        arr = pmmlutils.find(pred, "Array")
        categories = pmmlutils.parse_pmml_delimited(arr.text or "")
        listed = {v2e[c] for c in categories}
        if operator == "isIn":
            active = listed
        else:
            active = set(v2e.values()) - listed
        decision = CategoricalDecision(feature_number, active, default_decision)
    else:
        raise ValueError(f"bad predicate on positive child of {node_id}")

    return DecisionNode(
        node_id,
        decision,
        _translate(negative, encodings, feature_names, target_index),
        _translate(positive, encodings, feature_names, target_index),
    )


# ---------------------------------------------------------------------------
# Validation (RDFPMMLUtils.validatePMMLVsSchema:52-89)
# ---------------------------------------------------------------------------


def validate_pmml_vs_schema(pmml: ET.Element, schema: InputSchema) -> None:
    model = _direct_child(pmml, "MiningModel")
    if model is None:
        model = _direct_child(pmml, "TreeModel")
    if model is None:
        raise ValueError("PMML has neither MiningModel nor TreeModel")
    function = model.get("functionName")
    expected = "classification" if schema.is_classification() else "regression"
    if function != expected:
        raise ValueError(f"expected {expected} function type but got {function}")
    pmml_common.validate_feature_names(pmml, schema, "rdf")
    ms = pmmlutils.find(model, "MiningSchema")
    names = pmml_common.get_feature_names(ms, "MiningField")
    target_index = _find_target_index(ms, names)
    if schema.has_target():
        if target_index is None or target_index != schema.target_feature_index:
            raise ValueError(
                f"schema expects target at index {schema.target_feature_index}, "
                f"PMML has it at {target_index}"
            )
    elif target_index is not None:
        raise ValueError("PMML has a target but schema does not")
