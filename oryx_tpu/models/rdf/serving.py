"""RDF serving tier: in-memory forest behind the REST endpoints.

Equivalent of the reference's RDFServingModel / RDFServingModelManager
(app/oryx-app-serving/.../rdf/model/RDFServingModel.java:34-94,
RDFServingModelManager.java:55-113): the model is a forest + encodings +
schema; ``UP [treeID, nodeID, ...]`` updates one terminal node's prediction
in place (per-class counts for classification, running mean+count for
regression); ``MODEL``/``MODEL-REF`` swaps in a new validated forest.
``predict`` renders the vote as the most probable category value or the
numeric score string.
"""

from __future__ import annotations

import logging

from oryx_tpu.api.serving import AbstractServingModelManager, ServingModel
from oryx_tpu.common import textutils
from oryx_tpu.ml.mlupdate import read_pmml_from_update_key_message
from oryx_tpu.models.classreg import example_from_tokens
from oryx_tpu.models.rdf import pmml_codec
from oryx_tpu.models.rdf.tree import DecisionForest, TerminalNode
from oryx_tpu.models.schema import CategoricalValueEncodings, InputSchema

log = logging.getLogger(__name__)


class RDFServingModel(ServingModel):
    def __init__(
        self,
        forest: DecisionForest,
        encodings: CategoricalValueEncodings,
        input_schema: InputSchema,
    ):
        self.forest = forest
        self.encodings = encodings
        self.input_schema = input_schema

    def make_prediction(self, tokens):
        """Parsed datum → merged forest Prediction (makePrediction:65-70)."""
        if len(tokens) != self.input_schema.num_features:
            raise ValueError("Wrong number of features")
        example = example_from_tokens(tokens, self.input_schema, self.encodings)
        return self.forest.predict(example)

    def predict(self, tokens) -> str:
        """Most-probable category value, or numeric score (predict:52-63)."""
        prediction = self.make_prediction(tokens)
        if self.input_schema.is_classification():
            e2v = self.encodings.get_encoding_value_map(
                self.input_schema.target_feature_index
            )
            return e2v[prediction.most_probable_category_encoding]
        return str(prediction.prediction)

    def get_fraction_loaded(self) -> float:
        return 1.0

    def __repr__(self) -> str:  # pragma: no cover
        return f"RDFServingModel[numTrees:{len(self.forest.trees)}]"


class RDFServingModelManager(AbstractServingModelManager):
    def __init__(self, config):
        super().__init__(config)
        self.input_schema = InputSchema(config)
        self.model: RDFServingModel | None = None

    # -- update-topic consumption (consumeKeyMessage:56-106) -----------------
    def consume_key_message(self, key: str, message: str) -> None:
        if key == "UP":
            model = self.model
            if model is None:
                return  # no model to interpret with yet
            update = textutils.read_json(message)
            tree_id = int(update[0])
            node_id = str(update[1])
            node = model.forest.trees[tree_id].find_by_id(node_id)
            if node is None or not isinstance(node, TerminalNode):
                log.warning("no terminal node %s in tree %d", node_id, tree_id)
                return
            if self.input_schema.is_classification():
                # JSON map keys are always strings
                for encoding, count in update[2].items():
                    node.prediction.update(int(encoding), int(count))
            else:
                node.prediction.update(float(update[2]), int(update[3]))
        elif key in ("MODEL", "MODEL-REF"):
            pmml = read_pmml_from_update_key_message(key, message)
            pmml_codec.validate_pmml_vs_schema(pmml, self.input_schema)
            forest, encodings = pmml_codec.read(pmml)
            self.model = RDFServingModel(forest, encodings, self.input_schema)
            log.info("new model loaded (%d trees)", len(forest.trees))
        else:
            raise ValueError(f"bad key: {key}")

    def get_model(self):
        return self.model
