"""Classification/regression data model: features, examples, predictions.

Equivalent of the reference's classreg package (app/oryx-app-common/.../
classreg/example/{Example,Feature,NumericFeature,CategoricalFeature,
ExampleUtils}.java and classreg/predict/{NumericPrediction,
CategoricalPrediction,WeightedPrediction}.java): a datum line becomes an
``Example`` of typed features plus an optional target; terminal-node
predictions keep online statistics (running weighted mean for numeric
targets, per-category counts for categorical ones) so the speed tier can
update them in place; forest votes merge per-tree predictions weighted by
tree weight.
"""

from __future__ import annotations

import logging
import threading
from dataclasses import dataclass
from typing import Optional, Sequence

import numpy as np

log = logging.getLogger(__name__)

NUMERIC = "N"
CATEGORICAL = "C"


@dataclass(frozen=True)
class NumericFeature:
    """(NumericFeature.java) a real-valued feature."""

    value: float
    feature_type = NUMERIC


@dataclass(frozen=True)
class CategoricalFeature:
    """(CategoricalFeature.java) a categorical feature as its int encoding."""

    encoding: int
    feature_type = CATEGORICAL


Feature = "NumericFeature | CategoricalFeature | None"


class Example:
    """Typed features + optional target (Example.java)."""

    __slots__ = ("features", "target")

    def __init__(self, target, features: Sequence):
        self.target = target
        self.features = tuple(features)

    def get_feature(self, i: int):
        return self.features[i]

    def __repr__(self) -> str:  # pragma: no cover
        return f"Example({self.features} -> {self.target})"


def example_from_tokens(tokens, schema, encodings) -> Example:
    """Tokenized datum → Example (ExampleUtils.dataToExample:41-71).

    The target slot is None when the token is empty (prediction inputs);
    unknown categorical values or bad numbers raise ValueError/KeyError like
    the reference's NumberFormatException path.
    """
    features: "list[Optional[object]]" = [None] * len(tokens)
    target = None
    for i, token in enumerate(tokens):
        feature = None
        is_target = schema.is_target(i)
        if is_target and token == "":
            feature = None
        elif schema.is_numeric(i):
            feature = NumericFeature(float(token))
        elif schema.is_categorical(i):
            feature = CategoricalFeature(
                encodings.get_value_encoding_map(i)[token]
            )
        if is_target:
            target = feature
        else:
            features[i] = feature
    return Example(target, features)


class NumericPrediction:
    """Running weighted mean over a leaf (NumericPrediction.java:30-90)."""

    feature_type = NUMERIC

    def __init__(self, prediction: float, initial_count: int):
        self._lock = threading.Lock()
        self.prediction = float(prediction)
        self.count = int(initial_count)

    def update(self, new_prediction: float, new_count: int = 1) -> None:
        with self._lock:
            new_total = self.count + new_count
            self.count = new_total
            self.prediction += (new_count / new_total) * (
                new_prediction - self.prediction
            )

    def update_example(self, example: Example) -> None:
        self.update(example.target.value, 1)

    def __eq__(self, other) -> bool:
        if not isinstance(other, NumericPrediction):
            return False
        # sequential (never nested) acquisition: no lock-order deadlock
        with self._lock:
            mine = self.prediction
        with other._lock:
            theirs = other.prediction
        return mine == theirs

    def __repr__(self) -> str:  # pragma: no cover
        return f"NumericPrediction({self.prediction}, n={self.count})"


class CategoricalPrediction:
    """Per-category counts, possibly fractional (CategoricalPrediction.java:32-135)."""

    feature_type = CATEGORICAL

    def __init__(self, category_counts: Sequence[float]):
        self._lock = threading.Lock()
        self.category_counts = np.asarray(category_counts, dtype=np.float64).copy()
        if self.category_counts.size == 0:
            raise ValueError("empty category counts")
        self.count = int(round(float(self.category_counts.sum())))

    @property
    def category_probabilities(self) -> np.ndarray:
        # snapshot under the lock: a concurrent update() mutates counts in
        # place, and sum + divide over a moving array skews the distribution
        with self._lock:
            counts = self.category_counts.copy()
        total = float(counts.sum())
        return counts / total

    @property
    def most_probable_category_encoding(self) -> int:
        with self._lock:
            return int(np.argmax(self.category_counts))

    def update(self, encoding: int, count: int = 1) -> None:
        with self._lock:
            self.category_counts[encoding] += count
            self.count += count

    def update_example(self, example: Example) -> None:
        self.update(example.target.encoding, 1)

    def __eq__(self, other) -> bool:
        if not isinstance(other, CategoricalPrediction):
            return False
        # sequential (never nested) acquisition: no lock-order deadlock
        with self._lock:
            mine = self.category_counts.copy()
        with other._lock:
            theirs = other.category_counts.copy()
        return np.array_equal(mine, theirs)

    def __repr__(self) -> str:  # pragma: no cover
        return f"CategoricalPrediction({self.category_counts})"


def vote_on_feature(predictions: Sequence, weights: Sequence[float]):
    """Merge per-tree predictions into one (WeightedPrediction.voteOnFeature:44-95):
    categorical = weight-averaged probability distributions; numeric = weighted
    mean of tree means."""
    if not predictions:
        raise ValueError("No predictions")
    if len(predictions) != len(weights):
        raise ValueError(f"{len(predictions)} predictions but {len(weights)} weights")
    if predictions[0].feature_type == CATEGORICAL:
        w = np.asarray(weights, dtype=np.float64)
        probs = np.stack([p.category_probabilities for p in predictions])
        merged = (probs * w[:, None]).sum(axis=0) / w.sum()
        return CategoricalPrediction(merged)
    w = np.asarray(weights, dtype=np.float64)
    means = np.asarray([p.prediction for p in predictions])
    counts = sum(p.count for p in predictions)
    return NumericPrediction(float((means * w).sum() / w.sum()), counts)
