"""ALS incremental fold-in: the speed/serving update kernel.

Equivalent of the reference's ALSUtils (app/oryx-app-common/.../als/
ALSUtils.java:37-106): given a new interaction (u, i, value), compute the
target estimated strength Qui' (implicit: interpolate between current estimate
and 1/0 by strength; explicit: the new value), then the factor delta
dXu = solve(YtY, dQui·Yi) and Xu += dXu. The same math updates item vectors
from user vectors.

The solve itself is a tiny k×k backsubstitution against the cached Gramian
factorization (ops/solver.py). Aggregated interactions within a microbatch are
independent — each reads the pre-batch X/Y and updates only land when the
layer hears its own UP messages (as in the reference's parallelStream fold,
ALSSpeedModelManager.java:198-220) — so the whole microbatch collapses into
one stacked-RHS batched solve (compute_updated_batch); compute_updated_xu is
the single-interaction form used by serving fold-in.
"""

from __future__ import annotations

import math

import numpy as np

from oryx_tpu.ops.solver import Solver


def compute_target_qui(implicit: bool, value: float, current_value: float) -> float:
    """Target estimated strength, or NaN for 'no change'
    (ALSUtils.computeTargetQui:37-59)."""
    if implicit:
        if value > 0.0 and current_value < 1.0:
            diff = 1.0 - max(0.0, current_value)
            return current_value + (value / (1.0 + value)) * diff
        if value < 0.0 and current_value > 0.0:
            diff = -min(1.0, current_value)
            return current_value + (value / (value - 1.0)) * diff
        return float("nan")
    return value


def compute_updated_xu(
    solver: Solver,
    value: float,
    xu: "np.ndarray | None",
    yi: "np.ndarray | None",
    implicit: bool,
) -> "np.ndarray | None":
    """New user vector, or None for no change (ALSUtils.computeUpdatedXu:75-106)."""
    if yi is None:
        return None
    no_xu = xu is None
    qui = 0.0 if no_xu else float(np.dot(xu, yi))
    # 0.5 reflects a "don't know" state
    target_qui = compute_target_qui(implicit, value, 0.5 if no_xu else qui)
    if math.isnan(target_qui):
        return None
    d_qui = target_qui - qui
    dxu = solver.solve_d_to_d(np.asarray(yi, dtype=np.float64) * d_qui)
    base = np.zeros(len(dxu), dtype=np.float32) if no_xu else np.asarray(xu, dtype=np.float32).copy()
    return base + dxu.astype(np.float32)


def compute_updated_batch(
    solver: Solver,
    values: np.ndarray,  # (B,)
    xus: np.ndarray,  # (B, k) f32, rows meaningless where ~has_xu
    has_xu: np.ndarray,  # (B,) bool
    yis: np.ndarray,  # (B, k) f32, rows meaningless where ~has_yi
    has_yi: np.ndarray,  # (B,) bool
    implicit: bool,
) -> tuple[np.ndarray, np.ndarray]:
    """Vectorized fold-in over a whole microbatch: the B k×k delta solves
    collapse into ONE batched solve (stacked-RHS matmul against the cached
    Gramian factorization), replacing the reference's per-interaction
    parallelStream loop (ALSSpeedModelManager.java:198-220) and the serial
    host loop it mapped to here.

    Aggregated interactions are independent within a microbatch (each reads
    the pre-batch X/Y; updates only land when the layer hears its own UPs),
    so batching preserves the serial path's semantics exactly.

    Returns (new_xu (B, k) float32, changed (B,) bool); rows where changed is
    False are not meaningful."""
    values = np.asarray(values, dtype=np.float64)
    qui = np.einsum("bk,bk->b", xus.astype(np.float32), yis.astype(np.float32))
    qui = np.where(has_xu, qui.astype(np.float64), 0.0)
    current = np.where(has_xu, qui, 0.5)  # 0.5 = "don't know"
    if implicit:
        target = np.full_like(values, np.nan)
        pos = (values > 0.0) & (current < 1.0)
        neg = (values < 0.0) & (current > 0.0)
        with np.errstate(invalid="ignore", divide="ignore"):
            target = np.where(
                pos,
                current + (values / (1.0 + values)) * (1.0 - np.maximum(0.0, current)),
                target,
            )
            target = np.where(
                neg,
                current + (values / (values - 1.0)) * (-np.minimum(1.0, current)),
                target,
            )
    else:
        target = values
    changed = has_yi & ~np.isnan(target)
    d_qui = np.where(changed, target - qui, 0.0)
    rhs = yis.astype(np.float64) * d_qui[:, None]
    dxu = solver.solve(rhs)  # (B, k) in one stacked-RHS solve
    base = np.where(has_xu[:, None], xus, 0.0).astype(np.float32)
    return base + dxu.astype(np.float32), changed
