"""ALS incremental fold-in: the speed/serving update kernel.

Equivalent of the reference's ALSUtils (app/oryx-app-common/.../als/
ALSUtils.java:37-106): given a new interaction (u, i, value), compute the
target estimated strength Qui' (implicit: interpolate between current estimate
and 1/0 by strength; explicit: the new value), then the factor delta
dXu = solve(YtY, dQui·Yi) and Xu += dXu. The same math updates item vectors
from user vectors.

The solve itself is a tiny k×k triangular backsubstitution against the cached
Gramian factorization (ops/solver.py), applied per aggregated interaction in
timestamp order on host — matching the reference's sequential fold semantics
(repeated users see each other's updates within a microbatch).
"""

from __future__ import annotations

import math

import numpy as np

from oryx_tpu.ops.solver import Solver


def compute_target_qui(implicit: bool, value: float, current_value: float) -> float:
    """Target estimated strength, or NaN for 'no change'
    (ALSUtils.computeTargetQui:37-59)."""
    if implicit:
        if value > 0.0 and current_value < 1.0:
            diff = 1.0 - max(0.0, current_value)
            return current_value + (value / (1.0 + value)) * diff
        if value < 0.0 and current_value > 0.0:
            diff = -min(1.0, current_value)
            return current_value + (value / (value - 1.0)) * diff
        return float("nan")
    return value


def compute_updated_xu(
    solver: Solver,
    value: float,
    xu: "np.ndarray | None",
    yi: "np.ndarray | None",
    implicit: bool,
) -> "np.ndarray | None":
    """New user vector, or None for no change (ALSUtils.computeUpdatedXu:75-106)."""
    if yi is None:
        return None
    no_xu = xu is None
    qui = 0.0 if no_xu else float(np.dot(xu, yi))
    # 0.5 reflects a "don't know" state
    target_qui = compute_target_qui(implicit, value, 0.5 if no_xu else qui)
    if math.isnan(target_qui):
        return None
    d_qui = target_qui - qui
    dxu = solver.solve_d_to_d(np.asarray(yi, dtype=np.float64) * d_qui)
    base = np.zeros(len(dxu), dtype=np.float32) if no_xu else np.asarray(xu, dtype=np.float32).copy()
    return base + dxu.astype(np.float32)
