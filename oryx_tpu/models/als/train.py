"""TPU-native ALS training kernel — block-partitioned normal equations.

Replaces Spark MLlib's distributed ALS (behind ALSUpdate.buildModel,
app/oryx-app-mllib/.../als/ALSUpdate.java:108-179) with a jit'd JAX program
designed for the MXU, with *memory-bounded* block solves — the same property
that lets MLlib's block-partitioned ALS (ALSUpdate.java:141-152) train
2M–21M-row models without materializing every per-row Gramian at once:

  * implicit feedback à la Hu/Koren/Volinsky as in MLlib: confidence
    c = 1 + α·|r|, preference p = 1 if r > 0 else 0; explicit = ALS-WR with
    λ·n_u regularization scaling;
  * interactions are sorted by row host-side and split into **row blocks**
    of B rows each; because the COO is row-sorted, each block owns a
    contiguous nnz slice, padded to one uniform length L so every block is
    the same static shape (XLA: one trace, no dynamic shapes);
  * one block solve = scan the block's nnz in fixed-size chunks, gather the
    opposite factors, form weighted outer products, and accumulate into a
    (B+1, k, k) Gramian via a **sorted segment-sum** — peak memory
    O(B·k² + C·k²), never O(n_rows·k²) — then a single batched Cholesky
    (cho_factor/cho_solve over (B, k, k)), the MXU-friendly replacement for
    MLlib's per-block LAPACK calls;
  * under a mesh the **block axis shards over devices** via shard_map: each
    device lax.map's its local blocks with the opposite-side factors
    replicated, and the half-iteration's output factors come back
    row-partitioned (out_specs pins the sharding — XLA inserts the
    all-gather when the next half-iteration needs them replicated). This is
    the classic alternating block layout of distributed ALS.

Interactions must arrive sorted by row (data.build_rating_batch guarantees
it); both row-sorted and column-sorted blocked copies are built once and
reused across iterations.
"""

from __future__ import annotations

import functools
import math
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from oryx_tpu.models.als.data import RatingBatch

DEFAULT_NNZ_CHUNK = 16384

# Budgets (in f32 elements) bounding the two big transients: the per-block
# Gramian carry (B+1, k, k) and the per-chunk outer-product buffer (C, k, k).
_BLOCK_ELEM_BUDGET = 1 << 26  # 256 MB carry
_CHUNK_ELEM_BUDGET = 1 << 24  # 64 MB transient


def _auto_block(features: int) -> int:
    return max(512, min(8192, _BLOCK_ELEM_BUDGET // (features * features)))


def _auto_chunk(features: int) -> int:
    return max(256, min(8192, _CHUNK_ELEM_BUDGET // (features * features)))


@dataclass
class _BlockedSide:
    """Device-ready blocked COO for one half-iteration.

    ``rows`` holds block-LOCAL row indices in [0, block]; ``block`` is the
    spill row (padding), weight-zeroed in the solve. Each block's entries are
    the contiguous row-sorted slice of the global COO that falls in its row
    range, right-padded to the uniform length L (a multiple of chunk).
    """

    rows: jnp.ndarray  # (n_blocks, L) int32
    cols: jnp.ndarray  # (n_blocks, L) int32
    vals: jnp.ndarray  # (n_blocks, L) float32 (0 = padding)
    n_rows: int
    block: int
    n_blocks: int

    @property
    def padded_rows(self) -> int:
        return self.n_blocks * self.block


def make_blocked_side(
    rows: np.ndarray,
    cols: np.ndarray,
    vals: np.ndarray,
    n_rows: int,
    block: int,
    chunk: int,
    n_block_multiple: int = 1,
) -> _BlockedSide:
    """Host-side blocked-COO construction (row-sorted → contiguous slices)."""
    order = np.argsort(rows, kind="stable")
    r = rows[order].astype(np.int64)
    c = cols[order].astype(np.int32)
    v = vals[order].astype(np.float32)
    n_blocks = max(1, -(-n_rows // block))
    n_blocks = -(-n_blocks // n_block_multiple) * n_block_multiple
    bounds = np.searchsorted(r, np.arange(n_blocks + 1, dtype=np.int64) * block)
    lens = np.diff(bounds)
    max_len = int(lens.max()) if len(r) else 0
    length = max(chunk, -(-max(max_len, 1) // chunk) * chunk)
    # Every block pads to the largest block's nnz, so a hot row range inflates
    # memory AND scan work for all blocks. Power-law data can hit this; make
    # the blowup visible rather than silent (a hot SINGLE row cannot be split
    # in this formulation — splitting would need two-level partial-Gramian
    # merging; revisit if real data trips this).
    if len(r) and n_blocks > 1:
        pad_ratio = length * n_blocks / max(1, len(r))
        if pad_ratio > 4.0:
            import logging

            logging.getLogger(__name__).warning(
                "blocked COO padding ratio %.1fx (max block %d nnz vs %.0f "
                "mean): row-skewed data; consider a smaller block size",
                pad_ratio, max_len, len(r) / n_blocks,
            )
    brows = np.full((n_blocks, length), block, dtype=np.int32)
    bcols = np.zeros((n_blocks, length), dtype=np.int32)
    bvals = np.zeros((n_blocks, length), dtype=np.float32)
    for j in range(n_blocks):
        s, e = bounds[j], bounds[j + 1]
        if e > s:
            brows[j, : e - s] = (r[s:e] - j * block).astype(np.int32)
            bcols[j, : e - s] = c[s:e]
            bvals[j, : e - s] = v[s:e]
    return _BlockedSide(
        jnp.asarray(brows), jnp.asarray(bcols), jnp.asarray(bvals),
        n_rows, block, n_blocks,
    )


def _solve_block(y, rows, cols, vals, *, block, features, lam, alpha,
                 implicit, chunk, yty):
    """Solve one row block's factors against fixed column factors ``y``.

    rows: (L,) block-local int32 in [0, block] (block = spill/padding);
    returns (block, k). Peak memory O(block·k² + chunk·k²).
    """
    k = features
    n_chunks = rows.shape[0] // chunk

    def body(carry, i):
        big_a, big_b, cnt = carry
        sl = lambda a: jax.lax.dynamic_slice_in_dim(a, i * chunk, chunk)
        r, c, v = sl(rows), sl(cols), sl(vals)
        yg = y[c]  # (C, k) gather of the replicated opposite side
        if implicit:
            w = alpha * jnp.abs(v)  # confidence - 1
            pref = (v > 0).astype(jnp.float32)
            b_contrib = ((1.0 + w) * pref)[:, None] * yg
        else:
            w = jnp.ones_like(v)  # padding zeroed by pad mask below
            b_contrib = v[:, None] * yg
        pad = (r < block).astype(jnp.float32)
        w = w * pad
        outer = (yg[:, :, None] * yg[:, None, :]) * w[:, None, None]  # (C,k,k)
        seg = functools.partial(
            jax.ops.segment_sum, num_segments=block + 1, indices_are_sorted=True
        )
        big_a = big_a + seg(outer, r)
        big_b = big_b + seg(b_contrib * pad[:, None], r)
        cnt = cnt + seg(pad, r)
        return (big_a, big_b, cnt), None

    init = (
        jnp.zeros((block + 1, k, k), dtype=jnp.float32),
        jnp.zeros((block + 1, k), dtype=jnp.float32),
        jnp.zeros((block + 1,), dtype=jnp.float32),
    )
    (big_a, big_b, cnt), _ = jax.lax.scan(body, init, jnp.arange(n_chunks))
    big_a, big_b, cnt = big_a[:block], big_b[:block], cnt[:block]

    eye = jnp.eye(k, dtype=jnp.float32)
    # ALS-WR regularization scaling by interaction count (MLlib semantics)
    reg = lam * jnp.maximum(cnt, 1.0)
    if implicit:
        big_a = big_a + yty[None, :, :]
    big_a = big_a + reg[:, None, None] * eye[None, :, :]

    chol = jax.scipy.linalg.cholesky(big_a + 1e-6 * eye[None], lower=True)
    x = jax.scipy.linalg.cho_solve((chol, True), big_b[..., None])[..., 0]
    # rows with no interactions have no factor (reference: absent IDs)
    return jnp.where((cnt > 0)[:, None], x, 0.0)


@functools.partial(
    jax.jit, static_argnames=("block", "features", "implicit", "chunk")
)
def solve_side_blocked(y, brows, bcols, bvals, lam, alpha, *, block, features,
                       implicit, chunk):
    """One half-iteration, single device: lax.map over row blocks."""
    yty = (y.T @ y) if implicit else None  # (k,k) Gramian — one MXU matmul

    def one(args):
        r, c, v = args
        return _solve_block(
            y, r, c, v, block=block, features=features, lam=lam, alpha=alpha,
            implicit=implicit, chunk=chunk, yty=yty,
        )

    out = jax.lax.map(one, (brows, bcols, bvals))  # (n_blocks, block, k)
    return out.reshape(-1, features)


@functools.lru_cache(maxsize=64)
def _sharded_solver(mesh, row_axis, block, features, implicit, chunk):
    """jit(shard_map) for one half-iteration: blocks shard over ``row_axis``,
    opposite factors replicated, output factors row-partitioned (pinned by
    out_specs). Cached per (mesh, statics)."""
    from jax.sharding import PartitionSpec as P

    try:
        from jax import shard_map  # jax >= 0.8
    except ImportError:  # pragma: no cover — older jax
        from jax.experimental.shard_map import shard_map

    def local(y, brows, bcols, bvals, lam, alpha):
        yty = (y.T @ y) if implicit else None

        def one(args):
            r, c, v = args
            return _solve_block(
                y, r, c, v, block=block, features=features, lam=lam,
                alpha=alpha, implicit=implicit, chunk=chunk, yty=yty,
            )

        out = jax.lax.map(one, (brows, bcols, bvals))
        return out.reshape(-1, features)

    specs = dict(
        mesh=mesh,
        in_specs=(P(), P(row_axis), P(row_axis), P(row_axis), P(), P()),
        out_specs=P(row_axis),
    )
    # scan carries are block-local, not replicated: disable the varying-axis
    # check (kwarg renamed check_rep -> check_vma in jax 0.8)
    try:
        sm = shard_map(local, check_vma=False, **specs)
    except TypeError:  # pragma: no cover — older jax
        sm = shard_map(local, check_rep=False, **specs)
    return jax.jit(sm)


def als_train(
    batch: RatingBatch,
    features: int,
    lam: float,
    alpha: float,
    implicit: bool,
    iterations: int = 10,
    key=None,
    chunk: int | None = None,
    mesh=None,
    row_axis: str | None = None,
    block: int | None = None,
):
    """Full alternating optimization; returns (X, Y) as jax arrays.

    Single-device (no mesh): returns exact-shape ``(n_users, k)``/
    ``(n_items, k)`` arrays.

    With ``mesh``/``row_axis``: the block axis shards over that mesh axis on
    the way in (device_put) and the way out (shard_map out_specs pins the
    factors row-partitioned), and the returned factors are **padded up to the
    block boundary** (``shape[0] = n_blocks·block ≥ n_rows``, extra rows
    zero) — exact-size uneven shardings are not expressible, and gathering
    to slice would defeat the partitioning. Consumers slice host-side
    (``np.asarray(x)[:n_users]``). ``block``/``chunk`` default to sizes
    bounding device memory at ~256 MB / ~64 MB regardless of n_rows; block
    is chosen per side so a small side is not over-padded.
    """
    from oryx_tpu.common import rand

    n_users, n_items = len(batch.users), len(batch.items)
    k = features
    ndev = 1
    if mesh is not None and row_axis is not None:
        ndev = mesh.shape[row_axis]
    if chunk is None:
        chunk = _auto_chunk(k)
    auto = _auto_block(k) if block is None else block
    # keep every device busy: no point in blocks wider than a device's share
    block_u = max(32, min(auto, -(-n_users // ndev)))
    block_i = max(32, min(auto, -(-n_items // ndev)))

    user_side = make_blocked_side(
        batch.rows, batch.cols, batch.vals, n_users, block_u, chunk, ndev
    )
    item_side = make_blocked_side(
        batch.cols, batch.rows, batch.vals, n_items, block_i, chunk, ndev
    )

    if key is None:
        key = rand.get_key()
    k1, _ = jax.random.split(key)
    y0 = 0.1 * jax.random.normal(k1, (n_items, k), dtype=jnp.float32)
    # padded factor buffers: gathers only ever index real rows (< n_cols),
    # so padding rows are never read
    y = jnp.zeros((item_side.padded_rows, k), dtype=jnp.float32).at[:n_items].set(y0)

    if mesh is not None and row_axis is not None:
        from jax.sharding import NamedSharding, PartitionSpec as P

        row_shard = NamedSharding(mesh, P(row_axis, None))

        def put_side(side):
            return tuple(
                jax.device_put(a, NamedSharding(mesh, P(row_axis, None)))
                for a in (side.rows, side.cols, side.vals)
            )

        u_arrays = put_side(user_side)
        i_arrays = put_side(item_side)
        y = jax.device_put(y, row_shard)
        solve_u = _sharded_solver(mesh, row_axis, block_u, k, implicit, chunk)
        solve_i = _sharded_solver(mesh, row_axis, block_i, k, implicit, chunk)
        x = None
        for _ in range(iterations):
            x = solve_u(y, *u_arrays, lam, alpha)
            y = solve_i(x, *i_arrays, lam, alpha)
        return x, y

    x = None
    for _ in range(iterations):
        x = solve_side_blocked(
            y, user_side.rows, user_side.cols, user_side.vals, lam, alpha,
            block=block_u, features=k, implicit=implicit, chunk=chunk,
        )
        y = solve_side_blocked(
            x, item_side.rows, item_side.cols, item_side.vals, lam, alpha,
            block=block_i, features=k, implicit=implicit, chunk=chunk,
        )
    return x[:n_users], y[:n_items]
