"""TPU-native ALS training kernel — slot-padded block normal equations.

Replaces Spark MLlib's distributed ALS (behind ALSUpdate.buildModel,
app/oryx-app-mllib/.../als/ALSUpdate.java:108-179) with a jit'd JAX program
designed for the MXU, with *memory-bounded* block solves — the same property
that lets MLlib's block-partitioned ALS (ALSUpdate.java:141-152) train
2M–21M-row models without materializing every per-row Gramian at once:

  * implicit feedback à la Hu/Koren/Volinsky as in MLlib: confidence
    c = 1 + α·|r|, preference p = 1 if r > 0 else 0; explicit = ALS-WR with
    λ·n_u regularization scaling;
  * interactions are sorted by row host-side and packed into fixed-width
    **slots** of T entries each: a row with d interactions occupies
    ceil(d/T) slots (Gramians are additive, so a hot row simply spans more
    slots — no global padding blow-up from skew). Slots are grouped into
    **row blocks** of B rows, padded to one uniform slot count S per block
    (XLA: one trace, static shapes);
  * one block solve = scan the block's slots in fixed-size chunks, gather
    the opposite factors (Sc, T, k), and form per-slot Gramians with ONE
    batched matmul — einsum('st,sti,stj->sij') → (Sc, k, k) — which is the
    MXU-shaped formulation (contraction over the slot width T). Slots then
    merge into per-row Gramians via a short sorted segment-sum over at most
    Sc indices (k²-granularity scatter traffic is slots·k², ~mean-degree×
    less than the naive nnz·k² outer-product scatter). Peak memory stays
    O(B·k² + Sc·T·k); a single batched Cholesky (cho_factor/cho_solve over
    (B, k, k)) replaces MLlib's per-block LAPACK calls;
  * under a mesh the **block axis shards over devices** via shard_map: each
    device lax.map's its local blocks with the opposite-side factors
    replicated, and the half-iteration's output factors come back
    row-partitioned (out_specs pins the sharding — XLA inserts the
    all-gather when the next half-iteration needs them replicated). This is
    the classic alternating block layout of distributed ALS.

Interactions must arrive sorted by row (data.build_rating_batch guarantees
it); both row-sorted and column-sorted slotted copies are built once and
reused across iterations.
"""

from __future__ import annotations

import functools
import math
import os
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from oryx_tpu.models.als.data import RatingBatch

# Budgets (in f32 elements) bounding the two big transients: the per-block
# Gramian carry (B+1, k, k) and the per-chunk gather/Gramian buffers
# (Sc, T, k) + (Sc, k, k).
_BLOCK_ELEM_BUDGET = 1 << 26  # 256 MB carry
_CHUNK_ELEM_BUDGET = 1 << 24  # 64 MB transient


def _auto_block(features: int) -> int:
    return max(512, min(8192, _BLOCK_ELEM_BUDGET // (features * features)))


def _auto_slot_chunk(features: int, slot_width: int) -> int:
    per_slot = max(slot_width * features, features * features)
    return max(64, min(8192, _CHUNK_ELEM_BUDGET // per_slot))


def _auto_slot_width(nnz: int, n_nonempty_rows: int) -> int:
    """Slot width T ≈ mean row degree, as a power of two in [8, 512]."""
    mean = nnz / max(1, n_nonempty_rows)
    t = 1 << max(0, math.ceil(math.log2(max(1.0, mean))))
    return max(8, min(512, t))


@dataclass
class _BlockedSide:
    """Device-ready slotted COO for one half-iteration.

    ``srows`` holds block-LOCAL row indices in [0, block]; ``block`` is the
    spill row (slot padding), length-zeroed in the solve. Each block's slots
    are the contiguous row-sorted run of the global slot list that falls in
    its row range, right-padded to the uniform count S (a multiple of the
    scan chunk).
    """

    srows: jnp.ndarray  # (n_blocks, S) int32, pad = block
    scols: jnp.ndarray  # (n_blocks, S, T) int32
    svals: jnp.ndarray  # (n_blocks, S, T) float32
    slens: jnp.ndarray  # (n_blocks, S) int32 valid entries per slot (0 = pad)
    n_rows: int
    block: int
    n_blocks: int
    slot_width: int
    slot_chunk: int

    @property
    def padded_rows(self) -> int:
        return self.n_blocks * self.block


def _pack_workers(workers: "int | None", nnz: int) -> int:
    """Worker count for the host-side pack scatters: explicit wins; small
    packs stay serial (thread fan-out costs more than it saves below ~2M
    entries); big packs use up to 8 host cores."""
    if workers is not None:
        return max(1, workers)
    if nnz < 2_000_000:
        return 1
    return max(1, min(8, os.cpu_count() or 1))


def _chunked_scatter(fn, n: int, workers: int, chunk: int = 1_000_000) -> None:
    """Run ``fn(lo, hi)`` over [0, n) — serially, or chunked across a thread
    pool. Callers guarantee every (lo, hi) slice writes DISJOINT output
    cells, so chunk boundaries need no coordination; numpy's fancy-index
    assignment releases the GIL for flat dtypes, which is what makes the
    threads actually overlap."""
    if workers <= 1 or n <= chunk:
        fn(0, n)
        return
    import concurrent.futures as cf

    step = max(chunk, -(-n // (workers * 4)))  # ~4 chunks per worker
    with cf.ThreadPoolExecutor(workers) as pool:
        futs = [
            pool.submit(fn, lo, min(n, lo + step)) for lo in range(0, n, step)
        ]
        for f in futs:
            f.result()


def make_blocked_side(
    rows: np.ndarray,
    cols: np.ndarray,
    vals: np.ndarray,
    n_rows: int,
    block: int,
    slot_chunk: int | None,
    slot_width: int | None,
    n_block_multiple: int = 1,
    features: int | None = None,
    workers: int | None = None,
) -> _BlockedSide:
    """Host-side slotted-COO construction (row-sorted → contiguous slots).

    ``slot_width=None`` picks T from the side's mean row degree (one degree
    histogram, reused for the slot layout); ``slot_chunk=None`` then sizes
    the scan chunk from T and ``features`` to stay inside the transient
    budget. Entries scatter STRAIGHT into the preallocated (n_blocks, S, T)
    output slabs — no intermediate flat slot arrays — and the scatter is
    chunked over a thread pool (``workers``; every entry owns a distinct
    cell, so chunks are embarrassingly parallel)."""
    # sort by (row, col): row-major for contiguous slots, column-ascending
    # within each row so the per-slot gathers of the opposite factors walk
    # HBM in address order instead of randomly. One stable argsort on a
    # fused int64 key is ~2x numpy's lexsort at 10M nnz (radix path), and
    # int64 cannot overflow at any plausible row/col cardinality
    if len(rows):
        span = np.int64(cols.max()) + 1
        key = rows.astype(np.int64) * span + cols
        order = np.argsort(key, kind="stable")
    else:
        order = np.arange(0)
    r = rows[order].astype(np.int64)
    c = cols[order].astype(np.int32)
    v = vals[order].astype(np.float32)
    n_blocks = max(1, -(-n_rows // block))
    n_blocks = -(-n_blocks // n_block_multiple) * n_block_multiple
    padded_rows = n_blocks * block
    n_workers = _pack_workers(workers, len(r))

    deg = np.bincount(r, minlength=padded_rows) if len(r) else np.zeros(
        padded_rows, dtype=np.int64
    )
    if slot_width is None:
        slot_width = _auto_slot_width(len(r), int(np.count_nonzero(deg)))
    t = slot_width
    budget_max = _auto_slot_chunk(features or 32, t)
    # explicit values are still clamped into the transient budget: a chunk
    # tuned in nnz terms (each slot is T entries wide) must not OOM the device
    slot_chunk = budget_max if slot_chunk is None else max(
        16, min(slot_chunk, budget_max)
    )
    nslots_row = -(-deg // t)  # ceil; 0 slots for empty rows
    row_slot_start = np.zeros(padded_rows + 1, dtype=np.int64)
    np.cumsum(nslots_row, out=row_slot_start[1:])
    row_entry_start = np.zeros(padded_rows + 1, dtype=np.int64)
    np.cumsum(deg, out=row_entry_start[1:])
    total_slots = int(row_slot_start[-1])

    # slots are row-ordered, so block b's slots are exactly the run
    # row_slot_start[b*block : (b+1)*block] — per-block extents come
    # straight off the cumsum, no searchsorted
    bounds = row_slot_start[::block]  # (n_blocks + 1,)
    max_s = int(np.diff(bounds).max()) if total_slots else 0
    # fewest scan steps that fit the transient budget, with the chunk sized
    # to divide S exactly: sequential chunk steps are the TPU's enemy, and a
    # budget-sized chunk that doesn't divide S would pad S up to a multiple
    n_chunks = max(1, -(-max(max_s, 1) // slot_chunk))
    slot_chunk = max(16, -(-max(max_s, 1) // n_chunks))
    s_len = n_chunks * slot_chunk

    # Slot packing bounds skew damage (a hot row just spans more slots), but
    # uneven *block* slot counts still pad every block to the fullest one;
    # surface a pathological ratio rather than hiding it.
    if len(r) and n_blocks > 1:
        pad_ratio = s_len * t * n_blocks / max(1, len(r))
        if pad_ratio > 6.0:
            import logging

            logging.getLogger(__name__).warning(
                "slotted COO padding ratio %.1fx (T=%d, S=%d x %d blocks vs "
                "%d nnz): row-skewed data; consider a smaller block size",
                pad_ratio, t, s_len, n_blocks, len(r),
            )

    srows = np.full((n_blocks, s_len), block, dtype=np.int32)
    scols = np.zeros((n_blocks, s_len, t), dtype=np.int32)
    svals = np.zeros((n_blocks, s_len, t), dtype=np.float32)
    slens = np.zeros((n_blocks, s_len), dtype=np.int32)
    if total_slots:
        # per-slot coordinates: owning row, block, and index within block
        srow_f = np.repeat(np.arange(padded_rows, dtype=np.int64), nslots_row)
        sb = (srow_f // block).astype(np.int32)
        sidx = (np.arange(total_slots, dtype=np.int64) - bounds[sb]).astype(np.int32)
        # valid entries per slot straight from the degree histogram: a row's
        # slots carry T, T, ..., remainder — no per-entry bincount needed
        slot_in_row = np.arange(total_slots, dtype=np.int64) - row_slot_start[srow_f]
        srows[sb, sidx] = (srow_f % block).astype(np.int32)
        slens[sb, sidx] = np.minimum(
            deg[srow_f] - slot_in_row * t, t
        ).astype(np.int32)
        del slot_in_row
        if len(r):
            # per-entry final coordinates — each entry owns one distinct
            # (block, slot, pos) cell in the preallocated slabs, so the
            # scatter chunks cleanly across the worker pool. Index dtypes
            # are downcast and intermediates freed eagerly: at 10M nnz the
            # int64 versions alone would add hundreds of MB of transient,
            # and the reference-scale memory bound (test_als_scale) holds
            # the whole train under a hard rlimit
            p = np.arange(len(r), dtype=np.int64) - row_entry_start[r]
            slot = row_slot_start[r] + p // t
            pos = (p % t).astype(np.int32)
            del p
            eb = (r // block).astype(np.int32)
            es = (slot - bounds[eb]).astype(np.int32)
            del slot

            def scatter(lo: int, hi: int) -> None:
                scols[eb[lo:hi], es[lo:hi], pos[lo:hi]] = c[lo:hi]
                svals[eb[lo:hi], es[lo:hi], pos[lo:hi]] = v[lo:hi]

            _chunked_scatter(scatter, len(r), n_workers)
            del eb, es, pos
    return _BlockedSide(
        jnp.asarray(srows), jnp.asarray(scols), jnp.asarray(svals),
        jnp.asarray(slens), n_rows, block, n_blocks, t, slot_chunk,
    )


def _solve_block(y, srow, scols, svals, slens, *, block, features, lam, alpha,
                 implicit, slot_chunk, yty, compute_dtype=jnp.float32,
                 spd_kernel=False):
    """Solve one row block's factors against fixed column factors ``y``.

    srow: (S,) block-local int32 in [0, block] (block = spill/padding);
    scols/svals: (S, T); returns (block, k). Peak memory
    O(block·k² + slot_chunk·T·k). ``y`` may arrive pre-cast to
    ``compute_dtype`` (bfloat16 = MXU-native inputs, half the gather
    bandwidth); Gramian/RHS accumulation stays float32 via
    preferred_element_type, and the Cholesky solve is always float32.
    """
    k = features
    t = scols.shape[-1]
    n_chunks = srow.shape[0] // slot_chunk

    def body(carry, i):
        big_a, big_b, cnt = carry
        sl = lambda a: jax.lax.dynamic_slice_in_dim(a, i * slot_chunk, slot_chunk)
        rs, ls = sl(srow), sl(slens)
        cs, vs = sl(scols), sl(svals)
        m = (jnp.arange(t)[None, :] < ls[:, None]).astype(jnp.float32)  # (Sc,T)
        yg = y[cs]  # (Sc, T, k) gather of the replicated opposite side
        if implicit:
            w = alpha * jnp.abs(vs) * m  # confidence - 1
            coef = (1.0 + w) * (vs > 0).astype(jnp.float32) * m
        else:
            w = m
            coef = vs * m
        # per-slot Gramian: ONE batched MXU matmul, contraction over T
        ga = jnp.einsum(
            "st,sti,stj->sij", w.astype(compute_dtype), yg, yg,
            preferred_element_type=jnp.float32,
        )  # (Sc, k, k)
        gb = jnp.einsum(
            "st,sti->si", coef.astype(compute_dtype), yg,
            preferred_element_type=jnp.float32,
        )  # (Sc, k)
        seg = functools.partial(
            jax.ops.segment_sum, num_segments=block + 1, indices_are_sorted=True
        )
        big_a = big_a + seg(ga, rs)
        big_b = big_b + seg(gb, rs)
        cnt = cnt + seg(m.sum(-1), rs)
        return (big_a, big_b, cnt), None

    init = (
        jnp.zeros((block + 1, k, k), dtype=jnp.float32),
        jnp.zeros((block + 1, k), dtype=jnp.float32),
        jnp.zeros((block + 1,), dtype=jnp.float32),
    )
    # the chunk count is small by construction (fewest chunks within the
    # transient budget); fully unrolling short scans drops the while-loop
    # carry double-buffering of the (block+1, k, k) Gramian accumulator
    (big_a, big_b, cnt), _ = jax.lax.scan(
        body, init, jnp.arange(n_chunks), unroll=min(n_chunks, 4)
    )
    big_a, big_b, cnt = big_a[:block], big_b[:block], cnt[:block]

    eye = jnp.eye(k, dtype=jnp.float32)
    # ALS-WR regularization scaling by interaction count (MLlib semantics)
    reg = lam * jnp.maximum(cnt, 1.0)
    if implicit:
        big_a = big_a + yty[None, :, :]
    big_a = big_a + reg[:, None, None] * eye[None, :, :]

    big_a = big_a + 1e-6 * eye[None]
    if spd_kernel:
        # Pallas Gauss-Jordan: k elimination steps against VMEM instead of
        # XLA cholesky's ~3k full-operand HBM passes (see pallas_kernels).
        # interpret=None: compiled on TPU, emulated elsewhere — which is
        # what lets the CPU suite test this exact path (test_als.py)
        from oryx_tpu.ops.pallas_kernels import spd_solve_batched

        x = spd_solve_batched(big_a, big_b)
    else:
        chol = jax.scipy.linalg.cholesky(big_a, lower=True)
        x = jax.scipy.linalg.cho_solve((chol, True), big_b[..., None])[..., 0]
    # rows with no interactions have no factor (reference: absent IDs)
    return jnp.where((cnt > 0)[:, None], x, 0.0)


@functools.partial(
    jax.jit,
    static_argnames=(
        "block", "features", "implicit", "slot_chunk", "dtype", "spd_kernel",
    ),
)
def _solve_side_blocked_jit(y, srows, scols, svals, slens, lam, alpha, *,
                            block, features, implicit, slot_chunk, dtype,
                            spd_kernel):
    yty = (y.T @ y) if implicit else None  # (k,k) Gramian — one MXU matmul
    cd = jnp.dtype(dtype)
    ys = y.astype(cd) if cd != y.dtype else y  # one cast, gathered per chunk

    def one(args):
        r, c, v, ln = args
        return _solve_block(
            ys, r, c, v, ln, block=block, features=features, lam=lam,
            alpha=alpha, implicit=implicit, slot_chunk=slot_chunk, yty=yty,
            compute_dtype=cd, spd_kernel=spd_kernel,
        )

    out = jax.lax.map(one, (srows, scols, svals, slens))  # (n_blocks, block, k)
    return out.reshape(-1, features)


def _use_spd_kernel(y=None, mesh=None) -> bool:
    """True when the solve will actually run on TPU. Decided from the target
    devices (the mesh's, or the operand's), NOT ``jax.default_backend()``:
    under the axon site hook the process default can say "tpu" while the
    computation is pinned to the forced-host CPU platform (and vice versa
    after ``jax.config.update("jax_platforms", ...)``)."""
    if mesh is not None:
        return mesh.devices.flat[0].platform == "tpu"
    if y is not None and hasattr(y, "devices"):
        try:
            return next(iter(y.devices())).platform == "tpu"
        except Exception:  # noqa: BLE001 — tracers etc.: fall through
            pass
    return jax.default_backend() == "tpu"


def solve_side_blocked(y, srows, scols, svals, slens, lam, alpha, *, block,
                       features, implicit, slot_chunk, dtype="float32",
                       spd_kernel: "bool | None" = None):
    """One half-iteration, single device: lax.map over row blocks.

    ``spd_kernel=None`` picks the Pallas Gauss-Jordan solver on TPU and the
    LAPACK-backed cholesky path elsewhere (jit decisions are static, so the
    backend is resolved here at call time)."""
    if spd_kernel is None:
        spd_kernel = _use_spd_kernel(y=y)
    return _solve_side_blocked_jit(
        y, srows, scols, svals, slens, lam, alpha, block=block,
        features=features, implicit=implicit, slot_chunk=slot_chunk,
        dtype=dtype, spd_kernel=bool(spd_kernel),
    )


@functools.lru_cache(maxsize=64)
def _sharded_solver(mesh, row_axis, block, features, implicit, slot_chunk,
                    dtype="float32", spd_kernel=False):
    """jit(shard_map) for one half-iteration: blocks shard over ``row_axis``,
    opposite factors replicated, output factors row-partitioned (pinned by
    out_specs). Cached per (mesh, statics)."""
    from jax.sharding import PartitionSpec as P

    try:
        from jax import shard_map  # jax >= 0.8
    except ImportError:  # pragma: no cover — older jax
        from jax.experimental.shard_map import shard_map

    cd = jnp.dtype(dtype)

    def local(y, srows, scols, svals, slens, lam, alpha):
        yty = (y.T @ y) if implicit else None
        ys = y.astype(cd) if cd != y.dtype else y

        def one(args):
            r, c, v, ln = args
            return _solve_block(
                ys, r, c, v, ln, block=block, features=features, lam=lam,
                alpha=alpha, implicit=implicit, slot_chunk=slot_chunk, yty=yty,
                compute_dtype=cd, spd_kernel=spd_kernel,
            )

        out = jax.lax.map(one, (srows, scols, svals, slens))
        return out.reshape(-1, features)

    specs = dict(
        mesh=mesh,
        in_specs=(P(), P(row_axis), P(row_axis), P(row_axis), P(row_axis),
                  P(), P()),
        out_specs=P(row_axis),
    )
    # scan carries are block-local, not replicated: disable the varying-axis
    # check (kwarg renamed check_rep -> check_vma in jax 0.8)
    try:
        sm = shard_map(local, check_vma=False, **specs)
    except TypeError:  # pragma: no cover — older jax
        sm = shard_map(local, check_rep=False, **specs)
    return jax.jit(sm)


def prepare_blocked(
    batch: RatingBatch,
    features: int,
    ndev: int = 1,
    block: int | None = None,
    chunk: int | None = None,
    slot_width: int | None = None,
    workers: int | None = None,
) -> tuple[_BlockedSide, _BlockedSide]:
    """Pack both half-iteration sides with production block/chunk sizing.

    The single setup path shared by :func:`als_train` and the training
    benchmark, so published throughput always measures the same layout
    production uses. The two sides pack CONCURRENTLY on big inputs (the
    dominant costs — the fused-key argsort, gathers, bincounts, and the
    slab scatters — all release the GIL), on top of each side's own
    chunked scatter pool; ``workers`` caps both (None = auto, 1 = serial)."""
    n_users, n_items = len(batch.users), len(batch.items)
    auto = _auto_block(features) if block is None else block

    def even_block(n_rows: int) -> int:
        # divide rows EVENLY across the block count the budget implies (and
        # keep every device busy): a block of exactly `auto` would leave the
        # last block nearly empty while every block pads to the fullest
        # one's slot count
        n_blocks = max(1, -(-n_rows // max(32, min(auto, -(-n_rows // ndev)))))
        n_blocks = -(-n_blocks // ndev) * ndev
        return max(32, -(-n_rows // n_blocks))

    block_u = even_block(n_users)
    block_i = even_block(n_items)

    def pack_user() -> _BlockedSide:
        return make_blocked_side(
            batch.rows, batch.cols, batch.vals, n_users, block_u, chunk,
            slot_width, ndev, features=features, workers=workers,
        )

    def pack_item() -> _BlockedSide:
        return make_blocked_side(
            batch.cols, batch.rows, batch.vals, n_items, block_i, chunk,
            slot_width, ndev, features=features, workers=workers,
        )

    if _pack_workers(workers, len(batch.rows)) > 1:
        import concurrent.futures as cf

        with cf.ThreadPoolExecutor(2) as pool:
            fu, fi = pool.submit(pack_user), pool.submit(pack_item)
            return fu.result(), fi.result()
    return pack_user(), pack_item()


def init_item_factors(item_side: _BlockedSide, n_items: int, features: int,
                      key) -> jnp.ndarray:
    """Random Y₀ in the padded factor buffer (gathers only ever index real
    rows < n_items, so padding rows are never read)."""
    k1, _ = jax.random.split(key)
    y0 = 0.1 * jax.random.normal(k1, (n_items, features), dtype=jnp.float32)
    return jnp.zeros(
        (item_side.padded_rows, features), dtype=jnp.float32
    ).at[:n_items].set(y0)


def als_train(
    batch: RatingBatch,
    features: int,
    lam: float,
    alpha: float,
    implicit: bool,
    iterations: int = 10,
    key=None,
    chunk: int | None = None,
    mesh=None,
    row_axis: str | None = None,
    block: int | None = None,
    slot_width: int | None = None,
    dtype: str = "float32",
):
    """Full alternating optimization; returns (X, Y) as jax arrays.

    ``dtype`` sets the Gramian-matmul INPUT precision ("bfloat16" = MXU
    native; accumulation and solves stay float32 regardless).

    Single-device (no mesh): returns exact-shape ``(n_users, k)``/
    ``(n_items, k)`` arrays.

    With ``mesh``/``row_axis``: the block axis shards over that mesh axis on
    the way in (device_put) and the way out (shard_map out_specs pins the
    factors row-partitioned), and the returned factors are **padded up to the
    block boundary** (``shape[0] = n_blocks·block ≥ n_rows``, extra rows
    zero) — exact-size uneven shardings are not expressible, and gathering
    to slice would defeat the partitioning. Consumers slice host-side
    (``np.asarray(x)[:n_users]``). ``block``/``chunk`` default to sizes
    bounding device memory at ~256 MB / ~64 MB regardless of n_rows; block
    is chosen per side so a small side is not over-padded; the slot width T
    defaults to the side's mean row degree (power of two in [8, 512]).
    ``chunk`` counts SLOTS per scan step (each T entries wide), not nnz, and
    explicit values are clamped into the transient budget.
    """
    from oryx_tpu.common import rand

    if iterations < 1:
        raise ValueError(f"iterations must be >= 1, got {iterations}")
    if dtype not in ("float32", "bfloat16"):
        # fail fast at the API boundary: a typo ("bf16") would otherwise
        # surface deep inside a jitted solve, and a low-precision numpy
        # dtype ("float16", "int8") would run and silently degrade factors
        raise ValueError(
            f"compute dtype must be 'float32' or 'bfloat16', got {dtype!r}"
        )

    n_users, n_items = len(batch.users), len(batch.items)
    k = features
    ndev = 1
    if mesh is not None and row_axis is not None:
        ndev = mesh.shape[row_axis]
    user_side, item_side = prepare_blocked(
        batch, k, ndev, block=block, chunk=chunk, slot_width=slot_width
    )
    block_u, block_i = user_side.block, item_side.block
    chunk_u, chunk_i = user_side.slot_chunk, item_side.slot_chunk

    if key is None:
        key = rand.get_key()
    y = init_item_factors(item_side, n_items, k, key)

    if mesh is not None and row_axis is not None:
        from jax.sharding import NamedSharding, PartitionSpec as P

        row_shard = NamedSharding(mesh, P(row_axis, None))

        def put_side(side):
            return tuple(
                jax.device_put(a, NamedSharding(mesh, P(row_axis, *([None] * (a.ndim - 1)))))
                for a in (side.srows, side.scols, side.svals, side.slens)
            )

        u_arrays = put_side(user_side)
        i_arrays = put_side(item_side)
        y = jax.device_put(y, row_shard)
        use_spd = _use_spd_kernel(mesh=mesh)
        solve_u = _sharded_solver(mesh, row_axis, block_u, k, implicit,
                                  chunk_u, dtype, use_spd)
        solve_i = _sharded_solver(mesh, row_axis, block_i, k, implicit,
                                  chunk_i, dtype, use_spd)
        x = None
        for _ in range(iterations):
            x = solve_u(y, *u_arrays, lam, alpha)
            y = solve_i(x, *i_arrays, lam, alpha)
        return x, y

    x = None
    for _ in range(iterations):
        x = solve_side_blocked(
            y, user_side.srows, user_side.scols, user_side.svals,
            user_side.slens, lam, alpha,
            block=block_u, features=k, implicit=implicit, slot_chunk=chunk_u,
            dtype=dtype,
        )
        y = solve_side_blocked(
            x, item_side.srows, item_side.scols, item_side.svals,
            item_side.slens, lam, alpha,
            block=block_i, features=k, implicit=implicit, slot_chunk=chunk_i,
            dtype=dtype,
        )
    return x[:n_users], y[:n_items]
