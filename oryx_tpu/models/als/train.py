"""TPU-native ALS training kernel — slot-padded block normal equations.

Replaces Spark MLlib's distributed ALS (behind ALSUpdate.buildModel,
app/oryx-app-mllib/.../als/ALSUpdate.java:108-179) with a jit'd JAX program
designed for the MXU, with *memory-bounded* block solves — the same property
that lets MLlib's block-partitioned ALS (ALSUpdate.java:141-152) train
2M–21M-row models without materializing every per-row Gramian at once:

  * implicit feedback à la Hu/Koren/Volinsky as in MLlib: confidence
    c = 1 + α·|r|, preference p = 1 if r > 0 else 0; explicit = ALS-WR with
    λ·n_u regularization scaling;
  * interactions are sorted by row host-side and packed into fixed-width
    **slots** of T entries each: a row with d interactions occupies
    ceil(d/T) slots (Gramians are additive, so a hot row simply spans more
    slots — no global padding blow-up from skew). Slots are grouped into
    **row blocks** of B rows, padded to one uniform slot count S per block
    (XLA: one trace, static shapes);
  * one block solve = scan the block's slots in fixed-size chunks, gather
    the opposite factors (Sc, T, k), and form per-slot Gramians with ONE
    batched matmul — einsum('st,sti,stj->sij') → (Sc, k, k) — which is the
    MXU-shaped formulation (contraction over the slot width T). Slots then
    merge into per-row Gramians via a short sorted segment-sum over at most
    Sc indices (k²-granularity scatter traffic is slots·k², ~mean-degree×
    less than the naive nnz·k² outer-product scatter). Peak memory stays
    O(B·k² + Sc·T·k); a single batched Cholesky (cho_factor/cho_solve over
    (B, k, k)) replaces MLlib's per-block LAPACK calls;
  * under a mesh the **block axis shards over devices** via shard_map: each
    device lax.map's its local blocks with the opposite-side factors
    replicated, and the half-iteration's output factors come back
    row-partitioned (out_specs pins the sharding — XLA inserts the
    all-gather when the next half-iteration needs them replicated). This is
    the classic alternating block layout of distributed ALS.

Interactions must arrive sorted by row (data.build_rating_batch guarantees
it); both row-sorted and column-sorted slotted copies are built once and
reused across iterations.
"""

from __future__ import annotations

import functools
import math
import os
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from oryx_tpu.common import profiling
from oryx_tpu.models.als.data import RatingBatch

# Budgets (in f32 elements) bounding the two big transients: the per-block
# Gramian carry (B+1, k, k) and the per-chunk gather/Gramian buffers
# (Sc, T, k) + (Sc, k, k).
_BLOCK_ELEM_BUDGET = 1 << 26  # 256 MB carry
_CHUNK_ELEM_BUDGET = 1 << 24  # 64 MB transient


def _auto_block(features: int) -> int:
    return max(512, min(8192, _BLOCK_ELEM_BUDGET // (features * features)))


def _auto_slot_chunk(features: int, slot_width: int) -> int:
    per_slot = max(slot_width * features, features * features)
    return max(64, min(8192, _CHUNK_ELEM_BUDGET // per_slot))


def _auto_slot_width(nnz: int, n_nonempty_rows: int) -> int:
    """Slot width T ≈ mean row degree, as a power of two in [8, 512]."""
    mean = nnz / max(1, n_nonempty_rows)
    t = 1 << max(0, math.ceil(math.log2(max(1.0, mean))))
    return max(8, min(512, t))


@dataclass
class _BlockedSide:
    """Device-ready slotted COO for one half-iteration.

    ``srows`` holds block-LOCAL row indices in [0, block]; ``block`` is the
    spill row (slot padding), length-zeroed in the solve. Each block's slots
    are the contiguous row-sorted run of the global slot list that falls in
    its row range, right-padded to the uniform count S (a multiple of the
    scan chunk).
    """

    srows: jnp.ndarray  # (n_blocks, S) int32, pad = block
    scols: jnp.ndarray  # (n_blocks, S, T) int32
    svals: jnp.ndarray  # (n_blocks, S, T) float32
    slens: jnp.ndarray  # (n_blocks, S) int32 valid entries per slot (0 = pad)
    n_rows: int
    block: int
    n_blocks: int
    slot_width: int
    slot_chunk: int
    # host masters (srows, scols, svals, slens as numpy), kept only when a
    # BlockedLayoutCache owns the side so the next generation can repack an
    # incremental delta instead of the whole batch. Never mutated in place:
    # the delta path copies before writing (jnp.asarray may alias on CPU).
    np_slabs: "tuple | None" = None

    @property
    def padded_rows(self) -> int:
        return self.n_blocks * self.block


def _pack_workers(workers: "int | None", nnz: int) -> int:
    """Worker count for the host-side pack scatters: explicit wins; small
    packs stay serial (thread fan-out costs more than it saves below ~2M
    entries); big packs use up to 8 host cores."""
    if workers is not None:
        return max(1, workers)
    if nnz < 2_000_000:
        return 1
    return max(1, min(8, os.cpu_count() or 1))


def _chunked_scatter(fn, n: int, workers: int, chunk: int = 1_000_000) -> None:
    """Run ``fn(lo, hi)`` over [0, n) — serially, or chunked across a thread
    pool. Callers guarantee every (lo, hi) slice writes DISJOINT output
    cells, so chunk boundaries need no coordination; numpy's fancy-index
    assignment releases the GIL for flat dtypes, which is what makes the
    threads actually overlap."""
    if workers <= 1 or n <= chunk:
        fn(0, n)
        return
    import concurrent.futures as cf

    step = max(chunk, -(-n // (workers * 4)))  # ~4 chunks per worker
    with cf.ThreadPoolExecutor(workers) as pool:
        futs = [
            pool.submit(fn, lo, min(n, lo + step)) for lo in range(0, n, step)
        ]
        for f in futs:
            f.result()


def _padded_rows_for(n_rows: int, block: int, n_block_multiple: int = 1) -> int:
    """Rows after block padding — EXACTLY make_blocked_side's computation,
    callable before (or without) the pack so the first factor buffer can be
    allocated while the side is still packing on the host pool."""
    n_blocks = max(1, -(-n_rows // block))
    n_blocks = -(-n_blocks // n_block_multiple) * n_block_multiple
    return n_blocks * block


def _layout_params(deg: np.ndarray, nnz: int, slot_chunk: "int | None",
                   slot_width: "int | None", block: int,
                   features: "int | None") -> tuple:
    """Slot-layout shape parameters from a degree histogram: the pure
    function both the full pack and the incremental delta derive their
    geometry from (so a delta repack can detect any drift and the two paths
    can never disagree on shapes)."""
    if slot_width is None:
        slot_width = _auto_slot_width(nnz, int(np.count_nonzero(deg)))
    t = slot_width
    budget_max = _auto_slot_chunk(features or 32, t)
    slot_chunk = budget_max if slot_chunk is None else max(
        16, min(slot_chunk, budget_max)
    )
    nslots_row = -(-deg // t)  # ceil; 0 slots for empty rows
    padded_rows = len(deg)
    row_slot_start = np.zeros(padded_rows + 1, dtype=np.int64)
    np.cumsum(nslots_row, out=row_slot_start[1:])
    total_slots = int(row_slot_start[-1])
    bounds = row_slot_start[::block]  # (n_blocks + 1,)
    max_s = int(np.diff(bounds).max()) if total_slots else 0
    n_chunks = max(1, -(-max(max_s, 1) // slot_chunk))
    slot_chunk = max(16, -(-max(max_s, 1) // n_chunks))
    s_len = n_chunks * slot_chunk
    return t, slot_chunk, s_len, nslots_row, row_slot_start, bounds, total_slots


def make_blocked_side(
    rows: np.ndarray,
    cols: np.ndarray,
    vals: np.ndarray,
    n_rows: int,
    block: int,
    slot_chunk: int | None,
    slot_width: int | None,
    n_block_multiple: int = 1,
    features: int | None = None,
    workers: int | None = None,
    keep_np: bool = False,
) -> _BlockedSide:
    """Host-side slotted-COO construction (row-sorted → contiguous slots).

    ``slot_width=None`` picks T from the side's mean row degree (one degree
    histogram, reused for the slot layout); ``slot_chunk=None`` then sizes
    the scan chunk from T and ``features`` to stay inside the transient
    budget. Entries scatter STRAIGHT into the preallocated (n_blocks, S, T)
    output slabs — no intermediate flat slot arrays — and the scatter is
    chunked over a thread pool (``workers``; every entry owns a distinct
    cell, so chunks are embarrassingly parallel)."""
    # sort by (row, col): row-major for contiguous slots, column-ascending
    # within each row so the per-slot gathers of the opposite factors walk
    # HBM in address order instead of randomly. One stable argsort on a
    # fused int64 key is ~2x numpy's lexsort at 10M nnz (radix path), and
    # int64 cannot overflow at any plausible row/col cardinality
    if len(rows):
        span = np.int64(cols.max()) + 1
        key = rows.astype(np.int64) * span + cols
        order = np.argsort(key, kind="stable")
    else:
        order = np.arange(0)
    r = rows[order].astype(np.int64)
    c = cols[order].astype(np.int32)
    v = vals[order].astype(np.float32)
    padded_rows = _padded_rows_for(n_rows, block, n_block_multiple)
    n_blocks = padded_rows // block
    n_workers = _pack_workers(workers, len(r))

    deg = np.bincount(r, minlength=padded_rows) if len(r) else np.zeros(
        padded_rows, dtype=np.int64
    )
    # explicit slot_chunk values are still clamped into the transient
    # budget (a chunk tuned in nnz terms must not OOM the device), and the
    # chunk is sized to divide S exactly: sequential chunk steps are the
    # TPU's enemy, and a budget-sized chunk that doesn't divide S would pad
    # S up to a multiple. Slots are row-ordered, so block b's slots are
    # exactly the run row_slot_start[b*block : (b+1)*block] — per-block
    # extents come straight off the cumsum, no searchsorted.
    (t, slot_chunk, s_len, nslots_row, row_slot_start, bounds,
     total_slots) = _layout_params(deg, len(r), slot_chunk, slot_width,
                                   block, features)
    row_entry_start = np.zeros(padded_rows + 1, dtype=np.int64)
    np.cumsum(deg, out=row_entry_start[1:])

    # Slot packing bounds skew damage (a hot row just spans more slots), but
    # uneven *block* slot counts still pad every block to the fullest one;
    # surface a pathological ratio rather than hiding it.
    if len(r) and n_blocks > 1:
        pad_ratio = s_len * t * n_blocks / max(1, len(r))
        if pad_ratio > 6.0:
            import logging

            logging.getLogger(__name__).warning(
                "slotted COO padding ratio %.1fx (T=%d, S=%d x %d blocks vs "
                "%d nnz): row-skewed data; consider a smaller block size",
                pad_ratio, t, s_len, n_blocks, len(r),
            )

    srows = np.full((n_blocks, s_len), block, dtype=np.int32)
    scols = np.zeros((n_blocks, s_len, t), dtype=np.int32)
    svals = np.zeros((n_blocks, s_len, t), dtype=np.float32)
    slens = np.zeros((n_blocks, s_len), dtype=np.int32)
    if total_slots:
        # per-slot coordinates: owning row, block, and index within block
        srow_f = np.repeat(np.arange(padded_rows, dtype=np.int64), nslots_row)
        sb = (srow_f // block).astype(np.int32)
        sidx = (np.arange(total_slots, dtype=np.int64) - bounds[sb]).astype(np.int32)
        # valid entries per slot straight from the degree histogram: a row's
        # slots carry T, T, ..., remainder — no per-entry bincount needed
        slot_in_row = np.arange(total_slots, dtype=np.int64) - row_slot_start[srow_f]
        srows[sb, sidx] = (srow_f % block).astype(np.int32)
        slens[sb, sidx] = np.minimum(
            deg[srow_f] - slot_in_row * t, t
        ).astype(np.int32)
        del slot_in_row
        if len(r):
            # per-entry final coordinates — each entry owns one distinct
            # (block, slot, pos) cell in the preallocated slabs, so the
            # scatter chunks cleanly across the worker pool. Index dtypes
            # are downcast and intermediates freed eagerly: at 10M nnz the
            # int64 versions alone would add hundreds of MB of transient,
            # and the reference-scale memory bound (test_als_scale) holds
            # the whole train under a hard rlimit
            p = np.arange(len(r), dtype=np.int64) - row_entry_start[r]
            slot = row_slot_start[r] + p // t
            pos = (p % t).astype(np.int32)
            del p
            eb = (r // block).astype(np.int32)
            es = (slot - bounds[eb]).astype(np.int32)
            del slot

            def scatter(lo: int, hi: int) -> None:
                scols[eb[lo:hi], es[lo:hi], pos[lo:hi]] = c[lo:hi]
                svals[eb[lo:hi], es[lo:hi], pos[lo:hi]] = v[lo:hi]

            _chunked_scatter(scatter, len(r), n_workers)
            del eb, es, pos
    return _BlockedSide(
        jnp.asarray(srows), jnp.asarray(scols), jnp.asarray(svals),
        jnp.asarray(slens), n_rows, block, n_blocks, t, slot_chunk,
        np_slabs=(srows, scols, svals, slens) if keep_np else None,
    )


def _entry_weights(svals, slens, alpha, implicit, t):
    """Per-entry Gramian weight ``w`` and RHS coefficient ``coef`` (both
    masked to the slot's valid length): the confidence algebra of
    Hu/Koren/Volinsky implicit feedback, or plain masking for explicit.
    Shared by the einsum formulation and the fused Pallas kernel so the two
    paths can only ever differ in accumulation order."""
    m = (jnp.arange(t)[None, :] < slens[..., None]).astype(jnp.float32)
    if implicit:
        w = alpha * jnp.abs(svals) * m  # confidence - 1
        coef = (1.0 + w) * (svals > 0).astype(jnp.float32) * m
    else:
        w = m
        coef = svals * m
    return w, coef


def _delta_blocked_side(
    old: _BlockedSide,
    rows: np.ndarray,
    cols: np.ndarray,
    vals: np.ndarray,
    n_rows: int,
    block: int,
    slot_chunk: "int | None",
    slot_width: "int | None",
    n_block_multiple: int,
    features: "int | None",
    appended_rows: np.ndarray,
) -> "_BlockedSide | None":
    """Incremental repack: ``rows/cols/vals`` extend the cached side's
    batch by entries touching ``appended_rows`` (wherever they sit in the
    arrays — mid-array for the production row-sorted pipeline, the tail
    for a raw concatenation). Only the BLOCKS those rows live in re-sort
    and re-scatter; every other block's slabs copy through unchanged
    (their within-block slot layout depends only on their own rows'
    degrees). Returns None when the layout geometry drifted — block count,
    slot width, chunk, or a shrunk S — and a full pack is required. The
    result is bit-identical to a from-scratch pack of the full batch: the
    global sort is stable on the (row, col) key, and an affected block's
    entries keep their original relative order whether sorted globally or
    alone."""
    if old.np_slabs is None:
        return None
    padded_rows = _padded_rows_for(n_rows, block, n_block_multiple)
    n_blocks = padded_rows // block
    if n_blocks != old.n_blocks or block != old.block:
        return None
    deg = np.bincount(rows.astype(np.int64), minlength=padded_rows)
    (t, chunk, s_len, nslots_row, row_slot_start, bounds,
     total_slots) = _layout_params(deg, len(rows), slot_chunk, slot_width,
                                   block, features)
    old_s = old.np_slabs[0].shape[1]
    if t != old.slot_width or s_len < old_s:
        return None

    affected = np.unique(appended_rows // block).astype(np.int64)
    o_srows, o_scols, o_svals, o_slens = old.np_slabs
    pad_s = s_len - old_s
    if pad_s:
        # S grew: right-pad every block with empty slots — exactly the fill
        # a full pack leaves there (owner = spill row, zeros elsewhere)
        srows = np.full((n_blocks, s_len), block, dtype=np.int32)
        srows[:, :old_s] = o_srows
        scols = np.zeros((n_blocks, s_len, t), dtype=np.int32)
        scols[:, :old_s] = o_scols
        svals = np.zeros((n_blocks, s_len, t), dtype=np.float32)
        svals[:, :old_s] = o_svals
        slens = np.zeros((n_blocks, s_len), dtype=np.int32)
        slens[:, :old_s] = o_slens
    else:
        srows, scols = o_srows.copy(), o_scols.copy()
        svals, slens = o_svals.copy(), o_slens.copy()

    # re-derive the affected blocks from scratch: all of their entries (old
    # + appended) re-sort and re-scatter — the stable (row, col) sort of a
    # block's own entries is independent of every other block's
    srows[affected] = block
    scols[affected] = 0
    svals[affected] = 0
    slens[affected] = 0
    sel = np.flatnonzero(np.isin(rows // block, affected))
    if len(sel):
        r_all, c_all, v_all = rows[sel], cols[sel], vals[sel]
        span = np.int64(c_all.max()) + 1
        order = np.argsort(r_all.astype(np.int64) * span + c_all,
                           kind="stable")
        rr = r_all[order].astype(np.int64)
        cc = c_all[order].astype(np.int32)
        vv = v_all[order].astype(np.float32)
        # rank of each entry within its (col-sorted) row group: sel holds
        # every entry of each affected block, so group ranks equal the full
        # pack's per-row entry positions
        p = _slot_rank(rr)
        slot = row_slot_start[rr] + p // t
        pos = (p % t).astype(np.int32)
        eb = (rr // block).astype(np.int32)
        es = (slot - bounds[eb]).astype(np.int32)
        scols[eb, es, pos] = cc
        svals[eb, es, pos] = vv
        # per-slot owner rows + valid lengths for the affected rows
        arows = np.unique(rr)
        srow_f = np.repeat(arows, nslots_row[arows])
        sb = (srow_f // block).astype(np.int32)
        slot_in_row = _slot_rank(srow_f)
        sidx = (row_slot_start[srow_f]
                + slot_in_row - bounds[sb]).astype(np.int32)
        srows[sb, sidx] = (srow_f % block).astype(np.int32)
        slens[sb, sidx] = np.minimum(
            deg[srow_f] - slot_in_row * t, t
        ).astype(np.int32)
    return _BlockedSide(
        jnp.asarray(srows), jnp.asarray(scols), jnp.asarray(svals),
        jnp.asarray(slens), n_rows, block, n_blocks, t, chunk,
        np_slabs=(srows, scols, svals, slens),
    )


def _slot_rank(srow_f: np.ndarray) -> np.ndarray:
    """Rank of each element within its contiguous run of equal values
    (0, 1, ... per run) — per-row slot ranks when fed owner-rows-per-slot,
    per-row entry ranks when fed row-sorted entry rows."""
    grp = np.flatnonzero(np.r_[True, srow_f[1:] != srow_f[:-1]])
    return np.arange(len(srow_f), dtype=np.int64) - np.repeat(
        grp, np.diff(np.r_[grp, len(srow_f)])
    )


class BlockedLayoutCache:
    """Slotted-layout reuse across model generations (one per trainer).

    Successive batch-tier generations mostly extend the previous batch:
    the 58 s host pack at 1M×50f re-sorts and re-scatters entries whose
    layout has not moved. This cache keys on the previous generation's COO
    arrays per side and picks the cheapest correct path:

      * ``reused`` — arrays identical: hand back the SAME device-ready side
        (zero host work, zero re-upload);
      * ``delta`` — the new arrays extend the old (exact prefix, OR the
        production shape: row-sorted with each row's old entries a prefix
        of its new ones — what ``build_rating_batch``'s stable row sort
        over the insertion-ordered aggregation dict emits) AND the layout
        geometry held: only the blocks the appended entries touch re-sort
        and re-scatter (:func:`_delta_blocked_side`);
      * ``full`` — anything else (changed historical values — new events
        aggregated into an existing pair, or time decay rewriting
        strengths — a new id sorting mid-order and renumbering an axis
        (``IDIndexMapping`` sorts ids, so monotonic id schemes keep the
        mapping stable and delta-friendly), different geometry, shrunk
        batch): full pack.

    Results are bit-identical to a from-scratch pack in every mode (the
    delta path's per-block stable sort reproduces the global one), which
    ``tests/test_gramian_kernel.py`` pins. Cost: between generations the
    cache retains the previous COO triple and host slab copies (~nnz·9 B
    plus ~2·nnz·8 B/fill) AND pins the cached ``_BlockedSide``'s DEVICE
    slabs — several hundred MB of HBM at 10M nnz, transiently ~2× during
    a delta while old and new device slabs coexist. That device residency
    is what makes ``reused`` a zero-re-upload path; size HBM headroom for
    it, and drop the cache object to reclaim everything. Not thread-safe;
    the batch tier packs one generation at a time."""

    def __init__(self):
        self._arrays: "tuple | None" = None  # canonical (rows, cols, vals)
        self._sides: dict = {}  # name -> (side, params)
        self.last_modes: dict = {}

    def match_extension(self, rows, cols, vals) -> "np.ndarray | None":
        """Indices (into the new arrays) of the entries APPENDED since the
        cached generation, or None when the new batch does not extend it.

        Two shapes match. (1) Exact prefix — the new arrays literally start
        with the old ones (how a raw log append looks). (2) Row-wise
        extension — both generations row-sorted with each row's old entries
        forming a prefix of its new entries, which is exactly what the
        production pipeline produces: ``build_rating_batch`` stable-sorts
        by row, and the aggregation dict keeps first-seen (user, item)
        pairs ahead of newly seen ones within every row. A pair whose
        VALUE changed (new events aggregated in, or time decay rewriting
        history) fails the compare and falls back to a full pack.

        One check against the CANONICAL batch triple covers both sides —
        the item side's swapped (cols, rows, vals) view extends iff the
        batch does (membership is per-entry, not per-ordering)."""
        if self._arrays is None:
            return None
        o_r, o_c, o_v = self._arrays
        n_old = len(o_r)
        if len(rows) < n_old:
            return None
        if (np.array_equal(o_r, rows[:n_old])
                and np.array_equal(o_c, cols[:n_old])
                and np.array_equal(o_v, vals[:n_old])):
            return np.arange(n_old, len(rows), dtype=np.int64)
        if n_old == 0 or np.any(np.diff(rows) < 0) or np.any(np.diff(o_r) < 0):
            return None
        nr = int(max(rows[-1], o_r[-1])) + 1
        deg_new = np.bincount(rows, minlength=nr)
        deg_old = np.bincount(o_r, minlength=nr)
        if np.any(deg_old > deg_new):
            return None
        new_start = np.zeros(nr + 1, dtype=np.int64)
        np.cumsum(deg_new, out=new_start[1:])
        old_start = np.zeros(nr + 1, dtype=np.int64)
        np.cumsum(deg_old, out=old_start[1:])
        # position of each old entry inside the new arrays: its row's new
        # segment start plus its rank within the row (rows agree by
        # construction once the degree test passed)
        idx = new_start[o_r] + (np.arange(n_old, dtype=np.int64)
                                - old_start[o_r])
        if not (np.array_equal(cols[idx], o_c)
                and np.array_equal(vals[idx], o_v)):
            return None
        appended = np.ones(len(rows), dtype=bool)
        appended[idx] = False
        return np.flatnonzero(appended)

    def side(self, name: str, rows, cols, vals, n_rows, block, slot_chunk,
             slot_width, n_block_multiple=1, features=None, workers=None,
             appended_idx: "np.ndarray | None" = None) -> _BlockedSide:
        """Pack one side, reusing the cached layout when ``appended_idx``
        (from :meth:`match_extension`) says the arrays extend the cached
        batch. ``rows`` is THIS side's row view, so ``rows[appended_idx]``
        are the rows the appended entries touch on this side."""
        params = (block, slot_chunk, slot_width, n_block_multiple, features)
        cached = self._sides.get(name)
        old, old_params = cached if cached is not None else (None, None)
        if old is not None and old_params == params \
                and appended_idx is not None:
            if appended_idx.size == 0 and old.n_rows == n_rows:
                self.last_modes[name] = "reused"
                return old
            side = _delta_blocked_side(
                old, rows, cols, vals, n_rows, block, slot_chunk,
                slot_width, n_block_multiple, features,
                rows[appended_idx],
            )
            if side is not None:
                self.last_modes[name] = "delta"
                self._sides[name] = (side, params)
                return side
        side = make_blocked_side(
            rows, cols, vals, n_rows, block, slot_chunk, slot_width,
            n_block_multiple, features=features, workers=workers,
            keep_np=True,
        )
        self.last_modes[name] = "full"
        self._sides[name] = (side, params)
        return side

    def store_batch(self, rows, cols, vals) -> None:
        """Pin the generation's canonical arrays AFTER both sides packed
        (the two sides share one COO, so the prefix test must see one
        snapshot). COPIES, not references: a caller that mutates its batch
        arrays in place (time decay rewriting ``vals``) and trains again
        would otherwise have ``match_extension`` compare the cached triple
        against itself and silently reuse pre-mutation slabs."""
        self._arrays = (rows.copy(), cols.copy(), vals.copy())


def _solve_block(y, srow, scols, svals, slens, *, block, features, lam, alpha,
                 implicit, slot_chunk, yty, compute_dtype=jnp.float32,
                 spd_kernel=False, fused_gramian=False, kernel_interpret):
    """Solve one row block's factors against fixed column factors ``y``.

    srow: (S,) block-local int32 in [0, block] (block = spill/padding);
    scols/svals: (S, T); returns (block, k). Peak memory
    O(block·k² + slot_chunk·T·k). ``y`` may arrive pre-cast to
    ``compute_dtype`` (bfloat16 = MXU-native inputs, half the gather
    bandwidth); Gramian/RHS accumulation stays float32 via
    preferred_element_type, and the Cholesky solve is always float32.

    ``fused_gramian`` routes the whole accumulation through the Pallas
    gather-Gramian kernel: factor rows gather tile-by-tile into VMEM and
    contract in place, accumulating straight into the per-row output —
    skipping both the (Sc, T, k) HBM gather materialization and the
    segment-sum pass below. ``kernel_interpret`` carries the CALLER's
    device-platform decision into every Pallas kernel here (compiled on
    TPU, emulated elsewhere — the same flag, so a forced-platform hook can
    never run one kernel compiled and the other silently interpreted).
    """
    k = features
    t = scols.shape[-1]

    if fused_gramian:
        from oryx_tpu.ops.pallas_kernels import gather_gramian_accumulate

        w, coef = _entry_weights(svals, slens, alpha, implicit, t)
        big_a, big_b = gather_gramian_accumulate(
            y, srow, scols, w, coef, slens, block=block,
            interpret=kernel_interpret,
        )
        # interaction counts are k²-free — a plain (S,) segment-sum costs
        # nothing next to the Gramians and keeps the kernel surface small
        cnt = jax.ops.segment_sum(
            slens.astype(jnp.float32), srow, num_segments=block + 1,
            indices_are_sorted=True,
        )
    else:
        n_chunks = srow.shape[0] // slot_chunk

        def body(carry, i):
            big_a, big_b, cnt = carry
            sl = lambda a: jax.lax.dynamic_slice_in_dim(
                a, i * slot_chunk, slot_chunk
            )
            rs, ls = sl(srow), sl(slens)
            cs, vs = sl(scols), sl(svals)
            w, coef = _entry_weights(vs, ls, alpha, implicit, t)
            yg = y[cs]  # (Sc, T, k) gather of the replicated opposite side
            # per-slot Gramian: ONE batched MXU matmul, contraction over T
            ga = jnp.einsum(
                "st,sti,stj->sij", w.astype(compute_dtype), yg, yg,
                preferred_element_type=jnp.float32,
            )  # (Sc, k, k)
            gb = jnp.einsum(
                "st,sti->si", coef.astype(compute_dtype), yg,
                preferred_element_type=jnp.float32,
            )  # (Sc, k)
            seg = functools.partial(
                jax.ops.segment_sum, num_segments=block + 1,
                indices_are_sorted=True,
            )
            big_a = big_a + seg(ga, rs)
            big_b = big_b + seg(gb, rs)
            cnt = cnt + seg(ls.astype(jnp.float32), rs)
            return (big_a, big_b, cnt), None

        init = (
            jnp.zeros((block + 1, k, k), dtype=jnp.float32),
            jnp.zeros((block + 1, k), dtype=jnp.float32),
            jnp.zeros((block + 1,), dtype=jnp.float32),
        )
        # the chunk count is small by construction (fewest chunks within the
        # transient budget); fully unrolling short scans drops the while-loop
        # carry double-buffering of the (block+1, k, k) Gramian accumulator
        (big_a, big_b, cnt), _ = jax.lax.scan(
            body, init, jnp.arange(n_chunks), unroll=min(n_chunks, 4)
        )
    big_a, big_b, cnt = big_a[:block], big_b[:block], cnt[:block]

    eye = jnp.eye(k, dtype=jnp.float32)
    # ALS-WR regularization scaling by interaction count (MLlib semantics)
    reg = lam * jnp.maximum(cnt, 1.0)
    if implicit:
        big_a = big_a + yty[None, :, :]
    big_a = big_a + reg[:, None, None] * eye[None, :, :]

    big_a = big_a + 1e-6 * eye[None]
    if spd_kernel:
        # Pallas Gauss-Jordan: k elimination steps against VMEM instead of
        # XLA cholesky's ~3k full-operand HBM passes (see pallas_kernels)
        from oryx_tpu.ops.pallas_kernels import spd_solve_batched

        x = spd_solve_batched(big_a, big_b, interpret=kernel_interpret)
    else:
        chol = jax.scipy.linalg.cholesky(big_a, lower=True)
        x = jax.scipy.linalg.cho_solve((chol, True), big_b[..., None])[..., 0]
    # rows with no interactions have no factor (reference: absent IDs)
    return jnp.where((cnt > 0)[:, None], x, 0.0)


@functools.partial(
    jax.jit,
    static_argnames=(
        "block", "features", "implicit", "slot_chunk", "dtype", "spd_kernel",
        "fused_gramian", "kernel_interpret",
    ),
)
def _solve_side_blocked_jit(y, srows, scols, svals, slens, lam, alpha, *,
                            block, features, implicit, slot_chunk, dtype,
                            spd_kernel, fused_gramian, kernel_interpret):
    yty = (y.T @ y) if implicit else None  # (k,k) Gramian — one MXU matmul
    cd = jnp.dtype(dtype)
    ys = y.astype(cd) if cd != y.dtype else y  # one cast, gathered per chunk

    def one(args):
        r, c, v, ln = args
        return _solve_block(
            ys, r, c, v, ln, block=block, features=features, lam=lam,
            alpha=alpha, implicit=implicit, slot_chunk=slot_chunk, yty=yty,
            compute_dtype=cd, spd_kernel=spd_kernel,
            fused_gramian=fused_gramian, kernel_interpret=kernel_interpret,
        )

    out = jax.lax.map(one, (srows, scols, svals, slens))  # (n_blocks, block, k)
    return out.reshape(-1, features)


def _resolve_fused(fused_gramian: "bool | None", on_tpu: bool,
                   features: int) -> bool:
    """One gate for every path that selects the fused gather-Gramian kernel
    (single-device, mesh, benches): None = platform default; an explicit
    True past the kernel's VMEM feature gate downgrades LOUDLY to the
    einsum formulation instead of failing to compile on chip."""
    from oryx_tpu.ops.pallas_kernels import gather_gramian_supported

    want = on_tpu if fused_gramian is None else bool(fused_gramian)
    if want and not gather_gramian_supported(features):
        if fused_gramian:
            import logging

            logging.getLogger(__name__).warning(
                "fused_gramian requested but features=%d exceeds the "
                "kernel's VMEM gate; using the einsum formulation", features,
            )
        return False
    return want


def _use_spd_kernel(y=None, mesh=None) -> bool:
    """True when the solve will actually run on TPU. Decided from the target
    devices (the mesh's, or the operand's), NOT ``jax.default_backend()``:
    under the axon site hook the process default can say "tpu" while the
    computation is pinned to the forced-host CPU platform (and vice versa
    after ``jax.config.update("jax_platforms", ...)``)."""
    if mesh is not None:
        return mesh.devices.flat[0].platform == "tpu"
    if y is not None and hasattr(y, "devices"):
        try:
            return next(iter(y.devices())).platform == "tpu"
        except Exception:  # noqa: BLE001 — tracers etc.: fall through
            pass
    return jax.default_backend() == "tpu"


def solve_side_blocked(y, srows, scols, svals, slens, lam, alpha, *, block,
                       features, implicit, slot_chunk, dtype="float32",
                       spd_kernel: "bool | None" = None,
                       fused_gramian: "bool | None" = None):
    """One half-iteration, single device: lax.map over row blocks.

    ``spd_kernel=None`` / ``fused_gramian=None`` pick the Pallas kernels
    (Gauss-Jordan solve; fused gather-Gramian accumulation) on TPU and the
    XLA formulations elsewhere (jit decisions are static, so the backend is
    resolved here at call time). The SAME device-platform decision also
    sets the kernels' interpret mode: a caller that forces a kernel on
    (tests) gets it emulated off-TPU, and a forced-platform hook that
    flips ``jax.default_backend()`` after the operands were placed can
    never silently run a kernel in interpret mode on the chip — the
    ADVICE r5 ``spd_solve_batched`` default-interpret mismatch."""
    on_tpu = _use_spd_kernel(y=y)
    if spd_kernel is None:
        spd_kernel = on_tpu
    fused_gramian = _resolve_fused(fused_gramian, on_tpu, features)
    return _solve_side_blocked_jit(
        y, srows, scols, svals, slens, lam, alpha, block=block,
        features=features, implicit=implicit, slot_chunk=slot_chunk,
        dtype=dtype, spd_kernel=bool(spd_kernel),
        fused_gramian=bool(fused_gramian), kernel_interpret=not on_tpu,
    )


@functools.lru_cache(maxsize=64)
def _sharded_solver(mesh, row_axis, block, features, implicit, slot_chunk,
                    dtype="float32", spd_kernel=False, fused_gramian=False,
                    kernel_interpret=None):
    """jit(shard_map) for one half-iteration: blocks shard over ``row_axis``,
    opposite factors replicated, output factors row-partitioned (pinned by
    out_specs). Cached per (mesh, statics). ``kernel_interpret=None``
    resolves from the MESH's target devices — a caller that forgets the
    flag must never silently emulate the Pallas kernels on chip (the
    kernel-interpret-default class; every production caller passes it)."""
    if kernel_interpret is None:
        kernel_interpret = not _use_spd_kernel(mesh=mesh)
    from jax.sharding import PartitionSpec as P

    try:
        from jax import shard_map  # jax >= 0.8
    except ImportError:  # pragma: no cover — older jax
        from jax.experimental.shard_map import shard_map

    cd = jnp.dtype(dtype)

    def local(y, srows, scols, svals, slens, lam, alpha):
        yty = (y.T @ y) if implicit else None
        ys = y.astype(cd) if cd != y.dtype else y

        def one(args):
            r, c, v, ln = args
            return _solve_block(
                ys, r, c, v, ln, block=block, features=features, lam=lam,
                alpha=alpha, implicit=implicit, slot_chunk=slot_chunk, yty=yty,
                compute_dtype=cd, spd_kernel=spd_kernel,
                fused_gramian=fused_gramian, kernel_interpret=kernel_interpret,
            )

        out = jax.lax.map(one, (srows, scols, svals, slens))
        return out.reshape(-1, features)

    # in_specs[0] = P(): the full opposite factor y replicates into every
    # half-iteration (~N·k·4 B all-gathered per call) — the known ROADMAP
    # item-5(a) scaling bug, flagged by the replicated-collective checker
    # and accepted in conf/analyze-baseline.json until the routed-mesh fix
    # (ship only the factor rows each block needs) lands
    specs = dict(
        mesh=mesh,
        in_specs=(P(), P(row_axis), P(row_axis), P(row_axis), P(row_axis),
                  P(), P()),
        out_specs=P(row_axis),
    )
    # scan carries are block-local, not replicated: disable the varying-axis
    # check (kwarg renamed check_rep -> check_vma in jax 0.8)
    try:
        sm = shard_map(local, check_vma=False, **specs)
    except TypeError:  # pragma: no cover — older jax
        sm = shard_map(local, check_rep=False, **specs)
    return jax.jit(sm)


def _even_block(n_rows: int, features: int, ndev: int,
                block: "int | None") -> int:
    """Divide rows EVENLY across the block count the budget implies (and
    keep every device busy): a block of exactly the budget's auto size
    would leave the last block nearly empty while every block pads to the
    fullest one's slot count."""
    auto = _auto_block(features) if block is None else block
    n_blocks = max(1, -(-n_rows // max(32, min(auto, -(-n_rows // ndev)))))
    n_blocks = -(-n_blocks // ndev) * ndev
    return max(32, -(-n_rows // n_blocks))


def _side_packers(batch: RatingBatch, features: int, ndev: int, block_u: int,
                  block_i: int, chunk, slot_width, workers,
                  cache: "BlockedLayoutCache | None"):
    """(pack_user, pack_item) closures sharing one extension-match decision
    — computed HERE, before either thread starts, so concurrent side packs
    never race the cache's array comparison."""
    n_users, n_items = len(batch.users), len(batch.items)
    appended = cache.match_extension(batch.rows, batch.cols, batch.vals) \
        if cache is not None else None

    def pack_user() -> _BlockedSide:
        if cache is not None:
            return cache.side(
                "user", batch.rows, batch.cols, batch.vals, n_users, block_u,
                chunk, slot_width, ndev, features=features, workers=workers,
                appended_idx=appended,
            )
        return make_blocked_side(
            batch.rows, batch.cols, batch.vals, n_users, block_u, chunk,
            slot_width, ndev, features=features, workers=workers,
        )

    def pack_item() -> _BlockedSide:
        if cache is not None:
            return cache.side(
                "item", batch.cols, batch.rows, batch.vals, n_items, block_i,
                chunk, slot_width, ndev, features=features, workers=workers,
                appended_idx=appended,
            )
        return make_blocked_side(
            batch.cols, batch.rows, batch.vals, n_items, block_i, chunk,
            slot_width, ndev, features=features, workers=workers,
        )

    return pack_user, pack_item


def prepare_blocked(
    batch: RatingBatch,
    features: int,
    ndev: int = 1,
    block: int | None = None,
    chunk: int | None = None,
    slot_width: int | None = None,
    workers: int | None = None,
    cache: "BlockedLayoutCache | None" = None,
) -> tuple[_BlockedSide, _BlockedSide]:
    """Pack both half-iteration sides with production block/chunk sizing.

    The single setup path shared by :func:`als_train` and the training
    benchmark, so published throughput always measures the same layout
    production uses. The two sides pack CONCURRENTLY on big inputs (the
    dominant costs — the fused-key argsort, gathers, bincounts, and the
    slab scatters — all release the GIL), on top of each side's own
    chunked scatter pool; ``workers`` caps both (None = auto, 1 = serial).
    ``cache`` (a :class:`BlockedLayoutCache`) turns a repeated or appended
    generation's pack into a reuse or an incremental delta."""
    block_u = _even_block(len(batch.users), features, ndev, block)
    block_i = _even_block(len(batch.items), features, ndev, block)
    pack_user, pack_item = _side_packers(
        batch, features, ndev, block_u, block_i, chunk, slot_width, workers,
        cache,
    )
    if _pack_workers(workers, len(batch.rows)) > 1:
        import concurrent.futures as cf

        with cf.ThreadPoolExecutor(2) as pool:
            fu, fi = pool.submit(pack_user), pool.submit(pack_item)
            sides = fu.result(), fi.result()
    else:
        sides = pack_user(), pack_item()
    if cache is not None:
        cache.store_batch(batch.rows, batch.cols, batch.vals)
    return sides


def _init_factors(padded_rows: int, n_rows: int, features: int,
                  key) -> jnp.ndarray:
    k1, _ = jax.random.split(key)
    y0 = 0.1 * jax.random.normal(k1, (n_rows, features), dtype=jnp.float32)
    return jnp.zeros(
        (padded_rows, features), dtype=jnp.float32
    ).at[:n_rows].set(y0)


def init_item_factors(item_side: _BlockedSide, n_items: int, features: int,
                      key) -> jnp.ndarray:
    """Random Y₀ in the padded factor buffer (gathers only ever index real
    rows < n_items, so padding rows are never read)."""
    return _init_factors(item_side.padded_rows, n_items, features, key)


def _register_half_cost(key: str, side: _BlockedSide, nnz: int,
                        features: int, dtype: str) -> None:
    """Analytic per-half-iteration device cost for the trainer's cost
    accounting (common/profiling.py): the same useful-FLOP model the batch
    bench's MFU derives from (2·nnz·k² Gramian + 2·nnz·k RHS +
    rows·(k³/3 + 2k²) solve), with bytes as the dominant HBM terms — the
    slot-cell gather at the compute dtype plus the per-row Gramian and
    factor writes. The blocked solver is a scan of sub-programs rather than
    one compiled executable, so the trainer registers analytically where
    serving registers from ``cost_analysis()``; either way the label is one
    program signature multiplied by recorded calls."""
    k = features
    rows = side.padded_rows
    flops = (2.0 * nnz * k * k + 2.0 * nnz * k
             + rows * (k ** 3 / 3.0 + 2.0 * k * k))
    gather_itemsize = 2.0 if dtype == "bfloat16" else 4.0
    bytes_ = (float(side.scols.size) * k * gather_itemsize
              + rows * k * (k + 1) * 4.0)
    profiling.costs().register(key, flops, bytes_)


def _recorded_half(key: str, fn):
    """Wrap a half-iteration solver so each dispatch lands in the device
    cost counters (oryx_device_flops_total{program=key} et al.)."""

    def call(*args):
        profiling.costs().record(key)
        return fn(*args)

    return call


def als_train(
    batch: RatingBatch,
    features: int,
    lam: float,
    alpha: float,
    implicit: bool,
    iterations: int = 10,
    key=None,
    chunk: int | None = None,
    mesh=None,
    row_axis: str | None = None,
    block: int | None = None,
    slot_width: int | None = None,
    dtype: str = "float32",
    fused_gramian: "bool | None" = None,
    layout_cache: "BlockedLayoutCache | None" = None,
    timings: "dict | None" = None,
    checkpointer=None,
):
    """Full alternating optimization; returns (X, Y) as jax arrays.

    ``dtype`` sets the Gramian-matmul INPUT precision ("bfloat16" = MXU
    native; accumulation and solves stay float32 regardless).

    **Pack/compute overlap**: the user side packs on the calling thread
    while the item side packs on a worker — and the user half-iteration
    DISPATCHES before the item pack is awaited, so the device crunches the
    first half-iteration while the host finishes packing the other side.
    With a ``layout_cache`` a repeated/appended generation's pack collapses
    to a reuse or an incremental delta, which together make host packing
    cost less wall time than the device loop it feeds (the r5 gap: 58 s
    pack vs 6 s compute). ``timings``, when a dict is passed, receives
    ``pack_s`` (pack time actually BLOCKING the critical path),
    ``pack_user_s``/``pack_item_s`` (raw per-side work) and the cache
    modes.

    ``fused_gramian=None`` selects the fused Pallas gather-Gramian kernel
    on TPU (``ops/pallas_kernels.gather_gramian_accumulate``) and the
    einsum+segment-sum formulation elsewhere; ``True`` forces the kernel
    (interpret-emulated off-TPU — how the CPU suite tests the exact path).

    **Preemption tolerance**: ``checkpointer`` (a
    ``common/checkpoint.TrainerCheckpointer``) restores the newest valid
    factor state for its data fingerprint before the loop and saves
    ``{x, y}`` every interval (plus the final iteration) — each save
    handed to a background writer so the device→host fetch and file write
    overlap the next half-iteration, never blocking the device loop (the
    blocked time is reported as ``timings["ckpt_wait_s"]``, asserted ≈0
    by bench_batch). A restored checkpoint skips its completed iterations:
    a killed trainer redoes at most one interval. Restore/save failures
    degrade to from-scratch/skipped — checkpointing never fails a train.

    Single-device (no mesh): returns exact-shape ``(n_users, k)``/
    ``(n_items, k)`` arrays.

    With ``mesh``/``row_axis``: the block axis shards over that mesh axis on
    the way in (device_put) and the way out (shard_map out_specs pins the
    factors row-partitioned), and the returned factors are **padded up to the
    block boundary** (``shape[0] = n_blocks·block ≥ n_rows``, extra rows
    zero) — exact-size uneven shardings are not expressible, and gathering
    to slice would defeat the partitioning. Consumers slice host-side
    (``np.asarray(x)[:n_users]``). ``block``/``chunk`` default to sizes
    bounding device memory at ~256 MB / ~64 MB regardless of n_rows; block
    is chosen per side so a small side is not over-padded; the slot width T
    defaults to the side's mean row degree (power of two in [8, 512]).
    ``chunk`` counts SLOTS per scan step (each T entries wide), not nnz, and
    explicit values are clamped into the transient budget.
    """
    import concurrent.futures as cf
    import time

    from oryx_tpu.common import rand

    if iterations < 1:
        raise ValueError(f"iterations must be >= 1, got {iterations}")
    if dtype not in ("float32", "bfloat16"):
        # fail fast at the API boundary: a typo ("bf16") would otherwise
        # surface deep inside a jitted solve, and a low-precision numpy
        # dtype ("float16", "int8") would run and silently degrade factors
        raise ValueError(
            f"compute dtype must be 'float32' or 'bfloat16', got {dtype!r}"
        )

    n_users, n_items = len(batch.users), len(batch.items)
    k = features
    ndev = 1
    if mesh is not None and row_axis is not None:
        ndev = mesh.shape[row_axis]
    block_u = _even_block(n_users, k, ndev, block)
    block_i = _even_block(n_items, k, ndev, block)
    pack_user, pack_item = _side_packers(
        batch, k, ndev, block_u, block_i, chunk, slot_width, None,
        layout_cache,
    )
    pool = cf.ThreadPoolExecutor(1, thread_name_prefix="oryx-als-pack")
    item_timing: dict = {}

    def timed_pack_item() -> _BlockedSide:
        t0 = time.perf_counter()
        side = pack_item()
        item_timing["s"] = time.perf_counter() - t0
        return side

    def finish_item_pack() -> tuple[_BlockedSide, float]:
        t1 = time.perf_counter()
        side = item_fut.result()
        wait_s = time.perf_counter() - t1
        pool.shutdown(wait=False)
        _register_half_cost("als.train.item_half", side, batch.nnz, k, dtype)
        if layout_cache is not None:
            layout_cache.store_batch(batch.rows, batch.cols, batch.vals)
        if timings is not None:
            timings["pack_user_s"] = round(pack_user_s, 3)
            timings["pack_item_s"] = round(item_timing.get("s", 0.0), 3)
            timings["pack_wait_s"] = round(wait_s, 3)
            # pack cost on the CRITICAL PATH: the user pack plus however
            # much of the item pack the device did not hide
            timings["pack_s"] = round(pack_user_s + wait_s, 3)
            if layout_cache is not None:
                timings["pack_modes"] = dict(layout_cache.last_modes)
        return side, wait_s

    # everything past the submit sits under the finally: a user-pack or
    # factor-init failure must still shut the pool down, or the supervised
    # batch-tier retry loop would leak one pack thread per failed attempt
    try:
        item_fut = pool.submit(timed_pack_item)
        t0 = time.perf_counter()
        user_side = pack_user()
        pack_user_s = time.perf_counter() - t0
        chunk_u = user_side.slot_chunk
        _register_half_cost("als.train.user_half", user_side, batch.nnz, k,
                            dtype)

        if key is None:
            key = rand.get_key()
        # resume: the newest valid checkpoint matching the data fingerprint
        # replaces Y₀ (and skips its completed iterations); shape drift —
        # a block-size or hyperparameter change that slipped past the
        # fingerprint — falls back to a fresh start, never a bad gather
        start_iter = 0
        restored: "tuple | None" = None
        if checkpointer is not None:
            ck = checkpointer.restore()
            if ck is not None:
                rx, ry = ck.arrays.get("x"), ck.arrays.get("y")
                if (rx is not None and ry is not None
                        and rx.shape == (n_users, k)
                        and ry.shape == (n_items, k)):
                    restored = (np.asarray(rx, dtype=np.float32),
                                np.asarray(ry, dtype=np.float32))
                    start_iter = min(int(ck.step), iterations)
                    checkpointer.mark_resumed(start_iter)
                else:
                    import logging

                    logging.getLogger(__name__).warning(
                        "checkpoint %s does not match the current factor "
                        "shapes; training from scratch", ck.path,
                    )

        def _maybe_ckpt(completed: int, x_arr, y_arr) -> None:
            if checkpointer is None or not checkpointer.wants(
                completed, iterations
            ):
                return
            # exact-size slices: checkpoints are block-layout-agnostic,
            # so a resume survives a changed block/mesh geometry
            checkpointer.submit(
                completed, {"x": x_arr[:n_users], "y": y_arr[:n_items]}
            )

        def _finish_ckpt() -> None:
            if checkpointer is not None:
                checkpointer.finish()
                if timings is not None:
                    # wait_s = mid-train joins only (the overlap evidence);
                    # the final join mostly waits on the LAST iteration's
                    # device compute, which a plain train pays too
                    timings["ckpt_wait_s"] = round(checkpointer.wait_s, 3)
                    timings["ckpt_final_wait_s"] = round(
                        checkpointer.final_wait_s, 3
                    )
                    timings["ckpt_resumed_from"] = checkpointer.resumed_step

        # Y₀ needs only the item side's PADDED SHAPE, which is pure
        # arithmetic — the factor buffer (and the whole first user
        # half-iteration) must not wait on the item pack
        if restored is not None:
            y = jnp.zeros(
                (_padded_rows_for(n_items, block_i, ndev), k),
                dtype=jnp.float32,
            ).at[:n_items].set(restored[1])
        else:
            y = _init_factors(_padded_rows_for(n_items, block_i, ndev),
                              n_items, k, key)

        if mesh is not None and row_axis is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            row_shard = NamedSharding(mesh, P(row_axis, None))

            def put_side(side):
                return tuple(
                    jax.device_put(a, NamedSharding(
                        mesh, P(row_axis, *([None] * (a.ndim - 1)))))
                    for a in (side.srows, side.scols, side.svals, side.slens)
                )

            y = jax.device_put(y, row_shard)
            if start_iter >= iterations:
                # fully-trained checkpoint (a crash between train end and
                # publish): nothing to redo — re-pad X and keep the mesh
                # contract (padded, row-partitioned factors). Checked
                # BEFORE the user-side COO transfers to device or the
                # solver builds: a zero-redo resume must not pay either.
                finish_item_pack()
                x = jax.device_put(
                    jnp.zeros(
                        (_padded_rows_for(n_users, block_u, ndev), k),
                        dtype=jnp.float32,
                    ).at[:n_users].set(restored[0]),
                    row_shard,
                )
                _finish_ckpt()
                return x, y
            u_arrays = put_side(user_side)
            on_tpu = _use_spd_kernel(mesh=mesh)
            fused = _resolve_fused(fused_gramian, on_tpu, k)
            solve_u = _recorded_half("als.train.user_half", _sharded_solver(
                mesh, row_axis, block_u, k, implicit, chunk_u, dtype, on_tpu,
                fused, not on_tpu))
            x = solve_u(y, *u_arrays, lam, alpha)  # device busy; host packs
            item_side, _ = finish_item_pack()
            i_arrays = put_side(item_side)
            solve_i = _recorded_half("als.train.item_half", _sharded_solver(
                mesh, row_axis, block_i, k, implicit, item_side.slot_chunk,
                dtype, on_tpu, fused, not on_tpu))
            y = solve_i(x, *i_arrays, lam, alpha)
            completed = start_iter + 1
            _maybe_ckpt(completed, x, y)
            for _ in range(iterations - start_iter - 1):
                x = solve_u(y, *u_arrays, lam, alpha)
                y = solve_i(x, *i_arrays, lam, alpha)
                completed += 1
                _maybe_ckpt(completed, x, y)
            _finish_ckpt()
            return x, y

        def solve(side, opp, blk, ck):
            profiling.costs().record(
                "als.train.user_half" if side is user_side
                else "als.train.item_half"
            )
            return solve_side_blocked(
                opp, side.srows, side.scols, side.svals, side.slens, lam,
                alpha, block=blk, features=k, implicit=implicit,
                slot_chunk=ck, dtype=dtype, fused_gramian=fused_gramian,
            )

        if start_iter >= iterations:
            # fully-trained checkpoint: nothing to redo (the item pack
            # worker still gets joined so timings/cache state stay sound)
            finish_item_pack()
            _finish_ckpt()
            return jnp.asarray(restored[0]), jnp.asarray(restored[1])
        # first user half-iteration dispatches against Y₀ (or the restored
        # Y) while the item side is still packing on the worker thread
        x = solve(user_side, y, block_u, chunk_u)
        item_side, _ = finish_item_pack()
        chunk_i = item_side.slot_chunk
        y = solve(item_side, x, block_i, chunk_i)
        completed = start_iter + 1
        _maybe_ckpt(completed, x, y)
        for _ in range(iterations - start_iter - 1):
            x = solve(user_side, y, block_u, chunk_u)
            y = solve(item_side, x, block_i, chunk_i)
            completed += 1
            _maybe_ckpt(completed, x, y)
        _finish_ckpt()
        return x[:n_users], y[:n_items]
    finally:
        # JOIN the worker on every exit: after a user-pack failure an
        # orphaned item pack could outlive this call — and the ALSUpdate
        # cache lock — then write its side into the shared layout cache
        # mid-next-generation, desyncing _sides from _arrays and silently
        # corrupting a later delta pack. On success the future is already
        # consumed and this is free.
        pool.shutdown(wait=True, cancel_futures=True)
