"""TPU-native ALS training kernel.

Replaces Spark MLlib's distributed ALS (behind ALSUpdate.buildModel,
app/oryx-app-mllib/.../als/ALSUpdate.java:108-179) with a jit'd JAX program
designed for the MXU:

  * implicit feedback à la Hu/Koren/Volinsky as in MLlib: confidence
    c = 1 + α·|r|, preference p = 1 if r > 0 else 0; explicit = ALS-WR with
    λ·n_u regularization scaling;
  * per-side normal equations are accumulated by scanning fixed-size nnz
    chunks: gather factor rows, form weighted outer products (C,k,k), and
    scatter-add into the per-row Gramian buffer with a sorted segment-sum —
    O(nnz·k²) work, chunk-bounded memory;
  * all rows solve in one batched Cholesky (jax.scipy cho_factor/cho_solve
    over (n_rows,k,k)) — the MXU-friendly replacement for MLlib's per-block
    LAPACK calls;
  * under a mesh, the row dimension of the Gramian/factor buffers shards over
    devices (sharding annotations; XLA inserts the scatter/gather collectives)
    while the opposite-side factor matrix is replicated per half-iteration —
    the classic alternating block layout of distributed ALS.

Interactions must arrive sorted by row (data.build_rating_batch guarantees
it); both row-sorted and column-sorted copies are kept so each half-iteration
scans its natural order.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from oryx_tpu.models.als.data import RatingBatch

DEFAULT_NNZ_CHUNK = 16384


def _pad_to_multiple(arr: np.ndarray, multiple: int, fill) -> np.ndarray:
    n = len(arr)
    rem = (-n) % multiple
    if rem == 0:
        return arr
    return np.concatenate([arr, np.full(rem, fill, dtype=arr.dtype)])


@dataclass
class _SideArrays:
    """Device-ready COO for one half-iteration, padded to the chunk size;
    padding rows point at the spill row (index n_rows) with zero weight."""

    rows: jnp.ndarray
    cols: jnp.ndarray
    vals: jnp.ndarray


def _make_side(rows, cols, vals, n_rows: int, chunk: int) -> _SideArrays:
    order = np.argsort(rows, kind="stable")
    r = _pad_to_multiple(rows[order].astype(np.int32), chunk, n_rows)
    c = _pad_to_multiple(cols[order].astype(np.int32), chunk, 0)
    v = _pad_to_multiple(vals[order].astype(np.float32), chunk, 0.0)
    return _SideArrays(jnp.asarray(r), jnp.asarray(c), jnp.asarray(v))


@functools.partial(
    jax.jit,
    static_argnames=("n_rows", "features", "implicit", "chunk"),
)
def solve_side(
    factors,  # (n_cols, k) opposite-side factors
    rows,  # (nnz_padded,) int32 sorted
    cols,  # (nnz_padded,) int32
    vals,  # (nnz_padded,) float32 (0 = padding)
    n_rows: int,
    features: int,
    lam: float,
    alpha: float,
    implicit: bool,
    chunk: int = DEFAULT_NNZ_CHUNK,
):
    """One half-iteration: solve all row factors against fixed column factors."""
    k = features
    nnz = rows.shape[0]
    n_chunks = nnz // chunk

    def body(carry, i):
        big_a, big_b, cnt = carry
        sl = lambda a: jax.lax.dynamic_slice_in_dim(a, i * chunk, chunk)
        r, c, v = sl(rows), sl(cols), sl(vals)
        yg = factors[c]  # (C, k) gather
        if implicit:
            w = alpha * jnp.abs(v)  # confidence - 1
            pref = (v > 0).astype(jnp.float32)
            b_contrib = ((1.0 + w) * pref)[:, None] * yg
        else:
            w = jnp.ones_like(v)  # padding zeroed by pad_mask below
            b_contrib = v[:, None] * yg
        pad_mask = (r < n_rows).astype(jnp.float32)
        w = w * pad_mask
        outer = (yg[:, :, None] * yg[:, None, :]) * w[:, None, None]  # (C, k, k)
        big_a = big_a.at[r].add(outer)
        big_b = big_b.at[r].add(b_contrib * pad_mask[:, None])
        cnt = cnt.at[r].add(pad_mask)
        return (big_a, big_b, cnt), None

    big_a = jnp.zeros((n_rows + 1, k, k), dtype=jnp.float32)
    big_b = jnp.zeros((n_rows + 1, k), dtype=jnp.float32)
    cnt = jnp.zeros((n_rows + 1,), dtype=jnp.float32)
    (big_a, big_b, cnt), _ = jax.lax.scan(
        body, (big_a, big_b, cnt), jnp.arange(n_chunks)
    )
    big_a, big_b, cnt = big_a[:n_rows], big_b[:n_rows], cnt[:n_rows]

    eye = jnp.eye(k, dtype=jnp.float32)
    # ALS-WR regularization scaling by interaction count (MLlib semantics)
    reg = lam * jnp.maximum(cnt, 1.0)
    if implicit:
        yty = factors.T @ factors  # (k, k) Gramian — one MXU matmul
        big_a = big_a + yty[None, :, :]
    big_a = big_a + reg[:, None, None] * eye[None, :, :]

    chol = jax.scipy.linalg.cholesky(big_a + 1e-6 * eye[None], lower=True)
    x = jax.scipy.linalg.cho_solve((chol, True), big_b[..., None])[..., 0]
    # rows with no interactions have no factor (reference: absent IDs)
    return jnp.where((cnt > 0)[:, None], x, 0.0)


def als_train(
    batch: RatingBatch,
    features: int,
    lam: float,
    alpha: float,
    implicit: bool,
    iterations: int = 10,
    key=None,
    chunk: int = DEFAULT_NNZ_CHUNK,
    mesh=None,
    row_axis: str | None = None,
):
    """Full alternating optimization; returns (X, Y) as jax arrays.

    With ``mesh``/``row_axis`` given, factor and Gramian buffers are sharded
    over rows of the side being solved (NamedSharding); without, single-device.
    """
    from oryx_tpu.common import rand

    n_users, n_items = len(batch.users), len(batch.items)
    if key is None:
        key = rand.get_key()
    k1, _ = jax.random.split(key)
    y = 0.1 * jax.random.normal(k1, (n_items, features), dtype=jnp.float32)

    user_side = _make_side(batch.rows, batch.cols, batch.vals, n_users, chunk)
    item_side = _make_side(batch.cols, batch.rows, batch.vals, n_items, chunk)

    if mesh is not None and row_axis is not None:
        row_sharding = jax.sharding.NamedSharding(
            mesh, jax.sharding.PartitionSpec(row_axis)
        )
        y = jax.device_put(y, row_sharding)

    x = None
    for _ in range(iterations):
        x = solve_side(
            y, user_side.rows, user_side.cols, user_side.vals,
            n_users, features, lam, alpha, implicit, chunk,
        )
        y = solve_side(
            x, item_side.rows, item_side.cols, item_side.vals,
            n_items, features, lam, alpha, implicit, chunk,
        )
    return x, y
