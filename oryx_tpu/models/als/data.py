"""ALS input preparation: parse → decay → aggregate → index.

Host-side equivalent of the reference's ALSUpdate input pipeline
(app/oryx-app-mllib/.../als/ALSUpdate.java:326-423): CSV/JSON lines
``user,item,strength[,timestamp]`` with empty strength = delete (NaN);
time-decay of ratings (decayRating:383-389, ``oryx.als.decay.*``);
aggregation — implicit: NaN-aware sum per (user,item) (delete wins the pair),
explicit: last-by-timestamp wins (aggregateScores:395-423); optional
``log1p(v/epsilon)`` strength scaling; and string-ID → dense-index maps
(buildIDIndexMapping:181-190) built with host dictionaries instead of a
Spark zipWithIndex shuffle.

Output is a COO batch of (row, col, value) numpy arrays sorted by row,
ready to ship to the device trainer.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Iterable, Sequence

import numpy as np

from oryx_tpu.common import textutils


@dataclass
class Interaction:
    user: str
    item: str
    value: float  # NaN = delete
    timestamp_ms: int


def parse_line(line: str, now_ms: int | None = None) -> Interaction:
    """user,item[,strength[,ts]]; empty strength = delete → NaN."""
    tokens = textutils.parse_possibly_json(line)
    if len(tokens) < 2:
        raise ValueError(f"bad ALS input: {line!r}")
    user, item = tokens[0], tokens[1]
    if len(tokens) >= 3:
        value = float("nan") if tokens[2] == "" else float(tokens[2])
    else:
        value = 1.0
    ts = int(float(tokens[3])) if len(tokens) >= 4 else (now_ms or int(time.time() * 1000))
    return Interaction(user, item, value, ts)


def parse_lines(lines: Iterable[str], now_ms: int | None = None) -> list[Interaction]:
    import csv as _csv

    out = []
    for line in lines:
        try:
            out.append(parse_line(line, now_ms))
        except (ValueError, IndexError, OverflowError, _csv.Error):
            import logging

            logging.getLogger(__name__).warning("bad input: %s", line)
    return out


def decay(
    interactions: Sequence[Interaction],
    factor: float,
    zero_threshold: float,
    now_ms: int | None = None,
) -> list[Interaction]:
    """Exponential per-day decay + threshold filter (decayRating:383-389)."""
    if factor >= 1.0 and zero_threshold <= 0.0:
        return list(interactions)
    now_ms = now_ms or int(time.time() * 1000)
    out = []
    for it in interactions:
        v = it.value
        if factor < 1.0 and it.timestamp_ms < now_ms and not np.isnan(v):
            days = (now_ms - it.timestamp_ms) / 86400000.0
            v = v * factor**days
        if zero_threshold > 0.0 and not np.isnan(v) and v <= zero_threshold:
            continue
        out.append(Interaction(it.user, it.item, v, it.timestamp_ms))
    return out


def aggregate(
    interactions: Sequence[Interaction],
    implicit: bool,
    log_strength: bool = False,
    epsilon: float = 1.0e-5,
) -> dict[tuple[str, str], float]:
    """Combine per (user,item): implicit = NaN-aware sum (NaN anywhere deletes
    the pair), explicit = last (by timestamp order) wins; then drop NaN and
    apply optional log scaling (aggregateScores:395-423)."""
    ordered = sorted(interactions, key=lambda it: it.timestamp_ms)
    agg: dict[tuple[str, str], float] = {}
    if implicit:
        for it in ordered:
            k = (it.user, it.item)
            if np.isnan(it.value):
                agg[k] = float("nan")
            else:
                cur = agg.get(k)
                if cur is None:
                    agg[k] = it.value
                elif not np.isnan(cur):
                    agg[k] = cur + it.value
                # cur NaN: delete sticks for this batch (SUM_WITH_NAN)
    else:
        for it in ordered:
            agg[(it.user, it.item)] = it.value
    result = {k: v for k, v in agg.items() if not np.isnan(v)}
    if log_strength:
        result = {k: float(np.log1p(v / epsilon)) for k, v in result.items()}
    return result


class IDIndexMapping:
    """Bidirectional string-ID ↔ dense-index maps for one axis
    (buildIDIndexMapping:181-190; sorted for determinism)."""

    def __init__(self, ids: Iterable[str]):
        self.index_to_id: list[str] = sorted(set(ids))
        self.id_to_index: dict[str, int] = {s: i for i, s in enumerate(self.index_to_id)}

    @classmethod
    def from_sorted_unique(cls, ids: list) -> "IDIndexMapping":
        """Construct from an already-sorted, already-unique id list (the
        vectorized ingest path) without re-sorting."""
        self = cls.__new__(cls)
        self.index_to_id = list(ids)
        self.id_to_index = {s: i for i, s in enumerate(self.index_to_id)}
        return self

    def __len__(self) -> int:
        return len(self.index_to_id)


@dataclass
class RatingBatch:
    """COO ratings sorted by row, plus the ID maps."""

    rows: np.ndarray  # int32 [nnz]
    cols: np.ndarray  # int32 [nnz]
    vals: np.ndarray  # float32 [nnz]
    users: IDIndexMapping
    items: IDIndexMapping

    @property
    def nnz(self) -> int:
        return len(self.vals)


def build_rating_batch(
    aggregated: dict[tuple[str, str], float],
    users: IDIndexMapping | None = None,
    items: IDIndexMapping | None = None,
) -> RatingBatch:
    if users is None:
        users = IDIndexMapping(u for (u, _i) in aggregated)
    if items is None:
        items = IDIndexMapping(i for (_u, i) in aggregated)
    rows = np.empty(len(aggregated), dtype=np.int32)
    cols = np.empty(len(aggregated), dtype=np.int32)
    vals = np.empty(len(aggregated), dtype=np.float32)
    n = 0
    for (u, i), v in aggregated.items():
        ui = users.id_to_index.get(u)
        ii = items.id_to_index.get(i)
        if ui is None or ii is None:
            continue
        rows[n], cols[n], vals[n] = ui, ii, v
        n += 1
    rows, cols, vals = rows[:n], cols[:n], vals[:n]
    order = np.argsort(rows, kind="stable")
    return RatingBatch(rows[order], cols[order], vals[order], users, items)


def _unique_inverse(arr: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """np.unique(return_inverse) for id strings, via pandas' hash-based
    factorize when available (~2× numpy's sort-based unique at 1M ids;
    identical outputs with sort=True)."""
    try:
        import pandas as pd
    except ImportError:  # pragma: no cover — pandas is in the base image
        return np.unique(arr, return_inverse=True)
    codes, cats = pd.factorize(arr, sort=True)
    return np.asarray(cats), codes


def _tokenize_uniform(lines: list, now_s: str):
    """Whole-corpus tokenization for the uniform plain-CSV case: ONE join,
    ONE split, and strided list slices instead of a million per-line
    ``str.split`` calls (which were ~75% of vectorized-ingest wall).

    Applies only when a blob scan shows no quotes, no brackets, and no CRs
    anywhere AND every line has the same field count (detected by exact
    token-count arithmetic); anything else returns None and the per-line
    tokenizer decides. Returns (users, items, vals, tss) lists or None."""
    import itertools

    n = len(lines)
    first = lines[0]
    if not first:
        return None
    k = first.count(",") + 1
    if k not in (2, 3, 4):
        return None
    # EVERY line must have exactly k-1 commas (one C-level map — aggregate
    # token arithmetic alone can be fooled by offsetting raggedness, e.g. a
    # 4-field and a 2-field line summing to 2·3 tokens and silently
    # misaligning every row after the first irregular one)
    if set(map(str.count, lines, itertools.repeat(","))) != {k - 1}:
        return None
    blob = "\n".join(lines)
    if '"' in blob or "[" in blob or "\r" in blob:
        return None
    if blob.count("\n") != n - 1:
        return None  # embedded newline inside some line
    parts = blob.replace("\n", ",").split(",")
    if len(parts) != n * k:
        return None  # unreachable given the checks above; belt and braces
    users = parts[0::k]
    items = parts[1::k]
    if k == 2:
        return users, items, ["1"] * n, [now_s] * n
    vals = parts[2::k]
    if "" in vals:
        vals = [x or "nan" for x in vals]  # empty strength → NaN (delete)
    if k == 3:
        return users, items, vals, [now_s] * n
    tss = parts[3::k]
    if "" in tss:
        return None  # empty ts is a parse error (skipped) downstream
    return users, items, vals, tss


def _tokenize_per_line(lines: list, now_s: str):
    """Per-line tokenizer for mixed/edge CSV that is still plain (no JSON,
    no quoting): the original vectorized-ingest loop."""
    users: list = []
    items: list = []
    vals: list = []
    tss: list = []
    for ln in lines:
        if ln and ln[-1] in "\r\n":
            ln = ln.rstrip("\r\n")  # the csv parser strips line terminators
        if not ln or ln[0] == "[" or '"' in ln:
            return None
        if ln[0].isspace() and ln.lstrip()[:1] == "[":
            return None  # JSON sniffing strips leading whitespace downstream
        t = ln.split(",")
        nt = len(t)
        if nt == 3:
            users.append(t[0]); items.append(t[1])
            vals.append(t[2] or "nan"); tss.append(now_s)
        elif nt == 4:
            if not t[3]:
                return None  # empty ts is a parse error (skipped) downstream
            users.append(t[0]); items.append(t[1])
            vals.append(t[2] or "nan"); tss.append(t[3])
        elif nt == 2:
            users.append(t[0]); items.append(t[1])
            vals.append("1"); tss.append(now_s)
        else:
            return None
    return users, items, vals, tss


def _prepare_vectorized(
    lines: list,
    implicit: bool,
    decay_factor: float,
    decay_zero_threshold: float,
    log_strength: bool,
    epsilon: float,
    now_ms: int,
) -> "RatingBatch | None":
    """Vectorized ingest for the common plain-CSV case — the data-loader hot
    path at reference scale (25M-row MovieLens ingest takes minutes through
    per-line Interaction objects and dict aggregation; this is one tokenize
    pass plus numpy unique/lexsort/reduceat group-bys with IDENTICAL
    semantics to parse→decay→aggregate). Returns None when any line needs
    the general parser (JSON arrays, quoted CSV, bad lines) — the caller
    then replays the whole batch through the slow path."""
    if not lines:
        return None
    now_s = str(now_ms)
    fast = _tokenize_uniform(lines, now_s)
    if fast is not None:
        users, items, vals, tss = fast
    else:
        slow = _tokenize_per_line(lines, now_s)
        if slow is None:
            return None
        users, items, vals, tss = slow
    try:
        v = np.asarray(vals, dtype=np.float64)
        tsf = np.asarray(tss, dtype=np.float64)
    except ValueError:
        return None  # non-numeric strength/timestamp → general parser
    if not np.isfinite(tsf).all() or not (np.abs(tsf) < 2.0**63).all():
        # 'nan'/'inf' are parse errors downstream; >= 2^63 would wrap in the
        # int64 cast and invert last-by-timestamp ordering
        return None
    ts = tsf.astype(np.int64)

    # decay (decayRating:383-389): per-day exponential for past timestamps
    if decay_factor < 1.0:
        days = (now_ms - ts) / 86400000.0
        live = ~np.isnan(v) & (ts < now_ms)
        v = np.where(live, v * decay_factor ** np.maximum(days, 0.0), v)
    if decay_zero_threshold > 0.0:
        keep = np.isnan(v) | (v > decay_zero_threshold)
        v, ts = v[keep], ts[keep]
        users = np.asarray(users, dtype=object)[keep]
        items = np.asarray(items, dtype=object)[keep]
    if len(v) == 0:
        return RatingBatch(
            np.empty(0, np.int32), np.empty(0, np.int32),
            np.empty(0, np.float32),
            IDIndexMapping(()), IDIndexMapping(()),
        )

    uid_sorted, uinv = _unique_inverse(np.asarray(users, dtype=object))
    iid_sorted, iinv = _unique_inverse(np.asarray(items, dtype=object))
    key = uinv.astype(np.int64) * len(iid_sorted) + iinv

    if implicit:
        # SUM_WITH_NAN per pair: a plain group-sum reproduces the delete
        # rule exactly (any NaN poisons the pair's sum)
        order = np.argsort(key, kind="stable")
        ks = key[order]
        starts = np.flatnonzero(np.r_[True, ks[1:] != ks[:-1]])
        agg_key = ks[starts]
        agg_v = np.add.reduceat(v[order], starts)
    else:
        # explicit: last write in timestamp order wins (ties → input order)
        order = np.lexsort((np.arange(len(key)), ts, key))
        ks = key[order]
        last = np.flatnonzero(np.r_[ks[1:] != ks[:-1], True])
        agg_key = ks[last]
        agg_v = v[order][last]

    keep = ~np.isnan(agg_v)
    agg_key, agg_v = agg_key[keep], agg_v[keep]
    if log_strength:
        agg_v = np.log1p(agg_v / epsilon)

    rows64 = agg_key // len(iid_sorted)
    cols64 = agg_key % len(iid_sorted)
    # re-index over only the ids that SURVIVE aggregation (deleted-only ids
    # must not appear in the mappings — build_rating_batch semantics)
    su = np.unique(rows64)
    si = np.unique(cols64)
    rows = np.searchsorted(su, rows64).astype(np.int32)
    cols = np.searchsorted(si, cols64).astype(np.int32)
    users_map = IDIndexMapping.from_sorted_unique(uid_sorted[su].tolist())
    items_map = IDIndexMapping.from_sorted_unique(iid_sorted[si].tolist())
    final = np.argsort(rows, kind="stable")  # COO sorted by row
    return RatingBatch(
        rows[final], cols[final], agg_v[final].astype(np.float32),
        users_map, items_map,
    )


def prepare(
    lines: Iterable[str],
    implicit: bool,
    decay_factor: float = 1.0,
    decay_zero_threshold: float = 0.0,
    log_strength: bool = False,
    epsilon: float = 1.0e-5,
    now_ms: int | None = None,
) -> RatingBatch:
    """Full pipeline: parse → decay → aggregate → index → COO. Plain-CSV
    input takes the vectorized fast path; JSON/quoted/bad lines fall back to
    the general per-line parser."""
    lines = list(lines)
    now = now_ms or int(time.time() * 1000)
    fast = _prepare_vectorized(
        lines, implicit, decay_factor, decay_zero_threshold, log_strength,
        epsilon, now,
    )
    if fast is not None:
        return fast
    interactions = parse_lines(lines, now)
    interactions = decay(interactions, decay_factor, decay_zero_threshold, now)
    agg = aggregate(interactions, implicit, log_strength, epsilon)
    return build_rating_batch(agg)
