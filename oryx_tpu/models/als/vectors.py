"""In-memory feature-vector store for speed/serving ALS models.

Equivalent of the reference's FeatureVectors / FeatureVectorsPartition /
PartitionedFeatureVectors (app/oryx-app-common/.../als/FeatureVectorsPartition.java:36-131,
PartitionedFeatureVectors.java:43-93): id → float32 vector map plus a
recent-ids set, guarded by one readers-writer lock, with ``retain_recent_and_ids``
GC on model handoff.

TPU re-design: where the reference partitions vectors across threads so
serving scans parallelize on cores, here the whole store materializes into one
dense device matrix (id order pinned) behind a version counter — scans become
a single MXU matmul (models/als/serving.py). Point updates (speed-layer UP
messages, ALSServingModel.java:320-370's in-place setters) accumulate in a
pending map and fold into the EXISTING device matrix as one batched scatter
(``mat.at[idx].set``) plus one append for new ids — device-side double
buffering: the old matrix stays intact for in-flight queries, and the full
host→device re-upload happens only on whole-model handoffs (bulk_load /
retain GC / removals). get_vtv (the Gramian for fold-in solves) is one
X.T @ X on device.
"""

from __future__ import annotations

import collections
import threading
import weakref

import numpy as np

from oryx_tpu.common.lockutils import AutoReadWriteLock


class Transition:
    """One incremental materialization step: ``new_mat`` is ``prev_mat`` with
    rows ``changed_idx`` rewritten and ``n_new`` rows appended. Consumers
    holding a snapshot of ``prev_mat`` (ALSServingModel._YSnapshot) use this
    to update derived per-row state (LSH buckets) for only the delta.

    Matrices are held by WEAK reference: the log must never pin old device
    buffers in HBM — once every consumer drops a generation, the chain
    through it simply breaks and the consumer falls back to a full rebuild."""

    __slots__ = ("prev_ref", "new_ref", "changed_idx", "n_new")

    def __init__(self, prev_mat, new_mat, changed_idx: np.ndarray, n_new: int):
        self.prev_ref = weakref.ref(prev_mat)
        self.new_ref = weakref.ref(new_mat)
        self.changed_idx = changed_idx
        self.n_new = n_new


class FeatureVectorStore:
    def __init__(self):
        self._vectors: dict[str, np.ndarray] = {}
        self._recent_ids: set[str] = set()
        self._lock = AutoReadWriteLock()
        # device materialization cache, validated by a write-version counter
        # (no dirty flag: a flag could be cleared over a concurrent write)
        self._version = 0
        self._cache_lock = threading.Lock()
        self._cached_ids: list[str] | None = None
        self._cached_index: dict[str, int] = {}
        self._cached_matrix = None  # jax array
        self._cached_version = -1
        # point updates since the last materialization; applied as one
        # batched device scatter unless a structural change forces a rebuild
        self._pending_updates: dict[str, np.ndarray] = {}
        # version at which the last STRUCTURAL change (bulk handoff, removal,
        # GC) happened: incremental materialization is sound only from a
        # cache at/after this point. Never cleared — comparing versions is
        # race-free where clearing a boolean after a lock release is not.
        self._rebuild_needed_at = 0
        # recent incremental steps (weak matrix refs): lets a snapshot
        # consumer catch up across SEVERAL materialize generations — e.g.
        # when get_vtv consumed a pending batch between its y_snapshot calls
        self._transitions: collections.deque[Transition] = collections.deque(
            maxlen=8
        )

    # -- map ops (FeatureVectorsPartition:55-108) ---------------------------
    def set_vector(self, id_: str, vector: np.ndarray) -> None:
        v = np.asarray(vector, dtype=np.float32)
        with self._lock.write():
            self._vectors[id_] = v
            self._recent_ids.add(id_)
            self._pending_updates[id_] = v
            self._version += 1

    def bulk_load(self, ids, matrix: np.ndarray) -> None:
        """Set many vectors in one write-lock pass — the fast path for whole-
        model handoffs (MODEL-REF factor files, synthetic bench models)."""
        matrix = np.asarray(matrix, dtype=np.float32)
        with self._lock.write():
            for i, id_ in enumerate(ids):
                self._vectors[id_] = matrix[i]
                self._recent_ids.add(id_)
            self._pending_updates.clear()
            self._version += 1
            self._rebuild_needed_at = self._version

    def get_vector(self, id_: str) -> "np.ndarray | None":
        with self._lock.read():
            return self._vectors.get(id_)

    def get_vectors(self, ids) -> list:
        """Batched lookup under ONE read lock — per-call lock overhead
        otherwise dominates microbatch fold-in gathers (2 acquisitions per
        interaction)."""
        with self._lock.read():
            g = self._vectors.get
            return [g(i) for i in ids]

    def remove_vector(self, id_: str) -> None:
        with self._lock.write():
            removed = self._vectors.pop(id_, None) is not None
            self._recent_ids.discard(id_)
            self._pending_updates.pop(id_, None)
            self._version += 1
            if removed:  # row deletion compacts the matrix
                self._rebuild_needed_at = self._version

    def size(self) -> int:
        with self._lock.read():
            return len(self._vectors)

    def ids(self) -> list[str]:
        with self._lock.read():
            return list(self._vectors)

    def retain_recent_and_ids(self, ids: "set[str]") -> None:
        """GC on new-model handoff: drop vectors neither recently updated nor
        in the new model (FeatureVectorsPartition.retainRecentAndIDs)."""
        with self._lock.write():
            keep = self._recent_ids | set(ids)
            for k in list(self._vectors):
                if k not in keep:
                    del self._vectors[k]
            self._recent_ids.clear()
            self._pending_updates.clear()
            self._version += 1
            self._rebuild_needed_at = self._version

    # -- device materialization --------------------------------------------
    def materialize(self):
        """(ids, device matrix) snapshot; incremental when only point updates
        happened since the cache (one batched scatter + one append — never a
        full host→device upload), full rebuild on structural changes.

        Race-free: the version and pending set are read under the read lock
        (writers excluded), and the cache critical section is serialized, so
        a concurrent write strictly invalidates this materialization. The
        full-rebuild device upload happens OUTSIDE the locks (it can take
        seconds at reference scale and must not stall UP-consumer writes);
        the incremental path only dispatches async device ops and commits
        inline."""
        import jax.numpy as jnp

        with self._lock.read(), self._cache_lock:
            version = self._version
            if self._cached_version == version:
                return self._cached_ids, self._cached_matrix
            pending, self._pending_updates = self._pending_updates, {}
            k = (
                self._cached_matrix.shape[1]
                if self._cached_matrix is not None
                else None
            )
            if (
                self._cached_matrix is not None
                and self._rebuild_needed_at <= self._cached_version
                and pending
                and all(v.shape == (k,) for v in pending.values())
            ):
                changed_idx, changed_vals, new_ids, new_vecs = [], [], [], []
                for id_, vec in pending.items():
                    j = self._cached_index.get(id_)
                    if j is None:
                        new_ids.append(id_)
                        new_vecs.append(vec)
                    else:
                        changed_idx.append(j)
                        changed_vals.append(vec)
                prev_mat = self._cached_matrix
                mat = prev_mat
                if changed_idx:
                    mat = mat.at[jnp.asarray(changed_idx, dtype=jnp.int32)].set(
                        jnp.asarray(np.stack(changed_vals))
                    )
                if new_vecs:
                    mat = jnp.concatenate([mat, jnp.asarray(np.stack(new_vecs))])
                # new list: snapshots holding the previous ids list stay valid
                ids = self._cached_ids + new_ids
                for i, id_ in enumerate(new_ids):
                    self._cached_index[id_] = len(self._cached_ids) + i
                self._transitions.append(Transition(
                    prev_mat, mat,
                    np.asarray(changed_idx, dtype=np.int64), len(new_ids),
                ))
                self._cached_ids = ids
                self._cached_matrix = mat
                self._cached_version = version
                return ids, mat

            # full rebuild (first build, bulk handoff, removals, width
            # change): capture the host copy under the locks, upload outside
            ids = list(self._vectors)
            host = (
                np.stack([self._vectors[i] for i in ids])
                if ids
                else np.zeros((0, 0), dtype=np.float32)
            )
        mat = jnp.asarray(host) if host.size else None
        with self._cache_lock:
            if version > self._cached_version:
                self._cached_ids = ids
                self._cached_index = {s: i for i, s in enumerate(ids)}
                self._cached_matrix = mat
                self._cached_version = version
                self._transitions.clear()
            return self._cached_ids, self._cached_matrix

    def delta_since(self, from_mat, to_mat) -> "tuple[np.ndarray, int] | None":
        """Compose the recorded incremental steps from ``from_mat`` up to
        ``to_mat``: (changed row indices within from_mat's rows, rows
        appended). None when the chain is broken (full rebuild happened, a
        generation was garbage-collected, or either matrix is unknown) — the
        consumer then rebuilds its derived state from scratch."""
        with self._cache_lock:
            chain = list(self._transitions)
        if from_mat is to_mat:
            return np.empty(0, dtype=np.int64), 0
        start = next(
            (i for i, t in enumerate(chain) if t.prev_ref() is from_mat), None
        )
        if start is None:
            return None
        # continuity within the log is structural (each step's prev IS the
        # previous step's output, and a full rebuild clears the log), so
        # intermediate generations need no liveness check — only the two
        # endpoints, which the caller holds alive, anchor the walk
        n_base = from_mat.shape[0]
        changed: set[int] = set()
        n_new = 0
        for t in chain[start:]:
            # rows rewritten inside the appended tail are covered by the
            # consumer's whole-tail refresh; only base rows need listing
            changed.update(int(i) for i in t.changed_idx if i < n_base)
            n_new += t.n_new
            if t.new_ref() is to_mat:
                return np.asarray(sorted(changed), dtype=np.int64), n_new
        return None

    def get_vtv(self):
        """Gramian V^T V on device (FeatureVectors.getVTV)."""
        _, mat = self.materialize()
        if mat is None:
            return None
        return np.asarray(mat.T @ mat)
