"""In-memory feature-vector store for speed/serving ALS models.

Equivalent of the reference's FeatureVectors / FeatureVectorsPartition /
PartitionedFeatureVectors (app/oryx-app-common/.../als/FeatureVectorsPartition.java:36-131,
PartitionedFeatureVectors.java:43-93): id → float32 vector map plus a
recent-ids set, guarded by one readers-writer lock, with ``retain_recent_and_ids``
GC on model handoff.

TPU re-design: where the reference partitions vectors across threads so
serving scans parallelize on cores, here the whole store materializes into one
dense device matrix (id order pinned) behind a dirty flag — scans become a
single MXU matmul (models/als/serving.py), and per-id point updates only touch
host state until the next materialization. get_vtv (the Gramian for fold-in
solves) is one X.T @ X on device.
"""

from __future__ import annotations

import threading

import numpy as np

from oryx_tpu.common.lockutils import AutoReadWriteLock


class FeatureVectorStore:
    def __init__(self):
        self._vectors: dict[str, np.ndarray] = {}
        self._recent_ids: set[str] = set()
        self._lock = AutoReadWriteLock()
        # device materialization cache, validated by a write-version counter
        # (no dirty flag: a flag could be cleared over a concurrent write)
        self._version = 0
        self._cache_lock = threading.Lock()
        self._cached_ids: list[str] | None = None
        self._cached_matrix = None  # jax array
        self._cached_version = -1

    # -- map ops (FeatureVectorsPartition:55-108) ---------------------------
    def set_vector(self, id_: str, vector: np.ndarray) -> None:
        v = np.asarray(vector, dtype=np.float32)
        with self._lock.write():
            self._vectors[id_] = v
            self._recent_ids.add(id_)
            self._version += 1

    def bulk_load(self, ids, matrix: np.ndarray) -> None:
        """Set many vectors in one write-lock pass — the fast path for whole-
        model handoffs (MODEL-REF factor files, synthetic bench models)."""
        matrix = np.asarray(matrix, dtype=np.float32)
        with self._lock.write():
            for i, id_ in enumerate(ids):
                self._vectors[id_] = matrix[i]
                self._recent_ids.add(id_)
            self._version += 1

    def get_vector(self, id_: str) -> "np.ndarray | None":
        with self._lock.read():
            return self._vectors.get(id_)

    def remove_vector(self, id_: str) -> None:
        with self._lock.write():
            self._vectors.pop(id_, None)
            self._recent_ids.discard(id_)
            self._version += 1

    def size(self) -> int:
        with self._lock.read():
            return len(self._vectors)

    def ids(self) -> list[str]:
        with self._lock.read():
            return list(self._vectors)

    def retain_recent_and_ids(self, ids: "set[str]") -> None:
        """GC on new-model handoff: drop vectors neither recently updated nor
        in the new model (FeatureVectorsPartition.retainRecentAndIDs)."""
        with self._lock.write():
            keep = self._recent_ids | set(ids)
            for k in list(self._vectors):
                if k not in keep:
                    del self._vectors[k]
            self._recent_ids.clear()
            self._version += 1

    # -- device materialization --------------------------------------------
    def materialize(self):
        """(ids, device matrix) snapshot; rebuilt only when writes happened
        since the cached version (race-free: the version is read under the
        same read lock as the snapshot, so a concurrent write strictly
        invalidates this materialization)."""
        import jax.numpy as jnp

        with self._lock.read():
            version = self._version
            with self._cache_lock:
                if self._cached_version == version:
                    return self._cached_ids, self._cached_matrix
            ids = list(self._vectors)
            mat = (
                np.stack([self._vectors[i] for i in ids])
                if ids
                else np.zeros((0, 0), dtype=np.float32)
            )
        device_mat = jnp.asarray(mat) if mat.size else None
        with self._cache_lock:
            if version > self._cached_version:
                self._cached_ids = ids
                self._cached_matrix = device_mat
                self._cached_version = version
            return self._cached_ids, self._cached_matrix

    def get_vtv(self):
        """Gramian V^T V on device (FeatureVectors.getVTV)."""
        _, mat = self.materialize()
        if mat is None:
            return None
        return np.asarray(mat.T @ mat)
