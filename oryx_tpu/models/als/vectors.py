"""Factor arena: contiguous in-memory feature-vector store for speed/serving.

Equivalent of the reference's FeatureVectors / FeatureVectorsPartition /
PartitionedFeatureVectors (app/oryx-app-common/.../als/FeatureVectorsPartition.java:36-131,
PartitionedFeatureVectors.java:43-93): id → float32 vector map plus a
recent-ids set, guarded by one readers-writer lock, with ``retain_recent_and_ids``
GC on model handoff.

TPU re-design, round 9: the store used to be a ``dict[str, np.ndarray]`` —
one Python ndarray object (~200 B of header) plus a dict slot per row, which
at reference scale (21M rows × 50f ≈ 4 GB of raw factors) multiplies host
RSS 3-5× and turns every device materialization into a million-element
``np.stack``. Now all factors live in ONE preallocated ``(capacity, k)``
float32 slab (the **arena**): ids map to row indices, growth doubles the
slab, and removals/GC re-pack survivors into a FRESH slab (shrinking when
the fill fraction drops). Rows are never recycled in place — a row, once
bound to an id, keeps that binding for its slab's lifetime, so consumers
holding a pinned (slab, rows) snapshot view stay consistent across any
concurrent structural change. Host RSS tracks raw factor bytes; device
snapshot updates become slab slices and row-index scatters.

The store is **pure numpy on the host side**; the device materialization
cache (``materialize``) still builds/maintains a jax device matrix
incrementally (one batched scatter + one append per point-update batch —
never a full host→device re-upload), and a parallel HOST snapshot API
(``host_matrix``/``delta_info``, each carrying the pinned slab view)
serves consumers that must never create a device f32 copy at all (the
int8-quantized serving path gathers its exact rescore rows from it).
``get_vtv`` computes the Gramian from the slab with host BLAS, so a speed
tier never pins a device matrix just for fold-in solvers.
"""

from __future__ import annotations

import collections
import threading
import weakref

import numpy as np

from oryx_tpu.common.lockutils import AutoReadWriteLock

#: Process-wide arena sizing defaults, set by :func:`configure` from
#: ``oryx.serving.arena.*``. Plain ints/floats: reads are atomic.
_DEFAULT_INITIAL_ROWS = 1024
_DEFAULT_MIN_FILL = 0.25

#: Bounded per-write log backing ``delta_info``: (version, id, was_new).
#: A consumer whose snapshot version fell off the log rebuilds in full.
_LOG_MAX = 65536


def configure(config) -> None:
    """Apply ``oryx.serving.arena.*`` sizing knobs process-wide (the same
    configure-at-entry idiom as metrics/resilience): ``initial-rows`` seeds
    new slabs, ``min-fill`` triggers compaction after GC."""
    global _DEFAULT_INITIAL_ROWS, _DEFAULT_MIN_FILL
    _DEFAULT_INITIAL_ROWS = max(
        1, config.get_int("oryx.serving.arena.initial-rows", 1024)
    )
    _DEFAULT_MIN_FILL = min(
        1.0, max(0.0, config.get_float("oryx.serving.arena.min-fill", 0.25))
    )


def _host_gather(slab: np.ndarray, rows) -> np.ndarray:
    """One C-level gather of slab rows about to cross the host→device
    boundary — THE seam tests monkeypatch to count upload traffic (a full
    rebuild gathers every live row; a point-update batch only its delta)."""
    return slab[np.asarray(rows, dtype=np.int64)]


class _IdIndex:
    """Interned id → slab-row map: ids live utf-8-packed in ONE bytearray,
    the map is open-addressing linear probing over numpy arrays. ~25 B/id
    all-in versus the ~170 B/id of a Python ``dict[str, int]`` plus its key
    string objects — the difference between 1.2× and 2.2× raw-factor RSS at
    1M × 50f (measured; docs/performance.md "Serving memory").

    Keyed BY SLAB ROW: ``starts/lens/hashes[row]`` describe the id owning
    that row; the probe table stores rows (−1 empty, −2 tombstone).
    Overwritten/removed ids leave dead bytes in the blob; the store's
    structural compaction rebuilds the whole index, reclaiming them."""

    __slots__ = ("_blob", "_starts", "_lens", "_hashes", "_table", "_used",
                 "_tombstones")

    def __init__(self, capacity: int = 16):
        self._blob = bytearray()
        self._starts = np.zeros(capacity, dtype=np.int32)
        self._lens = np.zeros(capacity, dtype=np.int32)
        self._hashes = np.zeros(capacity, dtype=np.int64)
        table = 16
        while table < 2 * capacity:
            table *= 2
        self._table = np.full(table, -1, dtype=np.int32)
        self._used = 0        # live entries in the table
        self._tombstones = 0  # -2 slots; BOTH drive resize: a probe only
        # terminates on a -1 slot, so tombstones must never be allowed to
        # consume the last empty slots (delete-churn would otherwise spin
        # _probe forever once no -1 remains)

    def _grow_rows(self, need: int) -> None:
        cap = self._starts.shape[0]
        if need <= cap:
            return
        new_cap = max(cap, 16)
        while new_cap < need:
            new_cap *= 2
        for name, dtype in (("_starts", np.int32), ("_lens", np.int32),
                            ("_hashes", np.int64)):
            old = getattr(self, name)
            grown = np.zeros(new_cap, dtype=dtype)
            grown[:cap] = old
            setattr(self, name, grown)

    def _resize_table(self) -> None:
        """Rebuild the probe table from live entries — doubling only when
        the LIVE load demands it (a tombstone-triggered rebuild at the same
        size just sheds the -2 slots)."""
        old = self._table
        size = old.shape[0]
        if self._used * 3 > size * 2:
            size *= 2
        self._table = np.full(size, -1, dtype=np.int32)
        self._tombstones = 0
        mask = size - 1
        for row in old[old >= 0]:
            slot = int(self._hashes[row]) & mask
            while self._table[slot] >= 0:
                slot = (slot + 1) & mask
            self._table[slot] = row

    def _probe(self, enc: bytes, h: int) -> "tuple[int, int]":
        """(slot, row): row ≥ 0 on hit; on miss, slot is the insert point
        (first tombstone on the probe path, else the empty slot)."""
        mask = self._table.shape[0] - 1
        slot = h & mask
        insert_at = -1
        while True:
            row = int(self._table[slot])
            if row == -1:
                return (insert_at if insert_at >= 0 else slot), -1
            if row == -2:
                if insert_at < 0:
                    insert_at = slot
            elif self._hashes[row] == h:
                a = int(self._starts[row])
                if self._blob[a:a + int(self._lens[row])] == enc:
                    return slot, row
            slot = (slot + 1) & mask

    @staticmethod
    def _hash(enc: bytes) -> int:
        return hash(enc) & 0x7FFFFFFFFFFFFFFF

    def lookup(self, id_: str) -> int:
        """Slab row of ``id_``, or −1."""
        enc = id_.encode()
        return self._probe(enc, self._hash(enc))[1]

    def add(self, id_: str, row: int) -> None:
        """Bind a NEW id to ``row`` (caller guarantees absence)."""
        enc = id_.encode()
        h = self._hash(enc)
        self._grow_rows(row + 1)
        self._starts[row] = len(self._blob)
        self._lens[row] = len(enc)
        self._hashes[row] = h
        self._blob.extend(enc)
        if (self._used + self._tombstones + 1) * 3 > self._table.shape[0] * 2:
            self._resize_table()
        slot, _ = self._probe(enc, h)
        if self._table[slot] == -2:
            self._tombstones -= 1  # recycling a tombstoned slot
        self._table[slot] = row
        self._used += 1

    def delete(self, id_: str) -> int:
        """Unbind ``id_``; returns its row or −1. Blob bytes stay until a
        structural compaction rebuilds the index."""
        slot, row = self._probe(id_.encode(), self._hash(id_.encode()))
        if row >= 0:
            self._table[slot] = -2
            self._used -= 1
            self._tombstones += 1
        return row

    def decode(self, row: int) -> str:
        a = int(self._starts[row])
        return self._blob[a:a + int(self._lens[row])].decode()

    def nbytes(self) -> int:
        return (len(self._blob) + self._starts.nbytes + self._lens.nbytes
                + self._hashes.nbytes + self._table.nbytes)


class Transition:
    """One incremental materialization step: ``new_mat`` is ``prev_mat`` with
    rows ``changed_idx`` rewritten and ``n_new`` rows appended. Consumers
    holding a snapshot of ``prev_mat`` (ALSServingModel._YSnapshot) use this
    to update derived per-row state (LSH buckets) for only the delta.

    Matrices are held by WEAK reference: the log must never pin old device
    buffers in HBM — once every consumer drops a generation, the chain
    through it simply breaks and the consumer falls back to a full rebuild."""

    __slots__ = ("prev_ref", "new_ref", "changed_idx", "n_new")

    def __init__(self, prev_mat, new_mat, changed_idx: np.ndarray, n_new: int):
        self.prev_ref = weakref.ref(prev_mat)
        self.new_ref = weakref.ref(new_mat)
        self.changed_idx = changed_idx
        self.n_new = n_new


class HostDelta:
    """Composable host-side delta between two store versions, for consumers
    maintaining their OWN derived per-row state (the quantized device
    snapshot): positions are indices into the consumer's snapshot order;
    values are current-slab copies (intermediate values between the two
    versions are irrelevant — the newest value per row is what lands)."""

    __slots__ = ("version", "changed_ids", "changed_vals", "appended_ids",
                 "appended_vals", "appended_rows", "slab")

    def __init__(self, version, changed_ids, changed_vals, appended_ids,
                 appended_vals, appended_rows=None, slab=None):
        self.version = version
        self.changed_ids = changed_ids        # list[str], ids in the OLD order
        self.changed_vals = changed_vals      # (len(changed_ids), k) f32
        self.appended_ids = appended_ids      # list[str]
        self.appended_vals = appended_vals    # (len(appended_ids), k) f32
        self.appended_rows = appended_rows    # slab rows of the appended ids
        self.slab = slab                      # CURRENT slab object (row
        # indices are stable within an order epoch: _grow copies rows in
        # place and every row-moving change is structural)


class FeatureVectorStore:
    def __init__(self, initial_rows: "int | None" = None):
        self._initial_rows = initial_rows or _DEFAULT_INITIAL_ROWS
        self._lock = AutoReadWriteLock()
        # -- the arena ------------------------------------------------------
        self._slab: "np.ndarray | None" = None  # (capacity, k) float32
        self._ids = _IdIndex()                   # interned id -> slab row
        # one-shot first-allocation sizing from reserve(); compaction keeps
        # using the CONFIGURED floor, so a 21M-row reserve does not pin the
        # slab at 21M for the process lifetime after GC shrinks the model
        self._reserve_rows = 0
        self._n_alloc = 0                        # slab high-water mark
        # snapshot order: position -> slab row (append-only between
        # structural changes) and its inverse, both numpy — no per-id
        # Python objects anywhere in the store
        self._rowmap = np.empty(0, dtype=np.int32)
        self._n_pos = 0
        self._pos_of_row = np.empty(0, dtype=np.int32)
        self._recent = np.zeros(0, dtype=bool)   # per-row recent flag
        # -- versioning -----------------------------------------------------
        self._version = 0
        # version at which the last STRUCTURAL change (bulk handoff, removal,
        # GC, compaction) happened: incremental consumption is sound only
        # from a snapshot at/after this point. Never cleared — comparing
        # versions is race-free where clearing a boolean is not.
        self._rebuild_needed_at = 0
        # per-write log for host-side delta consumers (delta_info)
        self._log: collections.deque = collections.deque(maxlen=_LOG_MAX)
        # -- device materialization cache ----------------------------------
        self._cache_lock = threading.Lock()
        self._cached_ids: "list | None" = None
        self._cached_matrix = None  # jax array
        self._cached_version = -1
        # slab rows point-updated since the last device materialization
        self._pending: set = set()
        # recent incremental device steps (weak matrix refs): lets a snapshot
        # consumer catch up across SEVERAL materialize generations
        self._transitions: collections.deque = collections.deque(maxlen=8)
        # arena-bytes/fill gauges read live stores at scrape time
        from oryx_tpu.common import profiling

        profiling.register_arena(self)

    # -- arena plumbing (callers hold the write lock) -----------------------
    def _ensure_slab(self, k: int) -> None:
        # analyze: ignore[lock-discipline] -- runs only under self._lock.write(), taken by its callers
        if self._slab is None:
            # analyze: ignore[lock-discipline] -- runs only under self._lock.write(), taken by its callers
            cap = max(self._initial_rows, self._reserve_rows, 1)
            self._slab = np.zeros((cap, k), dtype=np.float32)
            # analyze: ignore[lock-discipline] -- runs only under self._lock.write(), taken by its callers
            self._recent = np.zeros(cap, dtype=bool)
            # analyze: ignore[lock-discipline] -- runs only under self._lock.write(), taken by its callers
            self._pos_of_row = np.zeros(cap, dtype=np.int32)
        elif self._slab.shape[1] != k:
            raise ValueError(
                f"factor width changed: arena holds {self._slab.shape[1]}-"
                f"feature rows, got {k} (a new feature count means a new "
                "model generation, which gets a fresh store)"
            )

    def _grow(self, need_rows: int) -> None:
        # analyze: ignore[lock-discipline] -- runs only under self._lock.write(), taken by its callers
        cap = self._slab.shape[0]
        new_cap = max(cap, 1)
        while new_cap < need_rows:
            new_cap *= 2
        if new_cap == cap:
            return
        slab = np.zeros((new_cap, self._slab.shape[1]), dtype=np.float32)
        # analyze: ignore[lock-discipline] -- runs only under self._lock.write(), taken by its callers
        slab[: self._n_alloc] = self._slab[: self._n_alloc]
        self._slab = slab
        for name, dtype in (("_recent", bool), ("_pos_of_row", np.int32)):
            old = getattr(self, name)
            grown = np.zeros(new_cap, dtype=dtype)
            grown[: old.shape[0]] = old
            setattr(self, name, grown)

    def _append_pos(self, row: int) -> None:
        # analyze: ignore[lock-discipline] -- runs only under self._lock.write(), taken by its callers
        if self._n_pos >= self._rowmap.shape[0]:
            grown = np.empty(max(16, 2 * self._rowmap.shape[0]), dtype=np.int32)
            grown[: self._n_pos] = self._rowmap[: self._n_pos]
            self._rowmap = grown
        self._rowmap[self._n_pos] = row
        # analyze: ignore[lock-discipline] -- runs only under self._lock.write(), taken by its callers
        self._pos_of_row[row] = self._n_pos
        self._n_pos += 1

    def _alloc_row(self, id_: str) -> int:
        # rows are NEVER recycled: a row, once bound to an id, keeps that
        # binding for the lifetime of the slab lineage (grow copies rows in
        # place; structural changes re-pack into a FRESH slab + index).
        # Consumers holding a pinned (slab, rows) snapshot view therefore
        # can never see another id's factors at a captured row — the
        # host-side analogue of the device path's double-buffered matrices
        # analyze: ignore[lock-discipline] -- runs only under self._lock.write(), taken by its callers
        if self._n_alloc >= self._slab.shape[0]:
            self._grow(self._n_alloc + 1)
        row = self._n_alloc
        self._n_alloc += 1
        # analyze: ignore[lock-discipline] -- runs only under self._lock.write(), taken by its callers
        self._ids.add(id_, row)
        self._append_pos(row)
        return row

    def _live_rows(self) -> np.ndarray:
        # analyze: ignore[lock-discipline] -- runs only under self._lock.write(), taken by its callers
        return self._rowmap[: self._n_pos]

    def _decode_ids(self, rows) -> list:
        # analyze: ignore[lock-discipline] -- runs only under self._lock.write(), taken by its callers
        dec = self._ids.decode
        return [dec(int(r)) for r in rows]

    def _rebuild_structural(self, keep_rows: np.ndarray,
                            keep_recent: bool) -> None:
        """Re-pack the surviving rows into a FRESH slab + interned id index
        (caller holds the write lock and handles version bookkeeping).

        Every row-freeing change goes through here, which upholds the
        pinned-snapshot invariant: the OLD slab/index objects are never
        mutated again, so an in-flight request's captured (slab, rows)
        rescore view and an out-of-lock id decode both stay consistent no
        matter how the live store moves on. Capacity shrinks to fit when
        the survivor fill falls below ``oryx.serving.arena.min-fill``
        (against the CONFIGURED floor — a reserve()-presized store still
        gives its memory back after GC), else it is kept."""
        # analyze: ignore[lock-discipline] -- runs only under self._lock.write(), taken by its callers
        old_slab, old_ids = self._slab, self._ids
        live = len(keep_rows)
        cap = old_slab.shape[0]
        if live <= cap * _DEFAULT_MIN_FILL:
            cap = max(self._initial_rows, 1)
            while cap < live:
                cap *= 2
        k = old_slab.shape[1]
        slab = np.zeros((cap, k), dtype=np.float32)
        slab[:live] = old_slab[keep_rows]
        ids = _IdIndex(cap)
        for i, row in enumerate(keep_rows):
            ids.add(old_ids.decode(int(row)), i)
        recent = np.zeros(cap, dtype=bool)
        if keep_recent and live:
            # analyze: ignore[lock-discipline] -- runs only under self._lock.write(), taken by its callers
            recent[:live] = self._recent[keep_rows]
        self._slab, self._ids, self._recent = slab, ids, recent
        # analyze: ignore[lock-discipline] -- runs only under self._lock.write(), taken by its callers
        self._rowmap = np.arange(live, dtype=np.int32)
        # analyze: ignore[lock-discipline] -- runs only under self._lock.write(), taken by its callers
        self._pos_of_row = np.zeros(cap, dtype=np.int32)
        self._pos_of_row[:live] = np.arange(live)
        # analyze: ignore[lock-discipline] -- runs only under self._lock.write(), taken by its callers
        self._n_pos = live
        # analyze: ignore[lock-discipline] -- runs only under self._lock.write(), taken by its callers
        self._n_alloc = live
        # analyze: ignore[lock-discipline] -- runs only under self._lock.write(), taken by its callers
        self._pending.clear()

    def reserve(self, rows: int) -> None:
        """Presize the arena for ``rows`` total rows — a MODEL handoff knows
        its id count (the PMML meta's x_ids/y_ids), and presizing skips the
        doubling-growth copies and their 1.5× transient peak. One-shot: it
        sizes the NEXT allocation only and never raises the compaction
        floor (oryx.serving.arena.initial-rows keeps governing shrink)."""
        with self._lock.write():
            if self._slab is None:
                self._reserve_rows = max(self._reserve_rows, rows)
            elif rows > self._slab.shape[0]:
                self._grow(rows)

    # -- map ops (FeatureVectorsPartition:55-108) ---------------------------
    def set_vector(self, id_: str, vector: np.ndarray) -> None:
        v = np.asarray(vector, dtype=np.float32)
        with self._lock.write():
            self._ensure_slab(v.shape[0])
            row = self._ids.lookup(id_)
            was_new = row < 0
            if was_new:
                row = self._alloc_row(id_)
            self._slab[row] = v
            self._recent[row] = True
            self._pending.add(row)
            self._version += 1
            self._log.append((self._version, row, was_new))

    def bulk_load(self, ids, matrix: np.ndarray) -> None:
        """Set many vectors in one write-lock pass — the fast path for whole-
        model handoffs (MODEL-REF factor files, synthetic bench models). The
        matrix is COPIED into the arena: later point updates rewrite slab
        rows in place and must never mutate the caller's array."""
        matrix = np.asarray(matrix, dtype=np.float32)
        ids = list(ids)
        with self._lock.write():
            if self._slab is None and len(ids) and len(set(ids)) != len(ids):
                # duplicate ids in one handoff: the fast path's positional
                # adds would leave BOTH rows live (the stale first
                # occurrence scored forever); route through the per-id
                # lookup path below, which collapses duplicates last-wins
                # exactly like the pre-arena dict store
                self._ensure_slab(matrix.shape[1])
            if self._slab is None and len(ids):
                # empty store: one slab-sized copy, rows in handoff order
                k = matrix.shape[1]
                cap = max(self._initial_rows, self._reserve_rows, len(ids), 1)
                self._slab = np.zeros((cap, k), dtype=np.float32)
                self._slab[: len(ids)] = matrix
                self._ids = _IdIndex(cap)
                for i, id_ in enumerate(ids):
                    self._ids.add(id_, i)
                self._rowmap = np.arange(len(ids), dtype=np.int32)
                self._pos_of_row = np.zeros(cap, dtype=np.int32)
                self._pos_of_row[: len(ids)] = np.arange(len(ids))
                self._n_pos = len(ids)
                self._n_alloc = len(ids)
                self._recent = np.zeros(cap, dtype=bool)
                self._recent[: len(ids)] = True
            elif len(ids):
                self._ensure_slab(matrix.shape[1])
                # growth stays on-demand in _alloc_row (amortized doubling):
                # pre-growing by len(ids) would count already-present ids as
                # new rows and permanently double the slab on a same-id
                # re-handoff
                for i, id_ in enumerate(ids):
                    row = self._ids.lookup(id_)
                    if row < 0:
                        row = self._alloc_row(id_)
                    self._slab[row] = matrix[i]
                    self._recent[row] = True
            self._pending.clear()
            self._version += 1
            self._rebuild_needed_at = self._version

    def get_vector(self, id_: str) -> "np.ndarray | None":
        with self._lock.read():
            row = self._ids.lookup(id_)
            # a COPY: slab rows are rewritten in place by later point
            # updates, and handing out live views would let a held result
            # change under the caller (the dict store's replace-on-write
            # semantics, preserved)
            return self._slab[row].copy() if row >= 0 else None

    def get_vectors(self, ids) -> list:
        """Batched lookup under ONE read lock — per-call lock overhead
        otherwise dominates microbatch fold-in gathers (2 acquisitions per
        interaction)."""
        with self._lock.read():
            lk = self._ids.lookup
            return [
                self._slab[row].copy() if (row := lk(i)) >= 0 else None
                for i in ids
            ]

    def remove_vector(self, id_: str) -> None:
        """Structural: the survivors re-pack into a fresh slab (O(live) —
        removals are rare; reference semantics only remove via model GC)."""
        with self._lock.write():
            row = self._ids.lookup(id_)
            self._version += 1
            if row >= 0:
                live = self._live_rows()
                self._rebuild_structural(live[live != row], keep_recent=True)
                self._rebuild_needed_at = self._version

    def size(self) -> int:
        with self._lock.read():
            return self._n_pos

    def ids(self) -> list:
        with self._lock.read():
            return self._decode_ids(self._live_rows())

    def retain_recent_and_ids(self, ids: "set[str]") -> None:
        """GC on new-model handoff: drop vectors neither recently updated nor
        in the new model (FeatureVectorsPartition.retainRecentAndIDs). The
        survivors re-pack into a fresh slab, shrinking capacity when the
        fill falls below ``oryx.serving.arena.min-fill``."""
        with self._lock.write():
            self._version += 1
            self._rebuild_needed_at = self._version
            if self._slab is None:
                return
            keep = self._recent.copy()
            for id_ in ids:
                row = self._ids.lookup(id_)
                if row >= 0:
                    keep[row] = True
            live = self._live_rows()
            self._rebuild_structural(live[keep[live]], keep_recent=False)

    # -- arena telemetry (scrape-time gauges; see common/profiling.py) ------
    def arena_nbytes(self) -> int:
        # analyze: ignore[lock-discipline] -- scrape-time advisory read; a torn sample skews one gauge scrape, never store state
        slab = self._slab
        return int(slab.nbytes) if slab is not None else 0

    def arena_fill(self) -> float:
        # analyze: ignore[lock-discipline] -- scrape-time advisory read; a torn sample skews one gauge scrape, never store state
        slab = self._slab
        if slab is None or slab.shape[0] == 0:
            return 0.0
        # analyze: ignore[lock-discipline] -- scrape-time advisory read; a torn sample skews one gauge scrape, never store state
        return self._n_pos / slab.shape[0]

    # -- host snapshot API (no device work; the int8 serving path) ----------
    def host_matrix(self) -> "tuple[list, np.ndarray, int, tuple]":
        """(ids, row-aligned float32 copy, version, (slab, rows)): the full
        host snapshot. The copy is one fancy-index gather of the live rows —
        consumers own it. The trailing (slab, rows) pair pins THIS order
        epoch for later exact-rescore gathers (:class:`_QuantSnapshot`):
        row indices stay valid for the slab object they were captured with,
        no matter what the live store does afterwards.

        Only the value gather runs under the read lock (consistency needs
        writers excluded); the per-row id decode — Python-string work that
        dominates at reference scale — happens OUTSIDE, against captures
        that structural changes replace rather than mutate."""
        with self._lock.read():
            slab = self._slab
            rows = self._live_rows().copy()
            index = self._ids
            version = self._version
            host = slab[rows] if slab is not None and rows.size else None
        dec = index.decode
        ids = [dec(int(r)) for r in rows]
        if host is None:
            return ids, np.zeros((0, 0), dtype=np.float32), version, (slab, rows)
        return ids, host, version, (slab, rows)

    def delta_info(self, since_version: int, since_len: int) -> "HostDelta | None":
        """Compose everything written since ``since_version`` for a consumer
        whose snapshot held the first ``since_len`` ids of the order. None
        when a structural change happened or the write log no longer covers
        the gap — the consumer then rebuilds from :meth:`host_matrix`.
        Values are CURRENT slab copies (newest-wins compose)."""
        with self._lock.read():
            if self._rebuild_needed_at > since_version:
                return None
            if self._version == since_version:
                return HostDelta(self._version, [], None, [], None)
            # every version bump since `since_version` is either structural
            # (caught above) or a logged set_vector; if the bounded log's
            # oldest retained entry skips past since_version+1, writes in
            # the gap were evicted and coverage is broken
            if not self._log or self._log[0][0] > since_version + 1:
                return None
            # newest-first walk, stopping at the consumer's version: the
            # log holds up to 65536 entries and a steady-state delta is a
            # handful — O(delta), not O(log)
            changed_rows: set = set()
            for v, row, _was_new in reversed(self._log):
                if v <= since_version:
                    break
                changed_rows.add(row)
            appended = [int(r) for r in self._rowmap[since_len: self._n_pos]]
            changed = sorted(changed_rows - set(appended))
            changed_vals = (
                self._slab[np.asarray(changed, dtype=np.int64)]
                if changed else None
            )
            appended_rows = np.asarray(appended, dtype=np.int64)
            appended_vals = (
                self._slab[appended_rows] if appended else None
            )
            return HostDelta(
                self._version, self._decode_ids(changed), changed_vals,
                self._decode_ids(appended), appended_vals,
                appended_rows=appended_rows, slab=self._slab,
            )

    # -- device materialization --------------------------------------------
    def materialize(self):
        """(ids, device matrix) snapshot; incremental when only point updates
        happened since the cache (one batched scatter + one append — never a
        full host→device upload), full rebuild on structural changes.

        Race-free: the version and pending set are read under the read lock
        (writers excluded), and the cache critical section is serialized, so
        a concurrent write strictly invalidates this materialization. The
        full-rebuild device upload happens OUTSIDE the locks (it can take
        seconds at reference scale and must not stall UP-consumer writes);
        the incremental path only dispatches async device ops and commits
        inline."""
        import jax.numpy as jnp

        with self._lock.read(), self._cache_lock:
            version = self._version
            if self._cached_version == version:
                return self._cached_ids, self._cached_matrix
            pending, self._pending = self._pending, set()
            if (
                self._cached_matrix is not None
                and self._rebuild_needed_at <= self._cached_version
                and pending
            ):
                cached_len = len(self._cached_ids)
                # appended rows keep INSERTION order: the order's tail past
                # the cached length is exactly the new rows, in sequence
                new_rows = [int(r) for r in
                            self._rowmap[cached_len: self._n_pos]]
                changed_idx, changed_rows = [], []
                for row in pending:
                    pos = int(self._pos_of_row[row])
                    if pos < cached_len:
                        changed_idx.append(pos)
                        changed_rows.append(row)
                # ONE host gather covering the whole delta (counted by the
                # upload-seam tests), split into scatter + append
                vals = _host_gather(self._slab, changed_rows + new_rows)
                changed_vals = vals[: len(changed_rows)]
                new_vecs = vals[len(changed_rows):]
                new_ids = self._decode_ids(new_rows)
                prev_mat = self._cached_matrix
                mat = prev_mat
                if changed_idx:
                    mat = mat.at[jnp.asarray(changed_idx, dtype=jnp.int32)].set(
                        jnp.asarray(changed_vals)
                    )
                if new_ids:
                    mat = jnp.concatenate([mat, jnp.asarray(new_vecs)])
                # new list: snapshots holding the previous ids list stay valid
                ids = self._cached_ids + new_ids
                self._transitions.append(Transition(
                    prev_mat, mat,
                    np.asarray(changed_idx, dtype=np.int64), len(new_ids),
                ))
                self._cached_ids = ids
                self._cached_matrix = mat
                self._cached_version = version
                return ids, mat

            # full rebuild (first build, bulk handoff, removals, GC):
            # capture the host copy under the locks; the device upload AND
            # the per-row Python id decode — both expensive at reference
            # scale — run outside so UP-consumer writes are never stalled
            # (the captured index object's row→id bindings are frozen:
            # rows are never recycled, structural changes swap in fresh
            # slab/index objects)
            rows = self._live_rows().copy()
            index = self._ids
            host = (
                _host_gather(self._slab, rows)
                if rows.size
                else np.zeros((0, 0), dtype=np.float32)
            )
        dec = index.decode
        ids = [dec(int(r)) for r in rows]
        mat = jnp.asarray(host) if host.size else None
        with self._cache_lock:
            if version > self._cached_version:
                self._cached_ids = ids
                self._cached_matrix = mat
                self._cached_version = version
                self._transitions.clear()
            return self._cached_ids, self._cached_matrix

    def delta_since(self, from_mat, to_mat) -> "tuple[np.ndarray, int] | None":
        """Compose the recorded incremental steps from ``from_mat`` up to
        ``to_mat``: (changed row indices within from_mat's rows, rows
        appended). None when the chain is broken (full rebuild happened, a
        generation was garbage-collected, or either matrix is unknown) — the
        consumer then rebuilds its derived state from scratch."""
        with self._cache_lock:
            chain = list(self._transitions)
        if from_mat is to_mat:
            return np.empty(0, dtype=np.int64), 0
        start = next(
            (i for i, t in enumerate(chain) if t.prev_ref() is from_mat), None
        )
        if start is None:
            return None
        # continuity within the log is structural (each step's prev IS the
        # previous step's output, and a full rebuild clears the log), so
        # intermediate generations need no liveness check — only the two
        # endpoints, which the caller holds alive, anchor the walk
        n_base = from_mat.shape[0]
        changed: set = set()
        n_new = 0
        for t in chain[start:]:
            # rows rewritten inside the appended tail are covered by the
            # consumer's whole-tail refresh; only base rows need listing
            changed.update(int(i) for i in t.changed_idx if i < n_base)
            n_new += t.n_new
            if t.new_ref() is to_mat:
                return np.asarray(sorted(changed), dtype=np.int64), n_new
        return None

    def get_vtv(self):
        """Gramian V^T V (FeatureVectors.getVTV). When the device
        materialization cache is CURRENT (f32/bf16 serving — y_snapshot
        keeps it fresh) the matmul runs on the device matrix that already
        exists: no slab copy, no store-lock hold. Otherwise — the speed
        tier and the int8 serving mode, where no device f32 copy may be
        forced into HBM — it computes from the slab with host BLAS."""
        with self._lock.read():
            with self._cache_lock:
                mat = (
                    self._cached_matrix
                    if self._cached_version == self._version else None
                )
            host = None
            if mat is None:
                if self._slab is None or self._n_pos == 0:
                    return None
                host = self._slab[self._live_rows()]
        if mat is not None:
            return np.asarray(mat.T @ mat)  # device matmul, no locks held
        return np.matmul(host.T, host)
