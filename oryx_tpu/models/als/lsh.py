"""Locality-sensitive hashing for approximate top-N (sample-rate semantics).

Equivalent of the reference's LocalitySensitiveHash
(app/oryx-app-serving/.../als/model/LocalitySensitiveHash.java:41-177):
``oryx.als.sample-rate`` < 1 trades recall for speed by only scoring items
whose sign-bit hash (under near-orthogonal random hyperplanes) lies within
``max_bits_differing`` of the query's hash. Hash count and allowed bit
difference are chosen so the candidate-bucket fraction approximates the
sample rate.

TPU re-design: the reference scans candidate *partitions* with a thread pool;
here items carry a bucket id, and top-N masks non-candidate rows to −∞ inside
the same single matmul+top_k device program — the knob preserves the
reference's approximation semantics, while TPU speed comes from the batched
matmul itself (serving.py).
"""

from __future__ import annotations

import math

import numpy as np

from oryx_tpu.common import rand

MAX_HASHES = 16


def _candidate_fraction(n_hashes: int, max_bits_differing: int) -> float:
    total = sum(math.comb(n_hashes, d) for d in range(max_bits_differing + 1))
    return total / (1 << n_hashes)


def choose_hash_config(sample_rate: float) -> tuple[int, int]:
    """Smallest hash count + allowed differing bits whose candidate fraction
    is closest to (without exceeding much) the sample rate
    (LocalitySensitiveHash.java:41-74)."""
    if sample_rate >= 1.0:
        return 0, 0
    best = (1, 0)
    best_err = float("inf")
    for n in range(1, MAX_HASHES + 1):
        for d in range(n):
            frac = _candidate_fraction(n, d)
            if frac <= sample_rate:
                err = sample_rate - frac
                if err < best_err:
                    best_err = err
                    best = (n, d)
    return best


class LocalitySensitiveHash:
    def __init__(self, sample_rate: float, features: int):
        self.sample_rate = sample_rate
        self.features = features
        self.num_hashes, self.max_bits_differing = choose_hash_config(sample_rate)
        rng = rand.get_random()
        if self.num_hashes:
            # near-orthogonal random hyperplanes (:80-105)
            m = rng.standard_normal((self.num_hashes, features)).astype(np.float32)
            q, _ = np.linalg.qr(m.T) if features >= self.num_hashes else (m.T, None)
            self.hyperplanes = np.ascontiguousarray(q.T[: self.num_hashes], dtype=np.float32)
        else:
            self.hyperplanes = np.zeros((0, features), dtype=np.float32)

    @property
    def num_buckets(self) -> int:
        return 1 << self.num_hashes

    def get_index_for(self, vector: np.ndarray) -> int:
        """Sign-bit hash (:142)."""
        if not self.num_hashes:
            return 0
        bits = (self.hyperplanes @ np.asarray(vector, dtype=np.float32)) > 0
        idx = 0
        for b in bits:
            idx = (idx << 1) | int(b)
        return idx

    def assign_buckets(self, matrix: np.ndarray) -> np.ndarray:
        """Bucket id per row, vectorized."""
        if not self.num_hashes:
            return np.zeros(len(matrix), dtype=np.int32)
        bits = (matrix @ self.hyperplanes.T) > 0  # (n, h)
        weights = (1 << np.arange(self.num_hashes - 1, -1, -1)).astype(np.int32)
        return (bits.astype(np.int32) @ weights).astype(np.int32)

    def get_candidate_indices(self, vector: np.ndarray) -> np.ndarray:
        """All bucket ids within max_bits_differing of the query hash (:156-177)."""
        base = self.get_index_for(vector)
        if not self.num_hashes:
            return np.asarray([0], dtype=np.int32)
        n = self.num_buckets
        all_ids = np.arange(n, dtype=np.int32)
        xor = all_ids ^ base
        popcount = np.zeros(n, dtype=np.int32)
        v = xor.copy()
        while v.any():
            popcount += v & 1
            v >>= 1
        return all_ids[popcount <= self.max_bits_differing]
