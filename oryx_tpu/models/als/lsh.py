"""Locality-sensitive hashing for approximate top-N (sample-rate semantics).

Equivalent of the reference's LocalitySensitiveHash
(app/oryx-app-serving/.../als/model/LocalitySensitiveHash.java:41-177):
``oryx.als.sample-rate`` < 1 trades recall for speed by only scoring items
whose sign-bit hash (under near-orthogonal random hyperplanes) lies within
``max_bits_differing`` of the query's hash. Hash count and allowed bit
difference are chosen so the candidate-bucket fraction approximates the
sample rate.

TPU re-design: the reference scans candidate *partitions* with a thread pool;
here items carry a bucket id, and top-N masks non-candidate rows to −∞ inside
the same single matmul+top_k device program — the knob preserves the
reference's approximation semantics, while TPU speed comes from the batched
matmul itself (serving.py).
"""

from __future__ import annotations

import math

import numpy as np

from oryx_tpu.common import rand

MAX_HASHES = 16


def _candidate_fraction(n_hashes: int, max_bits_differing: int) -> float:
    total = sum(math.comb(n_hashes, d) for d in range(max_bits_differing + 1))
    return total / (1 << n_hashes)


def choose_hash_config(sample_rate: float) -> tuple[int, int]:
    """Smallest hash count + allowed differing bits whose candidate fraction
    is closest to (without exceeding much) the sample rate
    (LocalitySensitiveHash.java:41-74)."""
    if sample_rate >= 1.0:
        return 0, 0
    best = (1, 0)
    best_err = float("inf")
    for n in range(1, MAX_HASHES + 1):
        for d in range(n):
            frac = _candidate_fraction(n, d)
            if frac <= sample_rate:
                err = sample_rate - frac
                if err < best_err:
                    best_err = err
                    best = (n, d)
    return best


class LocalitySensitiveHash:
    def __init__(self, sample_rate: float, features: int):
        self.sample_rate = sample_rate
        self.features = features
        self.num_hashes, self.max_bits_differing = choose_hash_config(sample_rate)
        # LUT row cache allocated eagerly: get_candidate_lut runs on the
        # coalescer's executor threads concurrently, and lazy allocation
        # would race (one thread's fresh array clobbering another's fills).
        # Concurrent fills of the same row write identical values, and the
        # filled flag is set only AFTER its row, so readers are safe.
        self._popcounts: "np.ndarray | None" = None
        if 0 < self.num_hashes and self.num_buckets <= 8192:
            self._lut_rows = np.zeros(
                (self.num_buckets, self.num_buckets), dtype=bool
            )
            self._lut_filled = np.zeros(self.num_buckets, dtype=bool)
        else:
            self._lut_rows = None
            self._lut_filled = None
        rng = rand.get_random()
        if self.num_hashes:
            # near-orthogonal random hyperplanes (:80-105)
            m = rng.standard_normal((self.num_hashes, features)).astype(np.float32)
            q, _ = np.linalg.qr(m.T) if features >= self.num_hashes else (m.T, None)
            self.hyperplanes = np.ascontiguousarray(q.T[: self.num_hashes], dtype=np.float32)
        else:
            self.hyperplanes = np.zeros((0, features), dtype=np.float32)

    @property
    def num_buckets(self) -> int:
        return 1 << self.num_hashes

    def get_index_for(self, vector: np.ndarray) -> int:
        """Sign-bit hash (:142)."""
        if not self.num_hashes:
            return 0
        bits = (self.hyperplanes @ np.asarray(vector, dtype=np.float32)) > 0
        idx = 0
        for b in bits:
            idx = (idx << 1) | int(b)
        return idx

    def assign_buckets(self, matrix: np.ndarray) -> np.ndarray:
        """Bucket id per row, vectorized."""
        if not self.num_hashes:
            return np.zeros(len(matrix), dtype=np.int32)
        bits = (matrix @ self.hyperplanes.T) > 0  # (n, h)
        weights = (1 << np.arange(self.num_hashes - 1, -1, -1)).astype(np.int32)
        return (bits.astype(np.int32) @ weights).astype(np.int32)

    def _popcount_table(self) -> np.ndarray:
        """popcount of every bucket id, built once per instance (idempotent
        under concurrent builds: identical values)."""
        if self._popcounts is None:
            v = np.arange(self.num_buckets, dtype=np.int32)
            pc = np.zeros(self.num_buckets, dtype=np.int32)
            while v.any():
                pc += v & 1
                v = v >> 1
            self._popcounts = pc
        return self._popcounts

    def get_candidate_indices(self, vector: np.ndarray) -> np.ndarray:
        """All bucket ids within max_bits_differing of the query hash (:156-177)."""
        if not self.num_hashes:
            return np.asarray([0], dtype=np.int32)
        base = self.get_index_for(vector)
        all_ids = np.arange(self.num_buckets, dtype=np.int32)
        pc = self._popcount_table()[all_ids ^ base]
        return all_ids[pc <= self.max_bits_differing]

    def get_candidate_lut(self, qs: np.ndarray) -> np.ndarray:
        """(B, num_buckets) bool candidate table for a BATCH of queries.

        A query's row depends only on its bucket id, so rows memoize in a
        dense (num_buckets, num_buckets) bool table filled lazily per
        distinct base bucket (≤ 64 MB at 8192 buckets; beyond that the
        direct vectorized xor/popcount computation is used) — steady-state
        builds are then one row gather instead of per-query bit loops."""
        qs = np.atleast_2d(np.asarray(qs, dtype=np.float32))
        if not self.num_hashes:
            return np.ones((len(qs), 1), dtype=bool)
        base = self.assign_buckets(qs)  # (B,)
        n = self.num_buckets
        all_ids = np.arange(n, dtype=np.int32)
        pc = self._popcount_table()
        if self._lut_rows is None:  # table would exceed ~64 MB: direct
            return pc[base[:, None] ^ all_ids[None, :]] <= self.max_bits_differing
        missing = np.unique(base[~self._lut_filled[base]])
        if missing.size:
            self._lut_rows[missing] = (
                pc[missing[:, None] ^ all_ids[None, :]]
                <= self.max_bits_differing
            )
            self._lut_filled[missing] = True
        return self._lut_rows[base]
