"""Rescoring SPI: per-request hooks to filter/adjust recommendation results.

Equivalent of the reference's oryx-app-api (app/oryx-app-api/.../als/
RescorerProvider.java, Rescorer.java, MultiRescorer.java:90,
MultiRescorerProvider.java:142, AbstractRescorerProvider.java): user-supplied
classes named by ``oryx.als.rescorer-provider-class`` adjust scores or filter
IDs for /recommend, /recommendToAnonymous, /mostPopularItems and
/mostActiveUsers.
"""

from __future__ import annotations

import abc
from typing import Sequence

from oryx_tpu.common import classutils


class Rescorer(abc.ABC):
    @abc.abstractmethod
    def rescore(self, id_: str, score: float) -> float:
        """New score, NaN to filter (Rescorer.java)."""

    def is_filtered(self, id_: str) -> bool:
        import math

        return math.isnan(self.rescore(id_, 0.0))


class RescorerProvider(abc.ABC):
    def get_recommend_rescorer(self, user_ids: Sequence[str], args: Sequence[str]):
        return None

    def get_recommend_to_anonymous_rescorer(self, item_ids: Sequence[str], args: Sequence[str]):
        return None

    def get_most_popular_items_rescorer(self, args: Sequence[str]):
        return None

    def get_most_active_users_rescorer(self, args: Sequence[str]):
        return None


AbstractRescorerProvider = RescorerProvider


class MultiRescorer(Rescorer):
    """Composes several rescorers (MultiRescorer.java:90)."""

    def __init__(self, rescorers: Sequence[Rescorer]):
        self.rescorers = [r for r in rescorers if r is not None]

    def rescore(self, id_: str, score: float) -> float:
        import math

        for r in self.rescorers:
            score = r.rescore(id_, score)
            if math.isnan(score):
                return score
        return score

    def is_filtered(self, id_: str) -> bool:
        return any(r.is_filtered(id_) for r in self.rescorers)

    @staticmethod
    def of(rescorers: Sequence["Rescorer | None"]) -> "Rescorer | None":
        present = [r for r in rescorers if r is not None]
        if not present:
            return None
        if len(present) == 1:
            return present[0]
        return MultiRescorer(present)


class MultiRescorerProvider(RescorerProvider):
    """Composes several providers (MultiRescorerProvider.java:142)."""

    def __init__(self, providers: Sequence[RescorerProvider]):
        self.providers = list(providers)

    def get_recommend_rescorer(self, user_ids, args):
        return MultiRescorer.of([p.get_recommend_rescorer(user_ids, args) for p in self.providers])

    def get_recommend_to_anonymous_rescorer(self, item_ids, args):
        return MultiRescorer.of(
            [p.get_recommend_to_anonymous_rescorer(item_ids, args) for p in self.providers]
        )

    def get_most_popular_items_rescorer(self, args):
        return MultiRescorer.of([p.get_most_popular_items_rescorer(args) for p in self.providers])

    def get_most_active_users_rescorer(self, args):
        return MultiRescorer.of([p.get_most_active_users_rescorer(args) for p in self.providers])


def load_rescorer_providers(config) -> "RescorerProvider | None":
    """Load the configured provider class(es)
    (ALSServingModelManager.loadRescorerProviders:146-163)."""
    names = config.get("oryx.als.rescorer-provider-class", None)
    if not names:
        return None
    if isinstance(names, str):
        names = [n.strip() for n in names.split(",") if n.strip()]
    providers = [
        classutils.load_instance_of(name, RescorerProvider, config) for name in names
    ]
    if len(providers) == 1:
        return providers[0]
    return MultiRescorerProvider(providers)
