"""Device-resident IVF (inverted-file) candidate generation over the
factor arena — the sublinear serving scan.

The int8 flat scan (PR 9) still reads every item row per query batch: at
21M x 250f that is ~5.3 GB of HBM per pass, so chip memory bandwidth caps
fleet qps no matter how many replicas the controller adds. This module
clusters the item factors with the in-tree k-means trainer
(models/kmeans/train.fit_index_centroids — deterministic seed, bounded
iterations, empty-cluster reseeding) and keeps the catalog as

  * ``centroids``   (C, k)    f32  — one row per cell,
  * ``cell_pos``    (C, L)    i32  — snapshot positions, -1-padded,
  * ``cell_q``      (C, L, k) i8   — per-row-scaled int8 factors,
  * ``cell_scale``  (C, L)    f32  — the per-row scales,
  * ``cell_norms``  (C, L)    f32  — exact norms (cosine path),
  * ``cell_buckets``(C, L)    i32  — LSH buckets (optional),

all in HBM. A query batch probes the top-P cells by centroid dot product
(one (B,k)x(k,C) matmul), gathers ONLY those cells' int8 rows (a
``lax.scan`` over the P probe columns keeps the gather transient at
B·L·k bytes), scores them quantized, and feeds the top
``rescore-factor x how_many`` candidates to the SAME exact-f32 arena-slab
rescore the flat int8 path uses. Per-query HBM traffic drops from n·k to
P·L·k bytes — sublinear in the catalog once C grows with sqrt(n).

Cells are maintained incrementally from the speed tier's fold-in deltas
riding the arena's write log (``delta_info``): a microbatch requantizes
and reassigns only the rows it touched and rewrites only the affected
cells' device slices — bit-identical to a full rebuild with the same
centroids (tests/test_ivf.py asserts this exactly). A cell overflowing
its padded width, or cell balance drifting past
``oryx.serving.index.rebalance-skew``, falls back to a full re-cluster.

Candidate generation and probing run under their OWN cost keys
(``als.ivf_probe/...``, ``als.ivf_scan/...``) so live MFU / bandwidth
attribution separates the probe from the exact rescore, and the pow2
(batch, probes) signatures ride the serving warm ladder exactly like the
flat programs (zero request-path compiles after a MODEL handoff).
"""

from __future__ import annotations

import functools
import logging
import math

import jax
import jax.numpy as jnp
import numpy as np

from oryx_tpu.common import compilecache
from oryx_tpu.common import metrics as metrics_mod
from oryx_tpu.common import profiling

log = logging.getLogger(__name__)

_INDEX_CELLS = metrics_mod.default_registry().counter(
    "oryx_index_cells_total",
    "IVF index cells created across index (re)builds",
)
_INDEX_PROBED = metrics_mod.default_registry().counter(
    "oryx_index_probed_cells_total",
    "IVF cells probed (batch size x probe width, per candidate scan)",
)
_INDEX_CANDIDATES = metrics_mod.default_registry().counter(
    "oryx_index_candidate_rows_total",
    "Candidate rows emitted by IVF scans for exact f32 rescore",
)
_INDEX_SKEW = metrics_mod.default_registry().gauge(
    "oryx_index_cell_skew",
    "Largest-cell occupancy over the mean (n/cells); the rebalance-skew "
    "bound triggers a re-cluster when this drifts past it",
)

#: Training subsample cap, per cell: k-means fits on at most
#: ``_TRAIN_PER_CELL * cells`` rows (deterministically sampled) — centroid
#: quality saturates well below that while full-catalog training would put
#: an O(n·C·k) matmul per Lloyd sweep on the rebuild path.
_TRAIN_PER_CELL = 64

#: Chunk of rows assigned to cells per device call during a full build —
#: bounds the (chunk, C) distance transient at reference scale.
_ASSIGN_CHUNK = 1 << 16

_KMEANS_SEED = 0x0f1e


def _round_up_pow2(n: int) -> int:
    return 1 << max(0, (n - 1).bit_length())


def auto_cells(n: int) -> int:
    """Default cell count: the power of two nearest sqrt(n) — the classic
    IVF sizing (probe cost C + scan cost P·n/C balance at C ~ sqrt(n))."""
    if n <= 1:
        return 1
    return max(1, 1 << int(round(math.log2(math.sqrt(n)))))


def probe_cost_key(batch: int, cells: int, probes: int) -> str:
    """Cost-accounting signature of the centroid-probe program."""
    return f"als.ivf_probe/b{batch}/c{cells}/p{probes}"


def scan_cost_key(batch: int, cells: int, probes: int,
                  excl: bool, lsh: bool) -> str:
    """Cost-accounting signature of the probed-cell candidate scan."""
    return (f"als.ivf_scan/b{batch}/c{cells}/p{probes}"
            + ("+excl" if excl else "") + ("+lsh" if lsh else ""))


# -- jitted programs ---------------------------------------------------------


@functools.partial(jax.jit, static_argnames=("probes",))
def _probe_cells(centroids, qs, probes: int):
    """Rank cells by centroid dot product and keep the top ``probes``:
    one (B,k)x(k,C) MXU matmul + top_k — the sublinear scan's only
    full-width-in-C work."""
    scores = jnp.matmul(
        qs, centroids.T, preferred_element_type=jnp.float32
    )  # (B, C)
    _, cells = jax.lax.top_k(scores, probes)
    return cells  # (B, P) int32


@functools.partial(jax.jit, static_argnames=("r",))
def _ivf_candidates(cell_pos, cell_q, cell_scale, qs, cells, excl, r: int):
    """Quantized scores over the probed cells only. ``cells`` is (B, P);
    a ``lax.scan`` over the P probe columns bounds the gather transient at
    one (B, L, k) int8 block — the per-step gathers ARE the scan's HBM
    traffic (P·L·k bytes per query vs n·k for the flat slab). Padding
    slots (cell_pos < 0) and per-query exclusions mask to -inf before the
    exact top-k over the (B, P·L) candidate pool."""

    def step(_, cell_col):  # cell_col: (B,) — one probe column
        pos = cell_pos[cell_col]       # (B, L) gather
        qm = cell_q[cell_col]          # (B, L, k) int8 gather
        sc = cell_scale[cell_col]      # (B, L)
        s = jnp.einsum(
            "bk,blk->bl", qs, qm.astype(qs.dtype),
            preferred_element_type=jnp.float32,
        ) * sc
        s = jnp.where(pos >= 0, s, -jnp.inf)
        if excl is not None:
            hit = (pos[:, :, None] == excl[:, None, :]).any(axis=-1)
            s = jnp.where(hit, -jnp.inf, s)
        return None, (s, pos)

    _, (scores, pos) = jax.lax.scan(step, None, cells.T)
    b = qs.shape[0]
    scores = jnp.moveaxis(scores, 0, 1).reshape(b, -1)  # (B, P·L)
    pos = jnp.moveaxis(pos, 0, 1).reshape(b, -1)
    vals, ix = jax.lax.top_k(scores, r)
    return vals, jnp.take_along_axis(pos, ix, axis=1)


@functools.partial(jax.jit, static_argnames=("r",))
def _ivf_candidates_masked(cell_pos, cell_q, cell_scale, cell_buckets,
                           lut, qs, cells, excl, r: int):
    """Per-query-LUT (LSH) variant: the probed slots' buckets gather along
    with the factors and filter through the (B, num_buckets) table."""

    def step(_, cell_col):
        pos = cell_pos[cell_col]
        qm = cell_q[cell_col]
        sc = cell_scale[cell_col]
        bk = cell_buckets[cell_col]    # (B, L)
        s = jnp.einsum(
            "bk,blk->bl", qs, qm.astype(qs.dtype),
            preferred_element_type=jnp.float32,
        ) * sc
        valid = jnp.take_along_axis(lut, bk, axis=1)
        s = jnp.where(valid & (pos >= 0), s, -jnp.inf)
        if excl is not None:
            hit = (pos[:, :, None] == excl[:, None, :]).any(axis=-1)
            s = jnp.where(hit, -jnp.inf, s)
        return None, (s, pos)

    _, (scores, pos) = jax.lax.scan(step, None, cells.T)
    b = qs.shape[0]
    scores = jnp.moveaxis(scores, 0, 1).reshape(b, -1)
    pos = jnp.moveaxis(pos, 0, 1).reshape(b, -1)
    vals, ix = jax.lax.top_k(scores, r)
    return vals, jnp.take_along_axis(pos, ix, axis=1)


@functools.partial(jax.jit, static_argnames=("r",))
def _ivf_cosine_candidates(cell_pos, cell_q, cell_scale, cell_norms,
                           lut_union, cell_buckets, qs, q_norms, cells,
                           r: int):
    """Mean-cosine candidates for ONE request's query-vector set: ``cells``
    is (P,), ``qs`` (Q, k). Norms are exact f32 (arena-derived at snapshot
    time), so only the dot is quantized — same contract as the flat path."""

    def step(_, c):  # c: scalar cell id
        pos = cell_pos[c]              # (L,)
        qm = cell_q[c]                 # (L, k)
        sc = cell_scale[c]             # (L,)
        nm = cell_norms[c]             # (L,)
        sims = (jnp.matmul(
            qs, qm.T.astype(qs.dtype), preferred_element_type=jnp.float32
        ) * sc[None, :]) / jnp.maximum(
            nm[None, :] * q_norms[:, None], 1e-12
        )  # (Q, L)
        s = jnp.where(pos >= 0, jnp.mean(sims, axis=0), -jnp.inf)
        if lut_union is not None:
            s = jnp.where(lut_union[cell_buckets[c]], s, -jnp.inf)
        return None, (s, pos)

    _, (scores, pos) = jax.lax.scan(step, None, cells)
    scores = scores.reshape(-1)        # (P·L,)
    pos = pos.reshape(-1)
    vals, ix = jax.lax.top_k(scores, r)
    return vals, pos[ix]


@jax.jit
def _assign_cells(rows, centroids):
    """Nearest-centroid cell per row (squared-Euclidean via the matmul
    expansion) — the build/maintenance assignment rule. int32 so the host
    cell tables index straight off it."""
    d2 = (
        (rows * rows).sum(axis=1, keepdims=True)
        - 2.0 * rows @ centroids.T
        + (centroids * centroids).sum(axis=1)[None, :]
    )
    return jnp.argmin(d2, axis=1).astype(jnp.int32)


# -- snapshot ----------------------------------------------------------------


class IVFSnapshot:
    """Immutable device view of Y as an inverted-file index (int8 cells +
    f32 centroids), plus the host-side mirrors (flat quantized rows, the
    assignment, the cell tables) that make incremental maintenance a
    per-affected-cell device scatter instead of a rebuild.

    Shares the flat int8 snapshot's duck type where serving touches it:
    ``ids`` / ``id_to_idx`` / ``n`` / ``version`` / ``gather_rows`` (the
    pinned arena-slab rescore view) / ``cost_keys_attempted``; ``mat`` /
    ``score_mat`` stay None — no flat factor copy of any dtype lands in
    HBM in this mode."""

    def __init__(self, ids, version: int, *, centroids_np=None, assign=None,
                 q_np=None, scale_np=None, norms_np=None, buckets_np=None,
                 cell_pos_np=None, cell_len=None, cell_width: int = 0,
                 probes: int = 8, skew_bound: float = 4.0,
                 centroids=None, cell_pos=None, cell_q=None,
                 cell_scale=None, cell_norms=None, cell_buckets=None,
                 slab=None, slab_rows=None,
                 prev: "IVFSnapshot | None" = None,
                 appended: "list[str] | None" = None):
        self.ids = ids
        self.version = version
        # host mirrors (maintenance only — the request path never reads them)
        self.centroids_np = centroids_np   # (C, k) f32
        self.assign = assign               # (n,) i32 snapshot position → cell
        self.q_np = q_np                   # (n, k) i8 flat quantized rows
        self.scale_np = scale_np           # (n,) f32
        self.norms_np = norms_np           # (n,) f32
        self.buckets_np = buckets_np       # (n,) i32 or None
        self.cell_pos_np = cell_pos_np     # (C, L) i32, -1 pad, sorted asc
        self.cell_len = cell_len           # (C,) i32
        self.cell_width = cell_width       # L (pow2)
        self.probes = probes               # default probe width P (pow2)
        self.skew_bound = float(skew_bound)
        # skew at (re)build time: the drift trigger fires on skew past
        # max(bound, 1.25 x this) — inherently skewed catalogs whose
        # re-cluster cannot balance below the bound must not rebuild on
        # every microbatch
        self.base_skew = 1.0
        # device arrays (the serving scan's inputs)
        self.centroids = centroids         # (C, k) f32
        self.cell_pos = cell_pos           # (C, L) i32
        self.cell_q = cell_q               # (C, L, k) i8
        self.cell_scale = cell_scale       # (C, L) f32
        self.cell_norms = cell_norms       # (C, L) f32
        self.cell_buckets = cell_buckets   # (C, L) i32 or None
        # pinned exact-rescore view (same contract as the flat int8
        # snapshot: the slab object + row indices captured in `ids` order)
        self.slab = slab
        self.slab_rows = slab_rows
        # flat-snapshot duck type for serving's guards
        self.mat = None
        self.score_mat = None
        self.sharded_mat = None
        self.sharded_buckets = None
        self.mesh = None
        self.buckets = None
        if prev is not None and appended is not None:
            self.id_to_idx = prev.id_to_idx
            for i in range(len(prev.ids), len(ids)):
                self.id_to_idx[ids[i]] = i
        else:
            self.id_to_idx = {s: i for i, s in enumerate(ids)}
        if (prev is not None
                and getattr(prev.cell_q, "shape", None)
                == getattr(cell_q, "shape", None)):
            self.cost_keys_attempted = prev.cost_keys_attempted
        else:
            self.cost_keys_attempted: set = set()
        profiling.register_quantized(self)
        if cell_len is not None and len(ids):
            _INDEX_SKEW.set(self.skew())

    @property
    def n(self) -> int:
        return len(self.ids)

    @property
    def n_cells(self) -> int:
        return 0 if self.centroids_np is None else len(self.centroids_np)

    def skew(self) -> float:
        """Largest cell occupancy over the mean (n / C)."""
        if self.cell_len is None or self.n == 0 or self.n_cells == 0:
            return 1.0
        return float(self.cell_len.max()) / max(self.n / self.n_cells, 1e-9)

    def quantized_nbytes(self) -> int:
        """Device bytes of the quantized cells (the
        oryx_device_quantized_factor_bytes gauge, same as the flat slab)."""
        total = 0
        for arr in (self.cell_q, self.cell_scale):
            total += int(getattr(arr, "nbytes", 0) or 0)
        return total

    def device_nbytes(self) -> int:
        """All device bytes the index holds (device_factor_bytes)."""
        total = 0
        for arr in (self.centroids, self.cell_pos, self.cell_q,
                    self.cell_scale, self.cell_norms, self.cell_buckets):
            total += int(getattr(arr, "nbytes", 0) or 0)
        return total

    def gather_rows(self, positions: np.ndarray) -> np.ndarray:
        """Exact f32 rows for snapshot positions, off the PINNED slab."""
        pos = np.clip(np.asarray(positions, dtype=np.int64), 0, self.n - 1)
        return self.slab[self.slab_rows[pos]]

    # -- construction --------------------------------------------------------

    @classmethod
    def build(cls, ids, host: np.ndarray, version: int, lsh,
              row_view: tuple, prev: "IVFSnapshot | None" = None, *,
              cells: int = 0, probes: int = 8, skew_bound: float = 4.0,
              centroids: "np.ndarray | None" = None, cell_width: int = 0):
        """Full index build from one host matrix: quantize (chunked),
        cluster (deterministic-seeded k-means on a bounded subsample unless
        ``centroids`` are given), assign every row, lay the cells out
        sorted-ascending and pow2-padded, and land the device arrays."""
        from oryx_tpu.models.als.serving import _quantize_rows

        n = len(ids)
        slab, slab_rows = row_view
        if n == 0 or host.size == 0:
            return cls(list(ids), version, probes=probes,
                       skew_bound=skew_bound)
        k = host.shape[1]
        q = np.empty((n, k), dtype=np.int8)
        scale = np.empty(n, dtype=np.float32)
        norms = np.empty(n, dtype=np.float32)
        chunk = 1 << 16
        for a in range(0, n, chunk):
            b = min(n, a + chunk)
            q[a:b], scale[a:b] = _quantize_rows(host[a:b])
            norms[a:b] = np.linalg.norm(host[a:b], axis=1)
        buckets_np = None
        if lsh and lsh.num_hashes:
            # np.array (not asarray): device-backed results come back
            # read-only and the incremental path writes these in place
            buckets_np = np.array(lsh.assign_buckets(host), dtype=np.int32)

        c = _round_up_pow2(max(1, cells if cells > 0 else auto_cells(n)))
        c = min(c, 1 << (n.bit_length() - 1))  # pow2, at most n
        assign = None
        if centroids is None:
            from oryx_tpu.models.kmeans.train import fit_index_centroids

            cap = max(_TRAIN_PER_CELL * c, 1 << 14)
            if n > cap:
                rng = np.random.default_rng(_KMEANS_SEED)
                sample = host[rng.choice(n, cap, replace=False)]
                centroids, _, _ = fit_index_centroids(
                    sample, c, seed=_KMEANS_SEED
                )
            else:
                centroids, _, assign = fit_index_centroids(
                    host, c, seed=_KMEANS_SEED
                )
        centroids = np.array(centroids, dtype=np.float32)
        c = len(centroids)
        if assign is not None:
            assign = np.array(assign, dtype=np.int32)  # writable copy
        if assign is None:
            assign = np.empty(n, dtype=np.int32)
            cent_dev = jnp.asarray(centroids)
            for a in range(0, n, _ASSIGN_CHUNK):
                b = min(n, a + _ASSIGN_CHUNK)
                assign[a:b] = np.asarray(
                    _assign_cells(jnp.asarray(host[a:b]), cent_dev)
                )
        cell_len = np.bincount(assign, minlength=c).astype(np.int32)
        width = cell_width if cell_width > 0 else _round_up_pow2(
            max(int(cell_len.max()) + (int(cell_len.max()) >> 2) + 4, 8)
        )
        if cell_len.max() > width:
            raise ValueError(
                f"cell_width {width} overflows (largest cell "
                f"{int(cell_len.max())})"
            )
        # canonical layout: members sorted ascending per cell (stable sort
        # groups by cell, positions stay ascending) — the invariant the
        # incremental path's in-place surgery preserves bit-exactly
        order = np.argsort(assign, kind="stable")
        cell_pos_np = np.full((c, width), -1, dtype=np.int32)
        offsets = np.zeros(c + 1, dtype=np.int64)
        offsets[1:] = np.cumsum(cell_len, dtype=np.int64)
        for j in range(c):
            members = order[offsets[j]:offsets[j + 1]]
            cell_pos_np[j, : len(members)] = members
        snap = cls(
            list(ids), version, centroids_np=centroids, assign=assign,
            q_np=q, scale_np=scale, norms_np=norms, buckets_np=buckets_np,
            cell_pos_np=cell_pos_np, cell_len=cell_len, cell_width=width,
            probes=max(1, min(_round_up_pow2(probes), c)),
            skew_bound=skew_bound,
            centroids=jnp.asarray(centroids),
            slab=slab, slab_rows=slab_rows, prev=prev,
        )
        snap._land_cells(np.arange(c, dtype=np.int64), full=True)
        snap.base_skew = snap.skew()
        _INDEX_CELLS.inc(c)
        _INDEX_SKEW.set(snap.base_skew)
        return snap

    def _cell_block(self, cell_ids: np.ndarray):
        """Host (A, L[, k]) blocks for ``cell_ids`` from the flat mirrors,
        with the padding values the device arrays carry (pos -1, q 0,
        scale/norm 1) — build and incremental maintenance share this so
        their device bytes are bit-identical by construction."""
        sub = self.cell_pos_np[cell_ids]                # (A, L)
        pad = sub < 0
        safe = np.clip(sub, 0, max(self.n - 1, 0))
        cq = self.q_np[safe]
        cq[pad] = 0
        cs = self.scale_np[safe]
        cs[pad] = 1.0
        cn = self.norms_np[safe]
        cn[pad] = 1.0
        cb = None
        if self.buckets_np is not None:
            cb = self.buckets_np[safe].astype(np.int32)
            cb[pad] = 0
        return sub, cq, cs, cn, cb

    def _land_cells(self, cell_ids: np.ndarray, full: bool = False) -> None:
        """Materialize ``cell_ids``' device slices: whole-array uploads on a
        full build, row scatters (functional ``.at[].set``) incrementally."""
        sub, cq, cs, cn, cb = self._cell_block(cell_ids)
        if full:
            self.cell_pos = jnp.asarray(sub)
            self.cell_q = jnp.asarray(cq)
            self.cell_scale = jnp.asarray(cs)
            self.cell_norms = jnp.asarray(cn)
            self.cell_buckets = jnp.asarray(cb) if cb is not None else None
            return
        ix = jnp.asarray(cell_ids)
        self.cell_pos = self.cell_pos.at[ix].set(jnp.asarray(sub))
        self.cell_q = self.cell_q.at[ix].set(jnp.asarray(cq))
        self.cell_scale = self.cell_scale.at[ix].set(jnp.asarray(cs))
        self.cell_norms = self.cell_norms.at[ix].set(jnp.asarray(cn))
        if self.cell_buckets is not None and cb is not None:
            self.cell_buckets = self.cell_buckets.at[ix].set(jnp.asarray(cb))

    @classmethod
    def from_delta(cls, prev: "IVFSnapshot", delta, lsh):
        """Incremental step off one composed arena delta: requantize and
        reassign ONLY the touched rows, splice them through the host cell
        tables (sorted-ascending order preserved), and rewrite only the
        affected cells' device slices. Returns None when a cell would
        overflow its padded width or the post-update balance drifts past
        ``skew_bound`` — the caller re-clusters (full rebuild, fresh
        centroids)."""
        from oryx_tpu.models.als.serving import _quantize_rows

        n_prev = prev.n
        n_new = n_prev + len(delta.appended_ids)
        if prev.cell_q is None or prev.centroids_np is None:
            return None
        # flat host mirrors: changed rows update in place (prev never reads
        # them again — the request path only touches device arrays and the
        # pinned slab), appends extend by copy
        q_np, scale_np, norms_np, buckets_np = (
            prev.q_np, prev.scale_np, prev.norms_np, prev.buckets_np
        )
        assign = prev.assign
        cell_pos_np, cell_len = prev.cell_pos_np, prev.cell_len
        width = prev.cell_width
        cent_dev = jnp.asarray(prev.centroids_np)
        affected: set[int] = set()

        changed_pos = np.asarray(
            [prev.id_to_idx[i] for i in delta.changed_ids
             if i in prev.id_to_idx],
            dtype=np.int64,
        )
        if len(changed_pos):
            qc, sc = _quantize_rows(delta.changed_vals)
            q_np[changed_pos] = qc
            scale_np[changed_pos] = sc
            norms_np[changed_pos] = np.linalg.norm(delta.changed_vals, axis=1)
            if buckets_np is not None:
                buckets_np[changed_pos] = lsh.assign_buckets(
                    delta.changed_vals
                )
            new_cells = np.asarray(_assign_cells(
                jnp.asarray(np.asarray(delta.changed_vals, dtype=np.float32)),
                cent_dev,
            ))
            for pos, nc in zip(changed_pos, new_cells):
                oc = int(assign[pos])
                affected.add(oc)
                if int(nc) != oc:
                    if not _splice(cell_pos_np, cell_len, oc, int(nc),
                                   int(pos), width):
                        return None
                    assign[pos] = nc
                    affected.add(int(nc))
        if delta.appended_ids:
            qa, sa = _quantize_rows(delta.appended_vals)
            q_np = np.concatenate([q_np, qa])
            scale_np = np.concatenate([scale_np, sa])
            norms_np = np.concatenate([
                norms_np, np.linalg.norm(delta.appended_vals, axis=1)
            ])
            if buckets_np is not None:
                buckets_np = np.concatenate([
                    buckets_np,
                    np.asarray(lsh.assign_buckets(delta.appended_vals),
                               dtype=np.int32),
                ])
            app_cells = np.asarray(_assign_cells(
                jnp.asarray(np.asarray(delta.appended_vals, dtype=np.float32)),
                cent_dev,
            ))
            assign = np.concatenate([assign, app_cells])
            for off, nc in enumerate(app_cells):
                if not _insert(cell_pos_np, cell_len, int(nc),
                               n_prev + off, width):
                    return None
                affected.add(int(nc))
        ids = prev.ids + delta.appended_ids
        slab_rows = (
            np.concatenate([prev.slab_rows,
                            np.asarray(delta.appended_rows, dtype=np.int64)])
            if len(delta.appended_ids) else prev.slab_rows
        )
        snap = cls(
            ids, delta.version, centroids_np=prev.centroids_np,
            assign=assign, q_np=q_np, scale_np=scale_np, norms_np=norms_np,
            buckets_np=buckets_np, cell_pos_np=cell_pos_np,
            cell_len=cell_len, cell_width=width, probes=prev.probes,
            skew_bound=prev.skew_bound, centroids=prev.centroids,
            cell_pos=prev.cell_pos, cell_q=prev.cell_q,
            cell_scale=prev.cell_scale, cell_norms=prev.cell_norms,
            cell_buckets=prev.cell_buckets, slab=delta.slab,
            slab_rows=slab_rows, prev=prev, appended=delta.appended_ids,
        )
        snap.base_skew = prev.base_skew
        if snap.skew() > max(snap.skew_bound, prev.base_skew * 1.25):
            log.info(
                "IVF cell balance drifted past %.1fx (%.2fx) — re-clustering",
                snap.skew_bound, snap.skew(),
            )
            return None
        if affected:
            snap._land_cells(np.fromiter(sorted(affected), dtype=np.int64))
        _INDEX_SKEW.set(snap.skew())
        return snap


def _splice(cell_pos_np, cell_len, old_cell: int, new_cell: int,
            pos: int, width: int) -> bool:
    """Move ``pos`` from one sorted cell row to another in place; False if
    the destination is full (caller rebuilds)."""
    ln = int(cell_len[old_cell])
    row = cell_pos_np[old_cell]
    i = int(np.searchsorted(row[:ln], pos))
    if i < ln and row[i] == pos:
        row[i:ln - 1] = row[i + 1:ln]
        row[ln - 1] = -1
        cell_len[old_cell] = ln - 1
    return _insert(cell_pos_np, cell_len, new_cell, pos, width)


def _insert(cell_pos_np, cell_len, cell: int, pos: int, width: int) -> bool:
    ln = int(cell_len[cell])
    if ln >= width:
        return False
    row = cell_pos_np[cell]
    i = int(np.searchsorted(row[:ln], pos))
    row[i + 1:ln + 1] = row[i:ln]
    row[i] = pos
    cell_len[cell] = ln + 1
    return True


# -- serving drivers ---------------------------------------------------------
# Called from ALSServingModel (models/als/serving.py) with the model as the
# first argument: exclusion padding, LSH luts, the exact rescore and host
# collection all reuse the model's flat-path helpers, so the IVF path
# differs ONLY in how candidates are generated.


def _candidate_width(model, snap: IVFSnapshot, probes: int,
                     want: int) -> int:
    """Rescore width for one scan: ``rescore-factor x want`` rounded up to
    a pow2 (signature stability), capped by what the probed cells can
    actually surface."""
    cap = min(snap.n, probes * snap.cell_width)
    return max(1, min(cap, _round_up_pow2(
        max(int(model.rescore_factor * want), 16)
    )))


def _scan(model, snap: IVFSnapshot, qs_host: np.ndarray, probes: int,
          r: int, excl, lut, register: bool):
    """One probe + candidate scan: (vals, idx) of width ``r`` in snapshot
    positions, quantized scores. Registers/records the probe and scan
    programs under their own cost keys so attribution separates candidate
    generation from the exact rescore."""
    qs = jnp.asarray(qs_host)
    b = qs_host.shape[0]
    c = snap.n_cells
    pk = probe_cost_key(b, c, probes)
    sk = scan_cost_key(b, c, probes, excl is not None, lut is not None)

    def scan_args(cells):
        if lut is not None:
            return (_ivf_candidates_masked,
                    (snap.cell_pos, snap.cell_q, snap.cell_scale,
                     snap.cell_buckets, lut, qs, cells, excl))
        return (_ivf_candidates,
                (snap.cell_pos, snap.cell_q, snap.cell_scale, qs, cells,
                 excl))

    if register and metrics_mod.default_registry().enabled:
        if pk not in snap.cost_keys_attempted:
            snap.cost_keys_attempted.add(pk)
            compilecache.aot_compile(
                _probe_cells, snap.centroids, qs, probes, cost_key=pk
            )
        if sk not in snap.cost_keys_attempted:
            snap.cost_keys_attempted.add(sk)
            fn, a = scan_args(
                jax.ShapeDtypeStruct((b, probes), jnp.int32)
            )
            compilecache.aot_compile(fn, *a, r, cost_key=sk)
    cells = _probe_cells(snap.centroids, qs, probes)
    fn, a = scan_args(cells)
    vals, idx = fn(*a, r)
    if register:
        profiling.costs().record(pk)
        profiling.costs().record(sk)
    _INDEX_PROBED.inc(b * probes)
    _INDEX_CANDIDATES.inc(b * r)
    return np.asarray(vals), np.asarray(idx)


def top_n(model, snap: IVFSnapshot, q_host: np.ndarray, how_many: int,
          offset: int, allowed, rescore, excluded) -> list:
    """Single-query IVF top-N with widening: rescore width doubles first
    (more candidates from the same probes), then the probe width doubles
    (pow2 signatures) until the request is satisfied or the scan covers
    the whole catalog (probes == cells is the flat scan, cell-shaped)."""
    want = how_many + offset
    excl = None
    if excluded:
        padded = model._excluded_indices(snap, [excluded], 1)
        if (padded >= 0).any():
            excl = jnp.asarray(padded)
    lut = (
        jnp.asarray(model._build_lut(q_host[None, :]))
        if model.lsh is not None and snap.cell_buckets is not None
        else None
    )
    probes = snap.probes
    r = _round_up_pow2(max(int(model.rescore_factor * want), 16))
    while True:
        cap = min(snap.n, probes * snap.cell_width)
        r_eff = min(r, cap)
        v, i = _scan(model, snap, q_host[None, :], probes, r_eff, excl,
                     lut, register=False)
        vals, idx = model._rescore_exact(snap, q_host[None, :], v, i)
        out = model._collect(snap, vals[0], idx[0], want, allowed, rescore)
        if len(out) >= want or (probes >= snap.n_cells
                                and r_eff >= snap.n):
            return out[offset:offset + how_many]
        if r_eff < cap:
            r = r_eff * 2  # widen the cut over the same probed cells
        else:
            probes = min(snap.n_cells, probes * 2)  # widen the probe set
            r = min(snap.n, r * 2)


def top_n_batch(model, snap: IVFSnapshot, qs_host: np.ndarray,
                how_many: int, alloweds, excluded,
                filtering: bool) -> list:
    """Batched IVF top-N: one probe matmul + one probed-cell scan for the
    whole batch, exact-f32-rescored from the arena slab before the final
    cut. Per-query widening (heavy host filtering) falls back to the
    single-query path, exactly like the flat int8 batch driver."""
    b = len(qs_host)
    use_excl = excluded is not None and any(e for e in excluded)
    excl = (
        jnp.asarray(model._excluded_indices(snap, excluded, b))
        if use_excl else None
    )
    lut = (
        jnp.asarray(model._build_lut(qs_host))
        if model.lsh is not None and snap.cell_buckets is not None
        else None
    )
    r = _candidate_width(model, snap, snap.probes, how_many)
    v, i = _scan(model, snap, qs_host, snap.probes, r, excl, lut,
                 register=True)
    vals, idx = model._rescore_exact(snap, qs_host, v, i)
    if not filtering:
        ids = snap.ids
        vb, ib = vals[:, :how_many], idx[:, :how_many]
        return [
            [(ids[int(i_)], float(v_)) for v_, i_ in zip(vb[q], ib[q])
             if np.isfinite(v_)]
            for q in range(b)
        ]
    out = []
    for q in range(b):
        allowed = alloweds[q] if alloweds else None
        got = model._collect(
            snap, vals[q], idx[q], how_many, allowed, None
        )[:how_many]
        if len(got) < how_many and r < snap.n:
            got = top_n(
                model, snap, qs_host[q], how_many, 0, allowed, None,
                excluded[q] if excluded else None,
            )
        out.append(got)
    return out


def top_n_cosine(model, snap: IVFSnapshot, qs_host: np.ndarray,
                 q_norms_host: np.ndarray, how_many: int, offset: int,
                 allowed, rescore) -> list:
    """Mean-cosine IVF top-N for one request's query-vector set: probes
    rank by the MEAN query direction, candidates rescore exact from the
    slab (cosine), widening mirrors :func:`top_n`."""
    want = how_many + offset
    qs = jnp.asarray(qs_host)
    q_norms = jnp.asarray(q_norms_host)
    lut_union = None
    if model.lsh is not None and snap.cell_buckets is not None:
        lu = np.zeros(model.lsh.num_buckets, dtype=bool)
        for qv in qs_host:
            lu[model.lsh.get_candidate_indices(qv)] = True
        lut_union = jnp.asarray(lu)
    probe_vec = np.mean(qs_host, axis=0, keepdims=True)
    probes = snap.probes
    r = _round_up_pow2(max(int(model.rescore_factor * want), 16))
    while True:
        cap = min(snap.n, probes * snap.cell_width)
        r_eff = min(r, cap)
        cells = _probe_cells(snap.centroids, jnp.asarray(probe_vec), probes)
        v, i = _ivf_cosine_candidates(
            snap.cell_pos, snap.cell_q, snap.cell_scale, snap.cell_norms,
            lut_union, snap.cell_buckets, qs, q_norms, cells[0], r_eff,
        )
        _INDEX_PROBED.inc(probes)
        _INDEX_CANDIDATES.inc(r_eff)
        vals, idx = model._rescore_exact(
            snap, qs_host, np.asarray(v)[None, :], np.asarray(i)[None, :],
            cosine=True,
        )
        out = model._collect(snap, vals[0], idx[0], want, allowed, rescore)
        if len(out) >= want or (probes >= snap.n_cells
                                and r_eff >= snap.n):
            return out[offset:offset + how_many]
        if r_eff < cap:
            r = r_eff * 2
        else:
            probes = min(snap.n_cells, probes * 2)
            r = min(snap.n, r * 2)


def warm_bucket(model, snap: IVFSnapshot, batch_size: int,
                how_many: int) -> None:
    """AOT-compile the IVF probe + scan signatures for one pow2 bucket —
    the per-bucket unit of the serving warm ladder, under the IVF cost
    keys. Both exclusion families warm (the default /recommend path always
    sends known-item exclusions at the floored pad width); the shared
    zero-batch executions in ALSServingModel.warm_bucket then populate the
    jit dispatch caches these programs actually serve from."""
    from oryx_tpu.models.als.serving import _EXCL_PAD_MIN

    probes = snap.probes
    c = snap.n_cells
    r = _candidate_width(model, snap, probes, how_many)
    qs_struct = jax.ShapeDtypeStruct(
        (batch_size, model.features), jnp.float32
    )
    excl_struct = jax.ShapeDtypeStruct(
        (batch_size, _EXCL_PAD_MIN), jnp.int32
    )
    cells_struct = jax.ShapeDtypeStruct((batch_size, probes), jnp.int32)
    pk = probe_cost_key(batch_size, c, probes)
    compilecache.aot_compile(
        _probe_cells, snap.centroids, qs_struct, probes, cost_key=pk
    )
    use_lsh = model.lsh is not None and snap.cell_buckets is not None
    keys = (scan_cost_key(batch_size, c, probes, False, use_lsh),
            scan_cost_key(batch_size, c, probes, True, use_lsh))
    if use_lsh:
        lut_struct = jax.ShapeDtypeStruct(
            (batch_size, model.lsh.num_buckets), jnp.bool_
        )
        compilecache.aot_compile(
            _ivf_candidates_masked, snap.cell_pos, snap.cell_q,
            snap.cell_scale, snap.cell_buckets, lut_struct, qs_struct,
            cells_struct, None, r, cost_key=keys[0],
        )
        compilecache.aot_compile(
            _ivf_candidates_masked, snap.cell_pos, snap.cell_q,
            snap.cell_scale, snap.cell_buckets, lut_struct, qs_struct,
            cells_struct, excl_struct, r, cost_key=keys[1],
        )
    else:
        compilecache.aot_compile(
            _ivf_candidates, snap.cell_pos, snap.cell_q, snap.cell_scale,
            qs_struct, cells_struct, None, r, cost_key=keys[0],
        )
        compilecache.aot_compile(
            _ivf_candidates, snap.cell_pos, snap.cell_q, snap.cell_scale,
            qs_struct, cells_struct, excl_struct, r, cost_key=keys[1],
        )
    snap.cost_keys_attempted.update({pk, *keys})
