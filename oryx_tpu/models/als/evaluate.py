"""ALS evaluation: RMSE (explicit) and mean per-user AUC (implicit).

Equivalent of the reference's Evaluation
(app/oryx-app-mllib/.../als/Evaluation.java:49-137): explicit models score
−RMSE over the test split; implicit models score mean AUC where each user's
positive test items are compared against sampled negative items (items the
user has not interacted with). Negative sampling happens on host (rejection
against the user's known set); scoring is one gathered einsum on device.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from oryx_tpu.common import rand
from oryx_tpu.models.als.data import RatingBatch


@jax.jit
def _pair_scores(x, y, rows, cols):
    return jnp.sum(x[rows] * y[cols], axis=-1)


def rmse(x, y, test: RatingBatch) -> float:
    """Root mean squared error over test pairs (Evaluation.rmse:49)."""
    if test.nnz == 0:
        return float("nan")
    preds = _pair_scores(x, y, jnp.asarray(test.rows), jnp.asarray(test.cols))
    return float(jnp.sqrt(jnp.mean((preds - jnp.asarray(test.vals)) ** 2)))


def area_under_curve(x, y, train: RatingBatch, test: RatingBatch, negatives_per_positive: int = 10) -> float:
    """Mean over users of per-user AUC vs sampled negatives
    (Evaluation.areaUnderCurve:66-137)."""
    if test.nnz == 0:
        return float("nan")
    n_items = y.shape[0]
    if n_items < 2:
        return float("nan")
    known: dict[int, set[int]] = {}
    for r, c in zip(train.rows, train.cols):
        known.setdefault(int(r), set()).add(int(c))
    for r, c in zip(test.rows, test.cols):
        known.setdefault(int(r), set()).add(int(c))

    rng = rand.get_random()
    pos_rows, pos_cols, neg_cols = [], [], []
    npp = negatives_per_positive
    # per-user rejection sampling with top-up retries: draw sizes stay
    # proportional to each user's need (bounded host memory) and every
    # positive reliably gets npp negatives unless the user has seen
    # nearly every item
    by_user: dict[int, list[int]] = {}
    for r, c in zip(test.rows, test.cols):
        by_user.setdefault(int(r), []).append(int(c))
    for r, cols in by_user.items():
        ku = known.get(r, set())
        if len(ku) >= n_items:
            continue
        ku_arr = np.fromiter(ku, dtype=np.int64, count=len(ku))
        need = npp * len(cols)
        negs: list[int] = []
        for _ in range(100):
            if len(negs) >= need:
                break
            draw = rng.integers(0, n_items, size=max(2 * (need - len(negs)), 16))
            negs.extend(draw[~np.isin(draw, ku_arr)][: need - len(negs)].tolist())
        for i, c in enumerate(cols):
            for j in negs[i * npp : (i + 1) * npp]:
                pos_rows.append(r)
                pos_cols.append(c)
                neg_cols.append(j)
    if not pos_rows:
        return float("nan")
    rows = jnp.asarray(np.asarray(pos_rows, dtype=np.int32))
    pc = jnp.asarray(np.asarray(pos_cols, dtype=np.int32))
    nc = jnp.asarray(np.asarray(neg_cols, dtype=np.int32))
    # one explicit batched fetch for both score sets (two piecemeal
    # np.asarray calls were two blocking transfers)
    pos_scores, neg_scores = jax.device_get(
        (_pair_scores(x, y, rows, pc), _pair_scores(x, y, rows, nc))
    )
    correct = (pos_scores > neg_scores).astype(np.float64) + 0.5 * (pos_scores == neg_scores)
    # mean of per-user AUC (not pooled) — reference averages per user
    df = {}
    for r, cval in zip(np.asarray(rows), correct):
        s, n = df.get(int(r), (0.0, 0))
        df[int(r)] = (s + cval, n + 1)
    per_user = [s / n for s, n in df.values()]
    return float(np.mean(per_user))
