"""ALS batch update: the MLUpdate implementation for collaborative filtering.

Equivalent of the reference's ALSUpdate (app/oryx-app-mllib/.../als/
ALSUpdate.java:82-343): hyperparameters from ``oryx.als.hyperparams.*``
(features, lambda, alpha, and epsilon iff logStrength), time-decayed and
NaN-aware-aggregated input, TPU ALS training (train.als_train), evaluation
(implicit: mean AUC; explicit: −RMSE), time-ordered train/test split
(splitNewDataToTrainTest:326-343), pointer-PMML artifact, and
publish_additional_model_data streaming every Y then X row as
``"UP" ["Y"/"X", id, vector(, knownItems)]`` (ALSUpdate.java:286-319 — items
first so user endpoints return complete results once users arrive).
"""

from __future__ import annotations

import json
import logging
import threading
import time
from pathlib import Path
from typing import Sequence

import numpy as np

from oryx_tpu.api.keymessage import KeyMessage
from oryx_tpu.common import checkpoint as ckpt_mod
from oryx_tpu.common import rand
from oryx_tpu.ml import param as hp
from oryx_tpu.ml.mlupdate import MLUpdate
from oryx_tpu.models.als import data as als_data
from oryx_tpu.models.als import evaluate as als_eval
from oryx_tpu.models.als import pmml_codec
from oryx_tpu.models.als import train as als_train_mod

log = logging.getLogger(__name__)


class ALSUpdate(MLUpdate):
    def __init__(self, config):
        super().__init__(config)
        self.iterations = config.get_int("oryx.als.iterations")
        self.implicit = config.get_bool("oryx.als.implicit")
        self.log_strength = config.get_bool("oryx.als.logStrength")
        self.no_known_items = config.get_bool("oryx.als.no-known-items")
        self.decay_factor = config.get_float("oryx.als.decay.factor")
        self.decay_zero_threshold = config.get_float("oryx.als.decay.zero-threshold")
        self.compute_dtype = config.get_string("oryx.als.compute-dtype", "float32")
        self.hyper_params = [
            hp.from_config(config, "oryx.als.hyperparams.features"),
            hp.from_config(config, "oryx.als.hyperparams.lambda"),
            hp.from_config(config, "oryx.als.hyperparams.alpha"),
        ]
        if self.log_strength:
            self.hyper_params.append(hp.from_config(config, "oryx.als.hyperparams.epsilon"))
        # slotted-layout reuse across generations: when the next
        # generation's COO extends this one's (append-mostly input and no
        # decay rewriting historical strengths), the host pack collapses to
        # an incremental delta of the touched blocks instead of a full
        # re-sort of every interaction ever seen. One cache per updater
        # (generations build sequentially on the batch tier); concurrent
        # hyperparameter candidates contend on the try-lock and simply pack
        # uncached rather than interleave the cache's generations.
        self._layout_cache = als_train_mod.BlockedLayoutCache()
        self._layout_cache_lock = threading.Lock()

    def get_hyper_parameter_values(self):
        return list(self.hyper_params)

    # -- train (buildModel:108-179) -----------------------------------------
    def build_model(self, context, train_data, hyper_parameters, candidate_path: Path):
        features = int(hyper_parameters[0])
        lam = float(hyper_parameters[1])
        alpha = float(hyper_parameters[2])
        epsilon = float(hyper_parameters[3]) if self.log_strength else 1.0e-5
        if features <= 0 or lam < 0.0 or alpha <= 0.0:
            raise ValueError("features must be positive, lambda >= 0, alpha > 0")

        batch = als_data.prepare(
            (km.message for km in train_data),
            implicit=self.implicit,
            decay_factor=self.decay_factor,
            decay_zero_threshold=self.decay_zero_threshold,
            log_strength=self.log_strength,
            epsilon=epsilon,
        )
        if batch.nnz == 0 or len(batch.users) == 0 or len(batch.items) == 0:
            return None
        # factor/Gramian rows shard over the mesh's model axis when the batch
        # tier runs multi-device (ComputeContext, SURVEY §2.14 block-ALS map)
        mesh = row_axis = None
        ctx_mesh = getattr(context, "mesh", None)
        if ctx_mesh is not None and ctx_mesh.size > 1 and "model" in ctx_mesh.axis_names:
            mesh, row_axis = ctx_mesh, "model"
        # preemption tolerance: the checkpoint identity is the generation's
        # DATA fingerprint — input-topic offsets (stamped on the context by
        # the batch layer; None for direct/test callers), the candidate's
        # hyperparameters, the batch shapes, and a CRC of the actual COO
        # arrays — so a restarted generation resumes ONLY state built from
        # exactly the data and settings it is about to train on
        checkpointer = None
        if ckpt_mod.enabled(self.config):
            fp = ckpt_mod.fingerprint(
                kind="als",
                offsets=getattr(context, "input_offsets", None),
                features=features, lam=lam, alpha=alpha, epsilon=epsilon,
                implicit=self.implicit, iterations=self.iterations,
                dtype=self.compute_dtype,
                shape=[len(batch.users), len(batch.items), int(batch.nnz)],
                data_crc=ckpt_mod.data_crc(batch.rows, batch.cols,
                                           batch.vals),
            )
            checkpointer = self.make_checkpointer(fp)
        cache = (
            self._layout_cache
            if self._layout_cache_lock.acquire(blocking=False) else None
        )
        timings: dict = {}
        try:
            x, y = als_train_mod.als_train(
                batch,
                features=features,
                lam=lam,
                alpha=alpha,
                implicit=self.implicit,
                iterations=self.iterations,
                key=rand.get_key(),
                mesh=mesh,
                row_axis=row_axis,
                dtype=self.compute_dtype,
                layout_cache=cache,
                timings=timings,
                checkpointer=checkpointer,
            )
        finally:
            if cache is not None:
                self._layout_cache_lock.release()
        # lineage identity for the generation's provenance stamp: the
        # checkpoint fingerprint keeps the generation id stable across a
        # crash-restart (same uncommitted offsets → same fp), and origin
        # records whether this training resumed or started from scratch.
        # Parallel candidates race last-writer-wins; exact for candidates=1.
        # Direct/test callers pass context=None — nothing to stamp onto.
        if context is not None:
            context.lineage_fingerprint = (
                fp if checkpointer is not None else None
            )
            context.lineage_origin = (
                "resume"
                if checkpointer is not None and checkpointer.resumed_step
                else "scratch"
            )
        log.info(
            "ALS train: %d nnz, pack %.2fs on the critical path (user %.2fs"
            " + item wait %.2fs; modes %s)",
            batch.nnz, timings.get("pack_s", 0.0),
            timings.get("pack_user_s", 0.0), timings.get("pack_wait_s", 0.0),
            timings.get("pack_modes"),
        )
        # mesh-path factors come back row-partitioned and padded to the block
        # boundary (train.als_train contract) — slice to exact size host-side
        return pmml_codec.model_to_pmml(
            np.asarray(x)[: len(batch.users)],
            np.asarray(y)[: len(batch.items)],
            batch.users.index_to_id,
            batch.items.index_to_id,
            features,
            lam,
            alpha,
            self.implicit,
            self.log_strength,
            epsilon,
            candidate_path,
        )

    # -- eval (evaluate:200-247) --------------------------------------------
    def evaluate(self, context, model, model_parent_path: Path, test_data, train_data):
        meta = pmml_codec.pmml_to_meta(model)
        users = als_data.IDIndexMapping(meta["x_ids"])
        items = als_data.IDIndexMapping(meta["y_ids"])
        x = _load_matrix(Path(model_parent_path) / meta["x_dir"], users, meta["features"])
        y = _load_matrix(Path(model_parent_path) / meta["y_dir"], items, meta["features"])
        test_batch = self._eval_batch(test_data, meta, users, items)
        if self.implicit:
            # rebuild the train known-set from the passed train data — stateless,
            # safe under concurrent candidate evaluation
            train_batch = self._eval_batch(train_data, meta, users, items)
            score = als_eval.area_under_curve(x, y, train_batch, test_batch)
            log.info("AUC = %s", score)
            return score
        score = -als_eval.rmse(x, y, test_batch)
        log.info("-RMSE = %s", score)
        return score

    def _eval_batch(self, data, meta, users, items):
        """Parse→decay→aggregate with the SAME pipeline as training, so eval
        scores compare like with like (reference routes test data through
        parsedToRatingRDD, which decays — ALSUpdate.java:219)."""
        interactions = als_data.decay(
            als_data.parse_lines([km.message for km in data]),
            self.decay_factor,
            self.decay_zero_threshold,
        )
        return als_data.build_rating_batch(
            als_data.aggregate(
                interactions, self.implicit, meta["logStrength"], meta["epsilon"]
            ),
            users,
            items,
        )

    # -- time-ordered split of NEW data (splitNewDataToTrainTest:326-343) ----
    def split_new_data_to_train_test(self, new_data: Sequence[KeyMessage]):
        if self.test_fraction <= 0:
            return list(new_data), []

        def ts(km: KeyMessage) -> int:
            try:
                return als_data.parse_line(km.message).timestamp_ms
            except ValueError:
                return 0

        ordered = sorted(new_data, key=ts)
        split = int(round(len(ordered) * (1.0 - self.test_fraction)))
        return ordered[:split], ordered[split:]

    # -- stream factors to serving/speed (publishAdditionalModelData:286-319) -
    def publish_additional_model_data(self, context, pmml, new_data, past_data, model_path, producer):
        meta = pmml_codec.pmml_to_meta(pmml)
        y_path = Path(model_path) / meta["y_dir"]
        x_path = Path(model_path) / meta["x_dir"]
        # items first (reference comment: more complete /recommend once users load)
        for id_, vec in pmml_codec.read_features(y_path):
            producer.send("UP", json.dumps(["Y", id_, [float(v) for v in vec]]))
        known_items: dict[str, list[str]] = {}
        if not self.no_known_items:
            known_sets: dict[str, set[str]] = {}
            for km in list(new_data) + list(past_data):
                try:
                    it = als_data.parse_line(km.message)
                except ValueError:
                    continue
                known_sets.setdefault(it.user, set()).add(it.item)
            known_items = {u: sorted(s) for u, s in known_sets.items()}
        for id_, vec in pmml_codec.read_features(x_path):
            if known_items:
                producer.send(
                    "UP",
                    json.dumps(["X", id_, [float(v) for v in vec], known_items.get(id_, [])]),
                )
            else:
                producer.send("UP", json.dumps(["X", id_, [float(v) for v in vec]]))


def _load_matrix(path: Path, mapping: als_data.IDIndexMapping, features: int) -> np.ndarray:
    m = np.zeros((len(mapping), features), dtype=np.float32)
    for id_, vec in pmml_codec.read_features(path):
        idx = mapping.id_to_index.get(id_)
        if idx is not None:
            m[idx] = vec
    return m
