"""ALS serving model: device-resident factors answering recommendation queries.

Equivalent of the reference's ALSServingModel / ALSServingModelManager /
TopNConsumer (app/oryx-app-serving/.../als/model/ALSServingModel.java:61-418,
ALSServingModelManager.java:44-182, TopNConsumer.java:30-80).

TPU re-design of the query path: the reference fans a top-N scan over
LSH-partitioned hash maps with a thread pool; here Y materializes into one
dense device matrix (dirty-flag cache), and top-N is a single
``scores = Y @ q`` matmul + ``lax.top_k`` on the MXU — with optional LSH
masking preserving ``sample-rate`` approximation semantics, and item norms
cached for cosine queries. Point updates (UP messages) mutate host maps and
only re-materialize lazily, so the query path never blocks on updates
(the double-buffer answer to JAX array immutability).
"""

from __future__ import annotations

import functools
import json
import logging
import math
import threading
import time
import weakref
from typing import Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from oryx_tpu.api.serving import ServingModel
from oryx_tpu.ml.mlupdate import read_pmml_from_update_key_message
from oryx_tpu.api.serving import AbstractServingModelManager
from oryx_tpu.common import compilecache
from oryx_tpu.common import lineage
from oryx_tpu.common import metrics as metrics_mod
from oryx_tpu.common import profiling
from oryx_tpu.common import spans
from oryx_tpu.models.als import ivf as ivf_mod
from oryx_tpu.models.als import pmml_codec
from oryx_tpu.models.als.lsh import LocalitySensitiveHash
from oryx_tpu.models.als.rescorer import load_rescorer_providers
from oryx_tpu.models.als.vectors import FeatureVectorStore
from oryx_tpu.common.lockutils import RateLimitCheck
from oryx_tpu.ops.solver import SolverCache

log = logging.getLogger(__name__)

_TOPN_BATCH_SECONDS = metrics_mod.default_registry().histogram(
    "oryx_serving_topn_batch_seconds",
    "Host-observed latency of one batched top-N device call",
)
_TOPN_QUERIES = metrics_mod.default_registry().counter(
    "oryx_serving_topn_queries_total",
    "Queries answered through the batched top-N path",
)
_LOAD_FRACTION = metrics_mod.default_registry().gauge(
    "oryx_serving_model_load_fraction",
    "Fraction of expected model vectors loaded (evaluated at scrape time)",
)
_PREWARMED_SWAPS = metrics_mod.default_registry().counter(
    "oryx_serving_prewarmed_swaps_total",
    "Model-generation swaps promoted after off-path bucket warmup",
)
_DEADLINE_SWAPS = metrics_mod.default_registry().counter(
    "oryx_serving_swap_deadline_promotions_total",
    "Staged model generations promoted by the swap deadline, unwarmed",
)


def _load_fraction_fn(manager_ref):
    """Scrape-time gauge callback over a WEAK manager ref: a strong ref
    would pin a retired manager (and its factor matrices) for the process
    lifetime after a test or redeploy drops it."""

    def fn() -> float:
        manager = manager_ref()
        model = manager.get_model() if manager is not None else None
        return model.get_fraction_loaded() if model is not None else 0.0

    return fn


def _round_up_pow2(n: int) -> int:
    return 1 << max(0, (n - 1).bit_length())


#: Floor of the pow2-bucketed exclusion-mask width. Known-item exclusion is
#: what the DEFAULT /recommend path sends (considerKnownItems=false), so its
#: jit signature must be shape-stable enough to PRE-warm: flooring the width
#: means every request with ≤ this many known items — the overwhelming
#: common case — lands on ONE compiled program, which the batch warmer
#: compiles off-path (warm_bucket). Users past the floor bucket up by pow2
#: and pay one compile per bucket per process (persistent-cache-served
#: afterwards), exactly like unusual howMany values.
_EXCL_PAD_MIN = 8


#: Valid values of ``oryx.serving.device-dtype``: "auto" keeps the historic
#: behavior (bf16 scoring copy on TPU, f32 elsewhere); explicit f32/bf16
#: force the scoring dtype; "int8" holds ONLY a per-row-scaled int8 slab on
#: device (¼ the f32 HBM) and rescores the top candidates exactly in f32
#: from the host factor arena before the final top-k.
_DEVICE_DTYPES = ("auto", "float32", "bfloat16", "int8")


def _topn_cost_key(batch_size: int, excl: bool, quant: bool = False) -> str:
    """Cost-accounting program signature for one batched top-N variant.
    Keyed by (batch size, exclusion-carrying, quantized) — the axes the
    coalescer's pow2 padding and the warm ladder actually produce; top-k
    width drift (unusual howMany) folds into the same key, a documented
    approximation (docs/observability.md "Device performance attribution").
    Quantized programs get their OWN keys: their per-call cost (int8 reads,
    rescale multiply) differs from the f32/bf16 scan's."""
    return (f"als.top_n_batch/b{batch_size}"
            + ("+excl" if excl else "") + ("+int8" if quant else ""))


def _score(qs, mat):
    """(B, n) scores with f32 accumulation. ``mat`` may be bfloat16 (the MXU's
    native input dtype — half the HBM traffic of f32); accumulation stays f32
    via preferred_element_type, the standard TPU matmul recipe."""
    return jnp.matmul(
        qs.astype(mat.dtype), mat.T, preferred_element_type=jnp.float32
    )


def _mask_excluded(scores, excl):
    """Per-query exclusion scatter: ``excl`` is (B, E) row indices, -1-padded.
    Out-of-range entries are remapped to n (a drop index): negative scatter
    indices would WRAP from the end, so they must be clamped explicitly."""
    n = scores.shape[1]
    excl = jnp.where((excl >= 0) & (excl < n), excl, n)
    return jax.vmap(lambda row, ix: row.at[ix].set(-jnp.inf, mode="drop"))(
        scores, excl
    )


@functools.partial(jax.jit, static_argnames=("k",))
def _top_k_dot_batch(mat, qs, valid, excl, k: int):
    """One MXU matmul for the whole query batch + approx top-k (the masking
    logic lives once in ``_masked_scores``). ``valid`` / ``excl`` are None on
    the unfiltered hot path so it stays exactly matmul + top_k (None is a
    static pytree — XLA never sees a dummy mask; the r1→r2 CPU regression was
    unconditional masking here).

    approx_max_k is the TPU-native top-k (recall ≥ 0.99 beats LSH 0.3's own
    approximation); exact on backends without the TPU op."""
    return _top_k_of_scores(_masked_scores(mat, qs, valid, excl), k)


@jax.jit
def _masked_scores(mat, qs, valid, excl):
    """Masked score matrix only — lets the widening retry in ``top_n`` reuse
    one matmul's scores across successively larger top-k calls instead of
    re-scanning Y each widening."""
    scores = _score(qs, mat)
    if valid is not None:
        scores = jnp.where(valid[None, :], scores, -jnp.inf)
    if excl is not None:
        scores = _mask_excluded(scores, excl)
    return scores


@functools.partial(jax.jit, static_argnames=("k",))
def _top_k_of_scores(scores, k: int):
    return jax.lax.approx_max_k(scores, k, recall_target=0.99)


@functools.partial(jax.jit, static_argnames=("k",))
def _top_k_dot_batch_masked(mat, qs, lut, buckets, excl, k: int):
    scores = _score(qs, mat)  # (B, n)
    valid = jnp.take_along_axis(lut, buckets[None, :], axis=1)  # (B, n)
    scores = jnp.where(valid, scores, -jnp.inf)
    if excl is not None:
        scores = _mask_excluded(scores, excl)
    return jax.lax.approx_max_k(scores, k, recall_target=0.99)


@functools.lru_cache(maxsize=32)
def _sharded_top_k_fn(mesh, axis: str, k: int, k_final: int, n_real: int,
                      use_lut: bool, use_excl: bool = True):
    """Cross-shard top-N: Y's rows shard over ``axis``; each device scores
    its block, masks (pad rows, per-query LSH lut, per-query excluded items)
    and takes a local top-k; the (B, ndev·k) candidates merge with one more
    top-k. This is the multi-chip scan of SURVEY §2.14 ("device-resident Y
    shards; top-N via sharded matmul + lax.top_k + cross-shard merge") — the
    framework's intra-request parallelism.

    Exclusion (known-item filtering, Recommend.java:84-106) is a device-side
    scatter: ``excl`` is (B, E) GLOBAL row indices, -1-padded; each shard
    rebases to local coordinates and drops out-of-range entries, so the mask
    costs O(E) scatter per shard instead of a host round-trip."""
    try:
        from jax import shard_map  # jax >= 0.8
    except ImportError:  # pragma: no cover — older jax
        from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    def local(mat_blk, qs_blk, excl_blk, lut_blk, buckets_blk):
        n_local = mat_blk.shape[0]
        offset = jax.lax.axis_index(axis) * n_local
        scores = _score(qs_blk, mat_blk)  # (B, n_local)
        col_ids = offset + jax.lax.broadcasted_iota(jnp.int32, scores.shape, 1)
        scores = jnp.where(col_ids < n_real, scores, -jnp.inf)
        if use_lut:
            valid = jnp.take_along_axis(
                lut_blk, buckets_blk[None, :].astype(jnp.int32), axis=1
            )
            scores = jnp.where(valid, scores, -jnp.inf)
        if use_excl:
            # per-query exclusions: global→local rebase; -1 pads and rows
            # owned by other shards are remapped to the drop index (negative
            # scatter indices would wrap, so clamp explicitly)
            local_excl = excl_blk - offset
            scores = _mask_excluded(scores, local_excl)
        vals, idx = jax.lax.top_k(scores, k)
        return vals, idx + offset

    @jax.jit
    def fn(mat, qs, excl, lut, buckets):
        # the replicated P(None, None) operands here are BATCH-shaped
        # (queries/exclusions/lut: B·k, B·E, B·buckets) — a deliberate
        # small broadcast, which the replicated-collective checker keeps
        # quiet on because none of them is data-gathered like a factor
        # table; Y (the model-scaled operand) is the sharded one
        vals, idx = shard_map(
            local,
            mesh=mesh,
            in_specs=(P(axis, None), P(None, None), P(None, None),
                      P(None, None), P(axis)),
            out_specs=(P(None, axis), P(None, axis)),
        )(mat, qs, excl, lut, buckets)
        mvals, pos = jax.lax.top_k(vals, k_final)  # (B, ndev*k) → (B, k_final)
        return mvals, jnp.take_along_axis(idx, pos, axis=1)

    return fn


@functools.partial(jax.jit, static_argnames=("k",))
def _top_k_cosine_sum(mat, norms, qs, q_norms, valid, k: int):
    # mean cosine similarity to several query vectors (CosineAverageFunction.java)
    sims = (mat @ qs.T) / jnp.maximum(norms[:, None] * q_norms[None, :], 1e-12)
    scores = jnp.where(valid, jnp.mean(sims, axis=1), -jnp.inf)
    return jax.lax.top_k(scores, k)


# -- quantized (int8) candidate scan ----------------------------------------
# The int8 device path reads ¼ the HBM of f32 per scan (the scan is
# bandwidth-bound: one pass over Y per query batch), at the cost of ~0.4%
# relative rounding error per score. The approximate scores only CHOOSE
# candidates; the final ranking comes from an exact f32 rescore of the top
# ``rescore-factor × how_many`` rows gathered from the host factor arena —
# so recall, not precision, is the only quantization exposure.


def _quantize_rows(mat: np.ndarray) -> "tuple[np.ndarray, np.ndarray]":
    """Per-row symmetric int8 quantization: scale_i = max|row_i| / 127.
    Zero rows get scale 1 (their dots are exactly 0 either way)."""
    if mat.size == 0:
        return (np.zeros(mat.shape, dtype=np.int8),
                np.ones(mat.shape[0], dtype=np.float32))
    amax = np.max(np.abs(mat), axis=1)
    scale = np.where(amax > 0, amax / 127.0, 1.0).astype(np.float32)
    q = np.clip(np.rint(mat / scale[:, None]), -127, 127).astype(np.int8)
    return q, scale


@jax.jit
def _quant_masked_scores(qmat, qscale, qs, valid, excl):
    """(B, n) approximate scores off the int8 slab: the convert rides the
    matmul operand (XLA fuses it — HBM traffic stays int8), accumulation is
    f32, and the per-row scale lands as one broadcast multiply."""
    scores = jnp.matmul(
        qs, qmat.T.astype(qs.dtype), preferred_element_type=jnp.float32
    ) * qscale[None, :]
    if valid is not None:
        scores = jnp.where(valid[None, :], scores, -jnp.inf)
    if excl is not None:
        scores = _mask_excluded(scores, excl)
    return scores


@functools.partial(jax.jit, static_argnames=("k",))
def _quant_candidates(qmat, qscale, qs, valid, excl, k: int):
    """Top-k CANDIDATES (approximate scores) for the exact f32 rescore."""
    return _top_k_of_scores(_quant_masked_scores(qmat, qscale, qs, valid, excl), k)


@functools.partial(jax.jit, static_argnames=("k",))
def _quant_candidates_masked(qmat, qscale, qs, lut, buckets, excl, k: int):
    """Per-query-LUT (LSH) variant of the quantized candidate scan."""
    scores = jnp.matmul(
        qs, qmat.T.astype(qs.dtype), preferred_element_type=jnp.float32
    ) * qscale[None, :]
    valid = jnp.take_along_axis(lut, buckets[None, :], axis=1)
    scores = jnp.where(valid, scores, -jnp.inf)
    if excl is not None:
        scores = _mask_excluded(scores, excl)
    return jax.lax.approx_max_k(scores, k, recall_target=0.99)


@functools.partial(jax.jit, static_argnames=("k",))
def _quant_cosine_candidates(qmat, qscale, norms, qs, q_norms, valid, k: int):
    """Mean-cosine candidates off the int8 slab (norms are EXACT f32,
    computed host-side from the arena at snapshot time)."""
    sims = (jnp.matmul(
        qs, qmat.T.astype(qs.dtype), preferred_element_type=jnp.float32
    ) * qscale[None, :]) / jnp.maximum(
        norms[None, :] * q_norms[:, None], 1e-12
    )
    scores = jnp.where(valid, jnp.mean(sims, axis=0), -jnp.inf)
    return jax.lax.top_k(scores, k)


class _YSnapshot:
    """Immutable device view of Y: ids, matrix, norms, LSH buckets. With a
    mesh, the scoring copy is row-sharded over ``shard_axis`` (rows padded to
    the shard count) so Y may exceed a single device's memory.

    ``prev`` + ``delta`` ((changed base-row indices, appended-row count) from
    FeatureVectorStore.delta_since) build the snapshot INCREMENTALLY after a
    speed microbatch of point updates: norms and the bf16 scoring copy are
    whole-matrix device ops (no transfer), and LSH buckets recompute for only
    the changed/appended rows — the reference's in-place update semantics
    (ALSServingModel.java:320-370) without ever re-uploading or re-hashing
    the full matrix."""

    def __init__(
        self,
        ids: list[str],
        mat,
        lsh: LocalitySensitiveHash | None,
        mesh=None,
        shard_axis: str = "model",
        prev: "_YSnapshot | None" = None,
        delta: "tuple[np.ndarray, int] | None" = None,
        device_dtype: str = "auto",
    ):
        self.ids = ids
        self.device_dtype = device_dtype
        self.mat = mat  # jax (n, k) or None, float32
        # lazy cost-registration marks (see _top_n_batch): per GENERATION so
        # a model swap re-registers against the new shapes, but carried
        # across same-shape incremental snapshots (point-update microbatches
        # whose dispatch signatures — and therefore per-call costs — are
        # unchanged). Marked even when registration fails, so a backend
        # without usable cost_analysis never re-pays lower+compile per call.
        if (prev is not None
                and getattr(prev.mat, "shape", None)
                == getattr(mat, "shape", None)):
            self.cost_keys_attempted = prev.cost_keys_attempted
        else:
            self.cost_keys_attempted: set = set()
        if prev is not None and delta is not None:
            # id→idx is append-only across incremental generations; sharing
            # the dict avoids an O(n) rebuild per microbatch (extra entries
            # in the older snapshot only affect exclusion masks, which drop
            # out-of-range rows on device)
            self.id_to_idx = prev.id_to_idx
            for i in range(len(prev.ids), len(ids)):
                self.id_to_idx[ids[i]] = i
        else:
            self.id_to_idx = {s: i for i, s in enumerate(ids)}
        self.mesh = mesh
        self.shard_axis = shard_axis
        self.sharded_mat = None
        self.sharded_buckets = None
        if mat is not None:
            self.norms = jnp.linalg.norm(mat, axis=1)
            # scoring copy: bf16 on TPU halves HBM traffic per scan; exact
            # dots/norms keep the f32 matrix. An explicit
            # oryx.serving.device-dtype overrides the backend heuristic
            # (int8 never reaches this class — see _QuantSnapshot)
            if device_dtype == "float32":
                self.score_mat = mat
            elif device_dtype == "bfloat16":
                self.score_mat = mat.astype(jnp.bfloat16)
            else:  # auto
                self.score_mat = (
                    mat.astype(jnp.bfloat16)
                    if jax.default_backend() == "tpu" else mat
                )
            if lsh and lsh.num_hashes:
                if prev is not None and delta is not None and prev.buckets is not None:
                    # rehash only the delta: pull changed/new rows (not the
                    # whole matrix) to host for bucket assignment
                    buckets = prev.buckets
                    ch, n_new = delta
                    if len(ch):
                        ch_j = jnp.asarray(ch, dtype=jnp.int32)
                        new_b = jnp.asarray(
                            lsh.assign_buckets(np.asarray(mat[ch_j]))
                        )
                        buckets = buckets.at[ch_j].set(new_b)
                    if n_new:
                        tail = np.asarray(mat[len(prev.ids):])
                        buckets = jnp.concatenate(
                            [buckets, jnp.asarray(lsh.assign_buckets(tail))]
                        )
                    self.buckets = buckets
                else:
                    self.buckets = jnp.asarray(lsh.assign_buckets(np.asarray(mat)))
            else:
                self.buckets = None
            if mesh is not None:
                n_shards = mesh.shape[shard_axis]
                pad = (-mat.shape[0]) % n_shards
                padded = (
                    jnp.concatenate(
                        [self.score_mat,
                         jnp.zeros((pad, mat.shape[1]), self.score_mat.dtype)]
                    )
                    if pad
                    else self.score_mat
                )
                sharding = jax.sharding.NamedSharding(
                    mesh, jax.sharding.PartitionSpec(shard_axis, None)
                )
                self.sharded_mat = jax.device_put(padded, sharding)
                # bucket array rides the same sharding (zeros when no LSH so
                # the shard_map signature stays fixed)
                b = (
                    np.asarray(self.buckets, dtype=np.int32)
                    if self.buckets is not None
                    else np.zeros(mat.shape[0], dtype=np.int32)
                )
                if pad:
                    b = np.concatenate([b, np.zeros(pad, dtype=np.int32)])
                bshard = jax.sharding.NamedSharding(
                    mesh, jax.sharding.PartitionSpec(shard_axis)
                )
                self.sharded_buckets = jax.device_put(b, bshard)
        else:
            self.norms = None
            self.score_mat = None
            self.buckets = None

    @property
    def n(self) -> int:
        return len(self.ids)


#: Host-side quantization chunk: bounds the transient f32 gather while
#: building a full quantized snapshot (2^16 rows × 50f ≈ 13 MB per chunk
#: instead of one n×k f32 copy next to the arena slab).
_QUANT_CHUNK = 1 << 16


class _QuantSnapshot:
    """Immutable int8 device view of Y (``oryx.serving.device-dtype = int8``):
    per-row-scaled int8 factors + exact f32 norms + optional LSH buckets.
    No f32 (or bf16) copy of Y ever lands in HBM — the whole point of the
    mode is fitting a 21M × 50f item side per chip with headroom.

    Built from the factor arena's HOST snapshot (``host_matrix``) and kept
    current with composed host deltas (``delta_info``): a speed microbatch
    of point updates requantizes only the changed/appended rows and lands
    them as row-index scatters, mirroring the f32 path's incremental
    device maintenance. ``version`` anchors the next delta."""

    def __init__(self, ids, version: int, qmat, qscale, norms, buckets,
                 prev: "_QuantSnapshot | None" = None,
                 appended: "list[str] | None" = None,
                 slab=None, slab_rows=None):
        self.ids = ids
        self.version = version
        self.qmat = qmat        # (n, k) int8 device
        self.qscale = qscale    # (n,) f32 device
        self.norms = norms      # (n,) f32 device, exact
        self.buckets = buckets  # (n,) int32 device or None
        # pinned exact-rescore view: THIS snapshot's slab object + its row
        # indices, captured by the store in the same order epoch as `ids`.
        # Structural store changes (GC, compaction) replace the live
        # slab/rowmap and never disturb this pair, so a rescore can never
        # crash on, or misalign against, a concurrently mutated store. A
        # point update rewriting a captured row in place is visible here —
        # the rescore ranks with fresher factors than the scan, benign.
        self.slab = slab
        self.slab_rows = slab_rows  # (n,) slab row per snapshot position
        self.mat = None         # no f32 device matrix in this mode
        self.score_mat = None
        self.sharded_mat = None
        self.sharded_buckets = None
        self.mesh = None
        if prev is not None and appended is not None:
            # id→idx append-only sharing, exactly like _YSnapshot
            self.id_to_idx = prev.id_to_idx
            for i in range(len(prev.ids), len(ids)):
                self.id_to_idx[ids[i]] = i
        else:
            self.id_to_idx = {s: i for i, s in enumerate(ids)}
        # lazy cost-registration marks: per generation, carried across
        # same-shape incremental snapshots (see _YSnapshot)
        if (prev is not None
                and getattr(prev.qmat, "shape", None)
                == getattr(qmat, "shape", None)):
            self.cost_keys_attempted = prev.cost_keys_attempted
        else:
            self.cost_keys_attempted: set = set()
        profiling.register_quantized(self)

    @property
    def n(self) -> int:
        return len(self.ids)

    def quantized_nbytes(self) -> int:
        """Device bytes held by the quantized factors (the
        oryx_device_quantized_factor_bytes gauge)."""
        total = 0
        for arr in (self.qmat, self.qscale):
            total += int(getattr(arr, "nbytes", 0) or 0)
        return total

    def gather_rows(self, positions: np.ndarray) -> np.ndarray:
        """Exact f32 factor rows for snapshot ``positions``, gathered from
        the PINNED slab view (see __init__) — one fancy index."""
        pos = np.clip(np.asarray(positions, dtype=np.int64), 0, self.n - 1)
        return self.slab[self.slab_rows[pos]]

    @classmethod
    def build(cls, ids, host: np.ndarray, version: int,
              lsh: "LocalitySensitiveHash | None",
              row_view: tuple,
              prev: "_QuantSnapshot | None" = None):
        """Full quantized build from one host matrix, chunked so the
        transient stays bounded at reference scale."""
        n = len(ids)
        slab, slab_rows = row_view
        if n == 0 or host.size == 0:
            return cls(list(ids), version, None, None, None, None)
        k = host.shape[1]
        q = np.empty((n, k), dtype=np.int8)
        scale = np.empty(n, dtype=np.float32)
        norms = np.empty(n, dtype=np.float32)
        for a in range(0, n, _QUANT_CHUNK):
            b = min(n, a + _QUANT_CHUNK)
            q[a:b], scale[a:b] = _quantize_rows(host[a:b])
            norms[a:b] = np.linalg.norm(host[a:b], axis=1)
        buckets = None
        if lsh and lsh.num_hashes:
            buckets = jnp.asarray(lsh.assign_buckets(host))
        return cls(list(ids), version, jnp.asarray(q), jnp.asarray(scale),
                   jnp.asarray(norms), buckets, prev=prev,
                   slab=slab, slab_rows=slab_rows)

    @classmethod
    def from_delta(cls, prev: "_QuantSnapshot", delta,
                   lsh: "LocalitySensitiveHash | None"):
        """Incremental step: requantize only the changed/appended rows and
        land them as device row scatters / one append."""
        qmat, qscale, norms, buckets = (
            prev.qmat, prev.qscale, prev.norms, prev.buckets
        )
        changed_pos = [prev.id_to_idx[i] for i in delta.changed_ids
                       if i in prev.id_to_idx]
        if changed_pos:
            pos = jnp.asarray(changed_pos, dtype=jnp.int32)
            qc, sc = _quantize_rows(delta.changed_vals)
            qmat = qmat.at[pos].set(jnp.asarray(qc))
            qscale = qscale.at[pos].set(jnp.asarray(sc))
            norms = norms.at[pos].set(
                jnp.asarray(np.linalg.norm(delta.changed_vals, axis=1))
            )
            if buckets is not None:
                buckets = buckets.at[pos].set(
                    jnp.asarray(lsh.assign_buckets(delta.changed_vals))
                )
        if delta.appended_ids:
            qa, sa = _quantize_rows(delta.appended_vals)
            qmat = jnp.concatenate([qmat, jnp.asarray(qa)])
            qscale = jnp.concatenate([qscale, jnp.asarray(sa)])
            norms = jnp.concatenate([norms, jnp.asarray(
                np.linalg.norm(delta.appended_vals, axis=1))])
            if buckets is not None:
                buckets = jnp.concatenate([buckets, jnp.asarray(
                    lsh.assign_buckets(delta.appended_vals))])
        ids = prev.ids + delta.appended_ids
        # extend the pinned rescore view: delta.slab is the CURRENT slab
        # (a non-structural grow copies rows in place, so prev's indices
        # stay valid in it) and the appended ids bring their own rows
        slab_rows = (
            np.concatenate([prev.slab_rows,
                            np.asarray(delta.appended_rows, dtype=np.int64)])
            if len(delta.appended_ids) else prev.slab_rows
        )
        return cls(ids, delta.version, qmat, qscale, norms, buckets,
                   prev=prev, appended=delta.appended_ids,
                   slab=delta.slab, slab_rows=slab_rows)


class ALSServingModel(ServingModel):
    def __init__(
        self,
        features: int,
        implicit: bool,
        sample_rate: float = 1.0,
        mesh=None,
        shard_axis: str = "model",
        device_dtype: str = "auto",
        rescore_factor: float = 4.0,
        index_enabled: bool = False,
        index_cells: int = 0,
        index_probes: int = 8,
        index_skew: float = 4.0,
    ):
        self.features = features
        self.implicit = implicit
        self.sample_rate = sample_rate
        if device_dtype not in _DEVICE_DTYPES:
            raise ValueError(
                f"oryx.serving.device-dtype must be one of {_DEVICE_DTYPES}, "
                f"not {device_dtype!r}"
            )
        if device_dtype == "int8" and mesh is not None:
            # the sharded scan's shard_map programs are f32/bf16; quantized
            # sharding is a later round — degrade loudly, never silently
            log.warning(
                "device-dtype=int8 is not supported with sharded serving; "
                "using bfloat16 for the sharded scoring copy"
            )
            device_dtype = "bfloat16"
        if index_enabled and device_dtype != "int8":
            # the IVF cells ARE the int8 representation (and the rescore
            # rides the int8 mode's pinned arena-slab view) — any other
            # resolved dtype means the index cannot engage
            log.warning(
                "oryx.serving.index.enabled requires device-dtype=int8 "
                "(resolved %r); serving without the IVF index", device_dtype
            )
            index_enabled = False
        self.index_enabled = bool(index_enabled)
        self.index_cells = int(index_cells)
        self.index_probes = max(1, int(index_probes))
        self.index_skew = max(1.0, float(index_skew))
        self.device_dtype = device_dtype
        self.rescore_factor = max(1.0, float(rescore_factor))
        self.mesh = mesh
        self.shard_axis = shard_axis
        self.x = FeatureVectorStore()
        self.y = FeatureVectorStore()
        self.lsh = LocalitySensitiveHash(sample_rate, features) if sample_rate < 1.0 else None
        self.known_items: dict[str, set[str]] = {}
        self._known_lock = threading.Lock()
        self.expected_user_ids: set[str] = set()
        self.expected_item_ids: set[str] = set()
        self.yty_cache = SolverCache(self.y.get_vtv)
        self._snapshot: _YSnapshot | None = None
        self._snapshot_src = None
        self._snap_lock = threading.Lock()

    # -- vector + known-item bookkeeping ------------------------------------
    def set_user_vector(self, user: str, vec) -> None:
        self.x.set_vector(user, vec)
        self.expected_user_ids.discard(user)

    def set_item_vector(self, item: str, vec) -> None:
        self.y.set_vector(item, vec)
        self.expected_item_ids.discard(item)
        self.yty_cache.set_dirty()

    def bulk_load_users(self, ids, matrix) -> None:
        """Whole-matrix X handoff keeping model bookkeeping consistent."""
        self.x.bulk_load(ids, matrix)
        self.expected_user_ids.difference_update(ids)

    def bulk_load_items(self, ids, matrix) -> None:
        """Whole-matrix Y handoff keeping model bookkeeping consistent."""
        self.y.bulk_load(ids, matrix)
        self.expected_item_ids.difference_update(ids)
        self.yty_cache.set_dirty()

    def get_user_vector(self, user: str):
        return self.x.get_vector(user)

    def get_item_vector(self, item: str):
        return self.y.get_vector(item)

    def add_known_items(self, user: str, items: Sequence[str]) -> None:
        with self._known_lock:
            self.known_items.setdefault(user, set()).update(items)

    def get_known_items(self, user: str) -> set[str]:
        with self._known_lock:
            return set(self.known_items.get(user, ()))

    def get_known_item_vectors_for_user(self, user: str) -> list[tuple[str, np.ndarray]]:
        """(ALSServingModel.getKnownItemVectorsForUser)"""
        out = []
        for item in self.get_known_items(user):
            v = self.y.get_vector(item)
            if v is not None:
                out.append((item, v))
        return out

    def item_counts(self) -> dict[str, int]:
        """How many users know each item (ALSServingModel.getItemCounts)."""
        counts: dict[str, int] = {}
        with self._known_lock:
            for items in self.known_items.values():
                for i in items:
                    counts[i] = counts.get(i, 0) + 1
        return counts

    def user_counts(self) -> dict[str, int]:
        """Known-item count per user (MostActiveUsers source)."""
        with self._known_lock:
            return {u: len(items) for u, items in self.known_items.items()}

    def all_user_ids(self) -> list[str]:
        return self.x.ids()

    def all_item_ids(self) -> list[str]:
        return self.y.ids()

    def retain_recent_and_user_ids(self, ids) -> None:
        self.x.retain_recent_and_ids(set(ids))

    def retain_recent_and_item_ids(self, ids) -> None:
        self.y.retain_recent_and_ids(set(ids))
        self.yty_cache.set_dirty()

    def retain_recent_and_known_items(self, users) -> None:
        keep = set(users)
        with self._known_lock:
            for u in list(self.known_items):
                if u not in keep:
                    del self.known_items[u]

    def get_fraction_loaded(self) -> float:  # ALSServingModel.java:396
        total = len(self.expected_user_ids) + len(self.expected_item_ids)
        total += self.x.size() + self.y.size()
        if total == 0:
            return 1.0
        return (self.x.size() + self.y.size()) / total

    # -- device snapshot ----------------------------------------------------
    def y_snapshot(self):
        if self.device_dtype == "int8":
            if self.index_enabled:
                return self._ivf_snapshot()
            return self._quant_snapshot()
        ids, mat = self.y.materialize()
        with self._snap_lock:
            if self._snapshot is None or self._snapshot_src is not mat:
                prev, delta = None, None
                if self._snapshot is not None and self._snapshot.mat is not None \
                        and mat is not None:
                    # catch up across any number of incremental generations
                    # (e.g. get_vtv consumed pending batches in between)
                    delta = self.y.delta_since(self._snapshot.mat, mat)
                    if delta is not None:
                        prev = self._snapshot
                self._snapshot = _YSnapshot(
                    ids, mat, self.lsh, self.mesh, self.shard_axis,
                    prev=prev, delta=delta, device_dtype=self.device_dtype,
                )
                self._snapshot_src = mat
            return self._snapshot

    def _quant_snapshot(self) -> _QuantSnapshot:
        """Current int8 device view: incremental (requantize + scatter only
        the rows a speed microbatch touched) when the arena's write log
        covers the gap, full chunked rebuild otherwise. The store's f32
        device-materialization cache is never engaged in this mode — the
        arena slab itself is the exact-f32 source of truth (the rescore
        gathers straight from it)."""
        with self._snap_lock:
            prev = self._snapshot if isinstance(self._snapshot, _QuantSnapshot) else None
            if prev is not None and prev.qmat is not None:
                delta = self.y.delta_info(prev.version, len(prev.ids))
                if delta is not None:
                    if not delta.changed_ids and not delta.appended_ids:
                        return prev
                    self._snapshot = _QuantSnapshot.from_delta(
                        prev, delta, self.lsh
                    )
                    return self._snapshot
            ids, host, version, row_view = self.y.host_matrix()
            self._snapshot = _QuantSnapshot.build(
                ids, host, version, self.lsh, row_view, prev=prev
            )
            return self._snapshot

    def _ivf_snapshot(self) -> "ivf_mod.IVFSnapshot":
        """Current IVF device view: incremental (requantize + reassign only
        the rows a speed microbatch touched, rewrite only the affected
        cells) when the arena's write log covers the gap AND the update
        neither overflows a cell nor drifts the balance past the skew
        bound; full re-cluster rebuild otherwise."""
        with self._snap_lock:
            prev = (self._snapshot
                    if isinstance(self._snapshot, ivf_mod.IVFSnapshot)
                    else None)
            if prev is not None and prev.cell_q is not None:
                delta = self.y.delta_info(prev.version, len(prev.ids))
                if delta is not None:
                    if not delta.changed_ids and not delta.appended_ids:
                        return prev
                    nxt = ivf_mod.IVFSnapshot.from_delta(
                        prev, delta, self.lsh
                    )
                    if nxt is not None:
                        self._snapshot = nxt
                        return nxt
            ids, host, version, row_view = self.y.host_matrix()
            self._snapshot = ivf_mod.IVFSnapshot.build(
                ids, host, version, self.lsh, row_view, prev=prev,
                cells=self.index_cells, probes=self.index_probes,
                skew_bound=self.index_skew,
            )
            return self._snapshot

    def _rescore_exact(self, snap: _QuantSnapshot, qs_host: np.ndarray,
                       vals: np.ndarray, idx: np.ndarray,
                       cosine: bool = False) -> "tuple[np.ndarray, np.ndarray]":
        """Exact f32 rescore of the quantized scan's candidates: gather the
        candidate rows from the snapshot's PINNED arena-slab view (one
        fancy index — the slab is what makes this cheap), recompute exact
        scores, and return the candidates re-ranked by exact score. Masked
        candidates (-inf from the scan) stay -inf. For ``cosine`` the batch
        dimension is the query-vector set of ONE request (mean cosine)."""
        B, R = idx.shape
        rows = snap.gather_rows(idx.reshape(-1)).reshape(B, R, -1)
        if cosine:
            # one request, many query vectors: qs_host (Q, k); rows (1, R, k)
            r = rows[0]
            rn = np.linalg.norm(r, axis=1)
            qn = np.linalg.norm(qs_host, axis=1)
            sims = (r @ qs_host.T) / np.maximum(
                rn[:, None] * qn[None, :], 1e-12
            )
            exact = np.mean(sims, axis=1, dtype=np.float32)[None, :]
        else:
            exact = np.einsum("bk,brk->br", qs_host, rows).astype(np.float32)
        exact = np.where(np.isfinite(vals), exact, -np.inf)
        order = np.argsort(-exact, axis=1, kind="stable")
        return (np.take_along_axis(exact, order, axis=1),
                np.take_along_axis(idx, order, axis=1))

    def _quant_scan(self, snap: _QuantSnapshot, qs_host: np.ndarray,
                    r: int, excl, valid=None, lut=None,
                    register_cost: "str | None" = None):
        """One quantized candidate scan + exact rescore: (vals, idx) of
        width ``r``, exact-f32-ranked. ``excl`` is the padded (B, E) index
        array or None; ``valid`` an optional (n,) candidate mask; ``lut``
        a per-query (B, num_buckets) LSH lookup table (selects the masked
        program). One registration/record/rescore sequence serves every
        variant."""
        qs = jnp.asarray(qs_host)
        if lut is not None:
            fn = _quant_candidates_masked
            args = (snap.qmat, snap.qscale, qs, lut, snap.buckets, excl, r)
        else:
            fn = _quant_candidates
            args = (snap.qmat, snap.qscale, qs, valid, excl, r)
        if register_cost is not None and (
                register_cost not in snap.cost_keys_attempted
                and metrics_mod.default_registry().enabled):
            snap.cost_keys_attempted.add(register_cost)
            compilecache.aot_compile(fn, *args, cost_key=register_cost)
        vals, idx = fn(*args)
        if register_cost is not None:
            profiling.costs().record(register_cost)
        return self._rescore_exact(snap, qs_host, np.asarray(vals),
                                   np.asarray(idx))

    # -- query primitives ----------------------------------------------------
    @staticmethod
    def _excluded_indices(snap: _YSnapshot, excluded, batch: int) -> np.ndarray:
        """(B, E) int32 of global Y rows to mask out, -1-padded, E a pow2
        FLOORED at ``_EXCL_PAD_MIN`` so the common exclusion widths all
        share one jit signature — the one the batch warmer precompiles."""
        idx_lists: list[list[int]] = []
        max_e = 1
        for b in range(batch):
            ids = excluded[b] if excluded is not None else None
            ix = (
                [snap.id_to_idx[i] for i in ids if i in snap.id_to_idx]
                if ids
                else []
            )
            idx_lists.append(ix)
            max_e = max(max_e, len(ix))
        width = max(_EXCL_PAD_MIN, _round_up_pow2(max_e))
        out = np.full((batch, width), -1, dtype=np.int32)
        for b, ix in enumerate(idx_lists):
            out[b, : len(ix)] = ix
        return out

    def _build_lut(self, qs_host: np.ndarray) -> np.ndarray:
        """(B, num_buckets) bool LSH candidate lookup table, one row per
        query — fully vectorized over the batch (lsh.get_candidate_lut)."""
        return self.lsh.get_candidate_lut(qs_host)

    def _sharded_query(self, snap: _YSnapshot, qs_host: np.ndarray, want: int, excluded):
        """Multi-device scan: per-shard matmul + local top-k + cross-shard
        merge, with LSH lut and per-query known-item exclusion applied
        device-side (no host fallback for filtered traffic)."""
        B = qs_host.shape[0]
        ndev = snap.mesh.shape[snap.shard_axis]
        n_local = snap.sharded_mat.shape[0] // ndev
        want = min(want, snap.n)
        k = min(n_local, _round_up_pow2(max(want, 16)))
        k_final = min(ndev * k, _round_up_pow2(max(want, 16)))
        use_lut = self.lsh is not None and snap.buckets is not None
        lut_j = (
            jnp.asarray(self._build_lut(qs_host))
            if use_lut
            else jnp.zeros((B, 1), dtype=bool)
        )
        use_excl = excluded is not None and any(e for e in excluded)
        excl = jnp.asarray(
            self._excluded_indices(snap, excluded, B)
            if use_excl
            else np.full((B, 1), -1, dtype=np.int32)  # fixed shard_map arity
        )
        fn = _sharded_top_k_fn(
            snap.mesh, snap.shard_axis, k, k_final, snap.n, use_lut, use_excl
        )
        vals, idx = fn(snap.sharded_mat, jnp.asarray(qs_host), excl, lut_j,
                       snap.sharded_buckets)
        return np.asarray(vals), np.asarray(idx)

    def top_n(
        self,
        query_vec: np.ndarray,
        how_many: int,
        offset: int = 0,
        allowed: "Callable[[str], bool] | None" = None,
        rescore: "Callable[[str, float], float] | None" = None,
        excluded: "Sequence[str] | None" = None,
    ) -> list[tuple[str, float]]:
        """Dot-product top-N over Y: one matmul + top_k (ALSServingModel.topN
        :261-276, TopNConsumer:56-73). ``excluded`` ids (known-item filtering)
        are masked on device; ``allowed``/``rescore`` host hooks (rescorer SPI)
        filter the candidate stream with widening retry."""
        snap = self.y_snapshot()
        if snap.n == 0 or (snap.mat is None and not isinstance(
                snap, (_QuantSnapshot, ivf_mod.IVFSnapshot))):
            return []
        q_host = np.asarray(query_vec, dtype=np.float32)
        if isinstance(snap, ivf_mod.IVFSnapshot):
            return ivf_mod.top_n(
                self, snap, q_host, how_many, offset, allowed, rescore,
                excluded,
            )
        if isinstance(snap, _QuantSnapshot):
            return self._quant_top_n(
                snap, q_host, how_many, offset, allowed, rescore, excluded
            )
        want = how_many + offset
        if snap.sharded_mat is not None:
            k = want if allowed is None and rescore is None else max(4 * want, 64)
            while True:
                vals, idx = self._sharded_query(
                    snap, q_host[None, :], k, [excluded] if excluded else None
                )
                out = self._collect(snap, vals[0], idx[0], want, allowed, rescore)
                if len(out) >= want or k >= snap.n:
                    return out[offset:offset + how_many]
                k = min(snap.n, k * 2)  # widen: host filter consumed candidates
        q = jnp.asarray(q_host)
        # unfiltered hot path stays exactly matmul + top_k: masks are None
        # (static) unless LSH or exclusions actually apply
        has_lsh = self.lsh is not None and snap.buckets is not None
        valid = self._candidate_mask(snap, q_host) if has_lsh else None
        excl = None
        if excluded:
            # pow2-padded with -1 fill (the batch helper at batch=1) so jit
            # signatures stay stable: every distinct known-item count would
            # otherwise trigger a fresh compile on the serving hot path
            padded = self._excluded_indices(snap, [excluded], 1)
            if (padded >= 0).any():
                excl = jnp.asarray(padded)
        # score once; widenings re-run only the top-k over the cached scores
        scores = _masked_scores(snap.score_mat, q[None, :], valid, excl)
        k = min(snap.n, _round_up_pow2(max(4 * want, 64)))
        while True:
            vals, idx = _top_k_of_scores(scores, k)
            out = self._collect(
                snap, np.asarray(vals)[0], np.asarray(idx)[0], want, allowed, rescore
            )
            if len(out) >= want or k >= snap.n:
                return out[offset:offset + how_many]
            k = min(snap.n, k * 2)  # widen if filtering consumed candidates

    def _quant_top_n(
        self, snap: _QuantSnapshot, q_host: np.ndarray, how_many: int,
        offset: int, allowed, rescore, excluded,
    ) -> list[tuple[str, float]]:
        """Single-query top-N on the int8 path: quantized candidate scan →
        exact f32 rescore from the arena → host filtering. The quantized
        matmul runs ONCE; widenings (``allowed``/``rescore`` hooks consuming
        candidates) re-run only the top-k over the cached score matrix,
        exactly like the f32 path — never another full-bandwidth pass
        over the int8 slab."""
        want = how_many + offset
        excl = None
        if excluded:
            padded = self._excluded_indices(snap, [excluded], 1)
            if (padded >= 0).any():
                excl = jnp.asarray(padded)
        has_lsh = self.lsh is not None and snap.buckets is not None
        valid = self._candidate_mask(snap, q_host) if has_lsh else None
        scores = _quant_masked_scores(
            snap.qmat, snap.qscale, jnp.asarray(q_host[None, :]), valid, excl
        )
        r = min(snap.n, _round_up_pow2(max(int(self.rescore_factor * want), 16)))
        while True:
            v, i = _top_k_of_scores(scores, r)
            vals, idx = self._rescore_exact(
                snap, q_host[None, :], np.asarray(v), np.asarray(i)
            )
            out = self._collect(snap, vals[0], idx[0], want, allowed, rescore)
            if len(out) >= want or r >= snap.n:
                return out[offset:offset + how_many]
            r = min(snap.n, r * 2)  # widen: host filter consumed candidates

    def top_n_batch(
        self,
        query_vecs: np.ndarray,
        how_many: int,
        alloweds: "Sequence[Callable[[str], bool] | None] | None" = None,
        excluded: "Sequence[Sequence[str] | None] | None" = None,
    ) -> list[list[tuple[str, float]]]:
        """Micro-batched top-N: many queries in ONE matmul+top_k device call —
        the TPU-idiomatic serving pattern (amortizes per-call overhead that the
        reference spends thread-fanning partition scans). ``excluded[b]`` ids
        are masked device-side; ``alloweds`` host callables (rescorer SPI)
        filter after the scan. One histogram observe + one counter add per
        CALL (not per query) keeps the hot path inside the metrics budget."""
        _TOPN_QUERIES.inc(len(query_vecs))
        t0 = time.perf_counter()
        try:
            return self._top_n_batch(query_vecs, how_many, alloweds, excluded)
        finally:
            # exemplar: the coalescer activates its device-call span around
            # this call, so a slow bucket points at that concrete trace
            _TOPN_BATCH_SECONDS.observe(
                time.perf_counter() - t0, exemplar=spans.current_trace_id()
            )

    def _top_n_batch(
        self,
        query_vecs: np.ndarray,
        how_many: int,
        alloweds: "Sequence[Callable[[str], bool] | None] | None" = None,
        excluded: "Sequence[Sequence[str] | None] | None" = None,
    ) -> list[list[tuple[str, float]]]:
        snap = self.y_snapshot()
        if snap.n == 0 or (snap.mat is None and not isinstance(
                snap, (_QuantSnapshot, ivf_mod.IVFSnapshot))):
            return [[] for _ in range(len(query_vecs))]
        qs_host = np.asarray(query_vecs, dtype=np.float32)
        filtering = alloweds is not None and any(a is not None for a in alloweds)
        if isinstance(snap, ivf_mod.IVFSnapshot):
            return ivf_mod.top_n_batch(
                self, snap, qs_host, how_many, alloweds, excluded, filtering
            )
        if isinstance(snap, _QuantSnapshot):
            return self._quant_top_n_batch(
                snap, qs_host, how_many, alloweds, excluded, filtering
            )
        if snap.sharded_mat is not None and not filtering:
            # sharded scan: calls are attributed (cost accounting counts
            # them) but no per-call cost is registered for the multi-shard
            # program — the calls-without-flops gap stays visible
            profiling.costs().record(
                f"als.top_n_batch/b{len(qs_host)}+sharded"
            )
            vals, idx = self._sharded_query(snap, qs_host, how_many, excluded)
            vals, idx = vals[:, :how_many], idx[:, :how_many]
            ids = snap.ids
            return [
                [(ids[int(i)], float(v)) for v, i in zip(vals[b], idx[b])
                 if np.isfinite(v)]
                for b in range(len(query_vecs))
            ]
        qs = jnp.asarray(qs_host)
        use_excl = excluded is not None and any(e for e in excluded)
        excl = (
            jnp.asarray(self._excluded_indices(snap, excluded, len(qs_host)))
            if use_excl
            else None
        )
        cost_reg = profiling.costs()
        cost_key = _topn_cost_key(len(qs_host), use_excl)
        if self.lsh is None or snap.buckets is None:
            k = min(
                snap.n,
                _round_up_pow2(max(2 * how_many, 64) if filtering else max(how_many, 16)),
            )
            if (cost_key not in snap.cost_keys_attempted
                    and metrics_mod.default_registry().enabled):
                # first use of this signature this generation: the dispatch
                # below pays the XLA compile anyway — the sanctioned AOT
                # route shares that compile AND yields the executable's
                # cost_analysis, so unwarmed signatures (odd batch sizes,
                # direct callers) still attribute FLOPs instead of reading
                # zero forever
                snap.cost_keys_attempted.add(cost_key)
                compilecache.aot_compile(
                    _top_k_dot_batch, snap.score_mat, qs, None, excl, k,
                    cost_key=cost_key,
                )
            vals, idx = _top_k_dot_batch(snap.score_mat, qs, None, excl, k)
        else:
            # per-query LSH candidate masks: (B, num_buckets) lookup table
            # indexed by item bucket on device
            k = min(snap.n, _round_up_pow2(max(2 * how_many, 64)))
            lut = jnp.asarray(self._build_lut(qs_host))
            if (cost_key not in snap.cost_keys_attempted
                    and metrics_mod.default_registry().enabled):
                snap.cost_keys_attempted.add(cost_key)
                compilecache.aot_compile(
                    _top_k_dot_batch_masked, snap.score_mat, qs, lut,
                    snap.buckets, excl, k, cost_key=cost_key,
                )
            vals, idx = _top_k_dot_batch_masked(
                snap.score_mat, qs, lut, snap.buckets, excl, k
            )
        cost_reg.record(cost_key)
        vals, idx = np.asarray(vals), np.asarray(idx)
        if not filtering:
            ids = snap.ids
            vb, ib = vals[:, :how_many], idx[:, :how_many]
            return [
                [(ids[int(i)], float(v)) for v, i in zip(vb[b], ib[b]) if np.isfinite(v)]
                for b in range(len(query_vecs))
            ]
        out = []
        for b in range(len(query_vecs)):
            allowed = alloweds[b] if alloweds else None
            got = self._collect(snap, vals[b], idx[b], how_many, allowed, None)[:how_many]
            if len(got) < how_many and k < snap.n:
                # heavy filtering consumed this query's candidates — fall back
                # to the widening single-query path
                got = self.top_n(
                    qs_host[b], how_many, 0, allowed, None,
                    excluded=excluded[b] if excluded else None,
                )
            out.append(got)
        return out

    def _quant_top_n_batch(
        self, snap: _QuantSnapshot, qs_host: np.ndarray, how_many: int,
        alloweds, excluded, filtering: bool,
    ) -> list[list[tuple[str, float]]]:
        """Batched top-N on the int8 path: ONE quantized device scan over
        the whole query batch (¼ the f32 HBM per pass) returning
        ``rescore-factor × how_many`` candidates each, exact-f32-rescored
        from the arena slab before the final cut. Cost keys carry ``+int8``
        so the attribution (and the warm ladder) see the quantized programs
        as their own signatures."""
        use_excl = excluded is not None and any(e for e in excluded)
        excl = (
            jnp.asarray(self._excluded_indices(snap, excluded, len(qs_host)))
            if use_excl
            else None
        )
        cost_key = _topn_cost_key(len(qs_host), use_excl, quant=True)
        r = min(snap.n,
                _round_up_pow2(max(int(self.rescore_factor * how_many), 16)))
        lut = (
            jnp.asarray(self._build_lut(qs_host))
            if self.lsh is not None and snap.buckets is not None
            else None
        )
        vals, idx = self._quant_scan(
            snap, qs_host, r, excl, lut=lut, register_cost=cost_key
        )
        if not filtering:
            ids = snap.ids
            vb, ib = vals[:, :how_many], idx[:, :how_many]
            return [
                [(ids[int(i_)], float(v_)) for v_, i_ in zip(vb[b], ib[b])
                 if np.isfinite(v_)]
                for b in range(len(qs_host))
            ]
        out = []
        for b in range(len(qs_host)):
            allowed = alloweds[b] if alloweds else None
            got = self._collect(snap, vals[b], idx[b], how_many, allowed, None)[:how_many]
            if len(got) < how_many and r < snap.n:
                # heavy filtering consumed this query's candidates — fall
                # back to the widening single-query quant path
                got = self._quant_top_n(
                    snap, qs_host[b], how_many, 0, allowed, None,
                    excluded[b] if excluded else None,
                )
            out.append(got)
        return out

    def warm_bucket(self, batch_size: int, how_many: int = 10) -> None:
        """Pre-compile the batched top-N program for ONE pow2 batch size
        against the live factor shapes — the per-bucket unit of the serving
        warmup ladder (serving/app.py _BatchWarmer, smallest bucket first).

        Two steps: an AOT ``jitted.lower(shapes).compile()`` via
        :func:`compilecache.aot_compile` (seeds the in-process lowering
        cache AND, when ``oryx.compile.cache-dir`` is set, the persistent
        cache — so restarts and sibling replicas skip the XLA compile
        entirely), then one real zero-batch execution to populate the jit
        dispatch cache the request path actually hits and to materialize
        the device-resident factor snapshot. Raises when the model has no
        items yet (the warmer retries later).

        BOTH signature families warm: exclusion-free AND exclusion-carrying
        — the default ``/recommend`` path (considerKnownItems=false) always
        sends known-item exclusions, and ``_excluded_indices`` pads them to
        the shape-stable ``_EXCL_PAD_MIN`` width this warms, so the first
        client burst after a MODEL handoff pays no compile on the endpoint
        it actually calls."""
        import jax

        snap = self.y_snapshot()
        if snap.n == 0 or (snap.mat is None and not isinstance(
                snap, (_QuantSnapshot, ivf_mod.IVFSnapshot))):
            raise ValueError("no item factors to warm against yet")
        qs_struct = jax.ShapeDtypeStruct(
            (batch_size, self.features), jnp.float32
        )
        excl_struct = jax.ShapeDtypeStruct(
            (batch_size, _EXCL_PAD_MIN), jnp.int32
        )
        if isinstance(snap, ivf_mod.IVFSnapshot):
            # the IVF ladder: pow2 (batch, probes) probe + scan signatures
            # under their own cost keys; the shared zero-batch executions
            # below then populate the exact dispatch caches requests hit
            ivf_mod.warm_bucket(self, snap, batch_size, how_many)
        elif isinstance(snap, _QuantSnapshot):
            # the quantized ladder: its programs (and so its AOT cost keys)
            # are distinct from the f32/bf16 scan's — a quantized-model
            # handoff warms exactly the signatures its traffic dispatches
            r = min(snap.n,
                    _round_up_pow2(max(int(self.rescore_factor * how_many), 16)))
            keys = (_topn_cost_key(batch_size, False, quant=True),
                    _topn_cost_key(batch_size, True, quant=True))
            if self.lsh is None or snap.buckets is None:
                compilecache.aot_compile(
                    _quant_candidates, snap.qmat, snap.qscale, qs_struct,
                    None, None, r, cost_key=keys[0],
                )
                compilecache.aot_compile(
                    _quant_candidates, snap.qmat, snap.qscale, qs_struct,
                    None, excl_struct, r, cost_key=keys[1],
                )
            else:
                lut_struct = jax.ShapeDtypeStruct(
                    (batch_size, self.lsh.num_buckets), jnp.bool_
                )
                compilecache.aot_compile(
                    _quant_candidates_masked, snap.qmat, snap.qscale,
                    qs_struct, lut_struct, snap.buckets, None, r,
                    cost_key=keys[0],
                )
                compilecache.aot_compile(
                    _quant_candidates_masked, snap.qmat, snap.qscale,
                    qs_struct, lut_struct, snap.buckets, excl_struct, r,
                    cost_key=keys[1],
                )
            snap.cost_keys_attempted.update(keys)
        elif snap.sharded_mat is not None:
            # the sharded scan builds its program through the lru-cached
            # _sharded_top_k_fn; the executions below compile it off-path
            pass
        elif self.lsh is None or snap.buckets is None:
            k = min(snap.n, _round_up_pow2(max(how_many, 16)))
            compilecache.aot_compile(
                _top_k_dot_batch, snap.score_mat, qs_struct, None, None, k,
                cost_key=_topn_cost_key(batch_size, False),
            )
            compilecache.aot_compile(
                _top_k_dot_batch, snap.score_mat, qs_struct, None,
                excl_struct, k,
                cost_key=_topn_cost_key(batch_size, True),
            )
        else:
            k = min(snap.n, _round_up_pow2(max(2 * how_many, 64)))
            lut_struct = jax.ShapeDtypeStruct(
                (batch_size, self.lsh.num_buckets), jnp.bool_
            )
            compilecache.aot_compile(
                _top_k_dot_batch_masked, snap.score_mat, qs_struct,
                lut_struct, snap.buckets, None, k,
                cost_key=_topn_cost_key(batch_size, False),
            )
            compilecache.aot_compile(
                _top_k_dot_batch_masked, snap.score_mat, qs_struct,
                lut_struct, snap.buckets, excl_struct, k,
                cost_key=_topn_cost_key(batch_size, True),
            )
        if snap.sharded_mat is None and not isinstance(
                snap, (_QuantSnapshot, ivf_mod.IVFSnapshot)):
            # mark both signatures attempted: the lazy first-use
            # registration in _top_n_batch would otherwise re-lower and
            # re-compile each one the ladder just registered — once per
            # signature per generation, during the handoff warm window
            snap.cost_keys_attempted.update({
                _topn_cost_key(batch_size, False),
                _topn_cost_key(batch_size, True),
            })
        zeros = np.zeros((batch_size, self.features), dtype=np.float32)
        self.top_n_batch(zeros, how_many)
        # one real exclusion-carrying execution: an id no snapshot contains
        # maps to an all(-1) mask of the floored width — the exact program
        # the default endpoint's known-item exclusions dispatch to
        self.top_n_batch(
            zeros, how_many,
            excluded=[("__warm__",)] + [None] * (batch_size - 1),
        )

    def top_n_cosine(
        self,
        query_vecs: np.ndarray,
        how_many: int,
        offset: int = 0,
        allowed: "Callable[[str], bool] | None" = None,
        rescore: "Callable[[str, float], float] | None" = None,
    ) -> list[tuple[str, float]]:
        """Mean-cosine top-N for /similarity (CosineAverageFunction.java:67)."""
        snap = self.y_snapshot()
        if snap.n == 0 or (snap.mat is None and not isinstance(
                snap, (_QuantSnapshot, ivf_mod.IVFSnapshot))):
            return []
        qs_host = np.atleast_2d(np.asarray(query_vecs, dtype=np.float32))
        if isinstance(snap, ivf_mod.IVFSnapshot):
            return ivf_mod.top_n_cosine(
                self, snap, qs_host,
                np.linalg.norm(qs_host, axis=1), how_many, offset,
                allowed, rescore,
            )
        qs = jnp.asarray(qs_host)
        q_norms = jnp.linalg.norm(qs, axis=1)
        # union of candidate buckets across ALL query vectors, mirroring the
        # reference's per-partition candidate scan
        valid = self._candidate_mask(snap, qs_host[0])
        for extra in qs_host[1:]:
            valid = valid | self._candidate_mask(snap, extra)
        want = how_many + offset
        if isinstance(snap, _QuantSnapshot):
            # quantized candidates (norms are exact f32), exact mean-cosine
            # rescore from the arena slab before the final cut
            r = min(snap.n,
                    _round_up_pow2(max(int(self.rescore_factor * want), 16)))
            while True:
                v, i = _quant_cosine_candidates(
                    snap.qmat, snap.qscale, snap.norms, qs, q_norms, valid, r
                )
                vals, idx = self._rescore_exact(
                    snap, qs_host, np.asarray(v)[None, :],
                    np.asarray(i)[None, :], cosine=True,
                )
                out = self._collect(snap, vals[0], idx[0], want, allowed, rescore)
                if len(out) >= want or r >= snap.n:
                    return out[offset:offset + how_many]
                r = min(snap.n, r * 2)
        k = min(snap.n, _round_up_pow2(max(4 * want, 64)))
        while True:
            vals, idx = _top_k_cosine_sum(snap.mat, snap.norms, qs, q_norms, valid, k)
            out = self._collect(snap, np.asarray(vals), np.asarray(idx), want, allowed, rescore)
            if len(out) >= want or k >= snap.n:
                return out[offset:offset + how_many]
            k = min(snap.n, k * 2)

    def _candidate_mask(self, snap: _YSnapshot, query_vec: np.ndarray):
        if self.lsh is None or snap.buckets is None:
            return jnp.ones(snap.n, dtype=bool)
        candidates = self.lsh.get_candidate_indices(query_vec)
        lut = np.zeros(self.lsh.num_buckets, dtype=bool)
        lut[candidates] = True
        return jnp.asarray(lut)[snap.buckets]

    @staticmethod
    def _collect(snap, vals, idx, want, allowed, rescore) -> list[tuple[str, float]]:
        out: list[tuple[str, float]] = []
        for v, i in zip(vals, idx):
            if not np.isfinite(v):
                break
            id_ = snap.ids[int(i)]
            if allowed is not None and not allowed(id_):
                continue
            score = float(v)
            if rescore is not None:
                score = rescore(id_, score)
                if math.isnan(score):
                    continue
            out.append((id_, score))
        if rescore is not None:
            out.sort(key=lambda t: -t[1])
        return out

    def device_factor_bytes(self) -> int:
        """Bytes the current Y snapshot holds on device (f32 matrix +
        scoring copy + norms + buckets, or the int8 slab + scales) — the
        HBM side of the bench memory section's f32-vs-int8 comparison."""
        snap = self.y_snapshot()
        if isinstance(snap, ivf_mod.IVFSnapshot):
            return snap.device_nbytes()
        arrays = (
            (snap.qmat, snap.qscale, snap.norms, snap.buckets)
            if isinstance(snap, _QuantSnapshot)
            else (snap.mat,
                  snap.score_mat if snap.score_mat is not snap.mat else None,
                  snap.norms, snap.buckets, snap.sharded_mat,
                  snap.sharded_buckets)
        )
        return int(sum(
            int(getattr(a, "nbytes", 0) or 0) for a in arrays if a is not None
        ))

    def dot_with_items(self, query_vec: np.ndarray, item_ids: Sequence[str]) -> list[float]:
        q = np.asarray(query_vec, dtype=np.float32)
        return [
            float(np.dot(q, v)) if (v := self.y.get_vector(i)) is not None else 0.0
            for i in item_ids
        ]

    def get_yty_solver(self):
        return self.yty_cache.get(blocking=True)

    def precompute_solvers(self) -> None:
        self.yty_cache.compute_now()

    def build_temporary_user_vector(
        self, item_values: Sequence[tuple[str, float]], xu: "np.ndarray | None" = None
    ) -> "np.ndarray | None":
        """Fold a context of (item, value) pairs into a temporary user vector
        (EstimateForAnonymous.buildTemporaryUserVector)."""
        from oryx_tpu.models.als import foldin

        solver = self.get_yty_solver()
        if solver is None:
            return None
        vec = None if xu is None else np.asarray(xu, dtype=np.float32)
        for item, value in item_values:
            yi = self.y.get_vector(item)
            new_vec = foldin.compute_updated_xu(solver, value, vec, yi, self.implicit)
            if new_vec is not None:
                vec = new_vec
        return vec


class ALSServingModelManager(AbstractServingModelManager):
    def __init__(self, config):
        super().__init__(config)
        self.sample_rate = config.get_float("oryx.als.sample-rate")
        self.min_model_load_fraction = config.get_float("oryx.serving.min-model-load-fraction")
        # device-factor representation: "auto" (bf16 scoring copy on TPU),
        # explicit "float32"/"bfloat16", or "int8" (per-row-scaled slab +
        # exact f32 rescore of the top rescore-factor x n candidates)
        self.device_dtype = config.get_string(
            "oryx.serving.device-dtype", "auto"
        )
        if self.device_dtype not in _DEVICE_DTYPES:
            raise ValueError(
                f"oryx.serving.device-dtype must be one of {_DEVICE_DTYPES}, "
                f"not {self.device_dtype!r}"
            )
        self.rescore_factor = config.get_float(
            "oryx.serving.rescore-factor", 4.0
        )
        # device-resident IVF candidate generation (sublinear serving
        # scan); engages only with device-dtype=int8 — the cells are the
        # int8 representation and the rescore rides the arena slab
        self.index_enabled = config.get_bool(
            "oryx.serving.index.enabled", False
        )
        self.index_cells = config.get_int("oryx.serving.index.cells", 0)
        self.index_probes = config.get_int("oryx.serving.index.probes", 8)
        self.index_skew = config.get_float(
            "oryx.serving.index.rebalance-skew", 4.0
        )
        # opportunistic YᵀY pre-trigger once the model is loaded enough, so
        # the first fold-in request doesn't stall on the factorization
        # (ALSServingModelManager.java:95-105); rate-limited like the
        # reference's test-and-trigger
        self._solver_trigger_rate = RateLimitCheck(5)
        self.model: ALSServingModel | None = None
        # double-buffered generation handoff: with the batch warmer running,
        # a MODEL push with new array shapes builds the incoming generation
        # here while the warm old generation keeps answering queries; the
        # warmer precompiles the staged model's buckets off-path and then
        # promotes it atomically — an update-topic model push never causes a
        # request-visible compile storm
        self._staged: ALSServingModel | None = None
        self._staged_at = 0.0
        self._swap_lock = threading.Lock()
        self._prewarm_swap = (
            config.get_bool("oryx.serving.compute.precompile-batches", False)
            and config.get_bool("oryx.compile.prewarm-swap", True)
        )
        self._swap_deadline = config.get_float(
            "oryx.compile.swap-deadline-sec", 120.0
        )
        _LOAD_FRACTION.set_function(_load_fraction_fn(weakref.ref(self)))
        self.rescorer_provider = load_rescorer_providers(config)
        self.mesh = None
        if config.get_bool("oryx.serving.compute.sharded", False):
            from oryx_tpu.parallel.mesh import make_mesh

            if len(jax.devices()) > 1:
                self.mesh = make_mesh(axes=("model",))
                log.info("serving Y sharded over %d devices", self.mesh.size)
            else:
                log.info("sharded serving requested but only one device")

    def get_model(self) -> "ALSServingModel | None":
        # deadline valve on the request path: one None-check when no swap is
        # staged; a staged generation whose warmer died (or whose warm keeps
        # failing) must still land eventually rather than strand the push.
        # Lock-free reads: single reference loads are atomic under the GIL
        # and a stale value is benign (the old generation stays valid until
        # the flip, which happens under _swap_lock and re-checks there)
        staged = self._staged  # analyze: ignore[lock-discipline] -- atomic reference load on the hot path; flip is under _swap_lock
        if staged is not None and self._swap_deadline > 0 and (
            time.monotonic() - self._staged_at > self._swap_deadline  # analyze: ignore[lock-discipline] -- _staged_at is written before _staged publishes, so a visible staged model always pairs with its own timestamp
        ):
            if self._promote_staged(expected=staged, deadline=True):
                log.warning(
                    "promoting staged model generation unwarmed: swap "
                    "deadline (%.0fs) passed", self._swap_deadline,
                )
        return self.model  # analyze: ignore[lock-discipline] -- atomic reference load on the hot path; flip is under _swap_lock

    def get_staged_model(self) -> "ALSServingModel | None":
        with self._swap_lock:
            return self._staged

    def promote_staged(self, expected=None) -> bool:
        """Atomically flip the warmed staged generation into service
        (called by the batch warmer after its bucket ladder completes).
        ``expected`` guards against promoting a model the caller did not
        warm: if a later MODEL push replaced the staged generation while
        the ladder ran, the flip is refused and the warmer re-runs."""
        return self._promote_staged(expected=expected, deadline=False)

    def _promote_staged(self, expected, deadline: bool) -> bool:
        with self._swap_lock:
            staged = self._staged
            if staged is None or (expected is not None and staged is not expected):
                return False
            self.model = staged
            self._staged = None
        (_DEADLINE_SWAPS if deadline else _PREWARMED_SWAPS).inc()
        # adoption timeline: the staged generation just went into service
        # (idempotent on the tracker side — the warmer and the deadline
        # valve can both report the same flip)
        lineage.tracker().mark_live()
        return True

    def _current_generation(self) -> "ALSServingModel | None":
        """The generation the update topic is describing NOW: the staged
        model once a MODEL handoff is in flight, else the serving one."""
        with self._swap_lock:
            return self._staged or self.model

    def consume_key_message(self, key: str, message: str) -> None:
        if key == "UP":
            model = self._current_generation()
            if model is None:
                return
            update = json.loads(message)
            kind, id_, vec = update[0], update[1], np.asarray(update[2], dtype=np.float32)
            if kind == "X":
                model.set_user_vector(id_, vec)
                if len(update) > 3:
                    model.add_known_items(id_, update[3])
            elif kind == "Y":
                model.set_item_vector(id_, vec)
            else:
                raise ValueError(f"bad update type: {kind}")
            self._maybe_trigger_solvers()
        elif key in ("MODEL", "MODEL-REF"):
            pmml = read_pmml_from_update_key_message(key, message)
            meta = pmml_codec.pmml_to_meta(pmml)
            features = meta["features"]
            current = self._current_generation()
            if current is None or current.features != features:
                new_model = ALSServingModel(
                    features, meta["implicit"], self.sample_rate,
                    mesh=self.mesh, device_dtype=self.device_dtype,
                    rescore_factor=self.rescore_factor,
                    index_enabled=self.index_enabled,
                    index_cells=self.index_cells,
                    index_probes=self.index_probes,
                    index_skew=self.index_skew,
                )
                # the handoff meta names every expected row: presize the
                # arenas so the fill skips doubling-growth copies
                new_model.x.reserve(len(meta["x_ids"]))
                new_model.y.reserve(len(meta["y_ids"]))
                new_model.expected_user_ids = set(meta["x_ids"])
                new_model.expected_item_ids = set(meta["y_ids"])
                with self._swap_lock:
                    if self.model is not None and self._prewarm_swap:
                        # double-buffer: keep serving the old generation; the
                        # warmer fills/warms this one off-path, then promotes.
                        # Timestamp BEFORE publishing the reference: the
                        # deadline valve reads both lock-free, and the old
                        # order let it pair a fresh staged model with a
                        # stale timestamp and promote it cold on the spot
                        staging = True
                        self._staged_at = time.monotonic()
                        self._staged = new_model
                    else:
                        staging = False
                        self.model = new_model
                        self._staged = None
                log.info("%s serving model generation (features=%d)",
                         "staging" if staging else "new", features)
            else:
                m = current
                m.retain_recent_and_user_ids(meta["x_ids"])
                m.retain_recent_and_item_ids(meta["y_ids"])
                m.retain_recent_and_known_items(meta["x_ids"])
                m.expected_user_ids = set(meta["x_ids"]) - set(m.x.ids())
                m.expected_item_ids = set(meta["y_ids"]) - set(m.y.ids())
            self._maybe_trigger_solvers()  # MODEL alone may cross the threshold
        else:
            raise ValueError(f"bad key: {key}")

    def _maybe_trigger_solvers(self) -> None:
        """Kick the async YᵀY factorization once the model passes the load
        fraction, so the first /estimateForAnonymous doesn't stall on it
        (ALSServingModelManager.java:95-105). Rate-limited: the fraction test
        walks the expected-ID sets, too costly per UP message; the launch
        itself is a no-op when the cache is clean (single-flight dirty flag),
        so later UPs re-warm naturally."""
        # the CURRENT generation: during a staged swap the UPs are filling
        # the staged model, and promoting it with a cold YtY solver would
        # stall the first post-flip fold-in on the synchronous factorization
        model = self._current_generation()
        if model is None or not self._solver_trigger_rate.test():
            return
        if model.get_fraction_loaded() >= self.min_model_load_fraction:
            model.precompute_solvers()
