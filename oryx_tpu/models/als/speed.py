"""ALS speed tier: in-memory model + per-microbatch fold-in updates.

Equivalent of the reference's ALSSpeedModel / ALSSpeedModelManager
(app/oryx-app/.../als/ALSSpeedModel.java:39-183,
ALSSpeedModelManager.java:51-233):

  * the model holds X and Y vector stores, expected-ID sets driving
    ``get_fraction_loaded``, and two single-flight SolverCaches (XᵀX, YᵀY);
  * ``MODEL``/``MODEL-REF`` messages start a new/retained model when the
    feature count changes, and set expectations + GC via retain-and-expect;
  * ``UP`` messages apply X/Y vectors (its own and the batch layer's);
  * ``build_updates`` gates on min-model-load-fraction, pre-warms solvers,
    sorts the microbatch by timestamp, aggregates with NaN-delete semantics,
    then folds in each interaction via the closed-form delta solve
    (foldin.compute_updated_xu) for both Xu and Yi, emitting
    ``["X", user, vec]`` / ``["Y", item, vec]`` JSON updates.
"""

from __future__ import annotations

import json
import logging

import numpy as np

from oryx_tpu.api.speed import AbstractSpeedModelManager, SpeedModel
from oryx_tpu.common.lockutils import RateLimitCheck
from oryx_tpu.ml.mlupdate import read_pmml_from_update_key_message
from oryx_tpu.models.als import data as als_data
from oryx_tpu.models.als import foldin
from oryx_tpu.models.als import pmml_codec
from oryx_tpu.models.als.vectors import FeatureVectorStore
from oryx_tpu.ops.solver import SolverCache

log = logging.getLogger(__name__)


def _format_rows(vecs: np.ndarray) -> list[str]:
    """Comma-joined '%.9g' rendering of each row of a float32 matrix —
    one C-level format call per row (numpy's savetxt inner idiom), ~10×
    stdlib json for big update batches. '%.9g' is exact for float32.

    Rows containing non-finite values (an explicit-feedback overflow can
    push a fold-in to inf) fall back to json.dumps, whose
    'Infinity'/'NaN' tokens Python consumers parse — '%g' would render
    'inf', which json.loads rejects."""
    rows64 = np.asarray(vecs, dtype=np.float64)
    fmt = ",".join(["%.9g"] * vecs.shape[1])
    out = [fmt % tuple(row) for row in rows64]
    finite = np.isfinite(rows64).all(axis=1)
    if not finite.all():
        for b in np.flatnonzero(~finite).tolist():
            out[b] = json.dumps(rows64[b].tolist())[1:-1]
    return out


class ALSSpeedModel(SpeedModel):
    """X/Y stores + expected IDs + solver caches (ALSSpeedModel.java:39-183)."""

    def __init__(self, features: int, implicit: bool):
        self.features = features
        self.implicit = implicit
        self.x = FeatureVectorStore()
        self.y = FeatureVectorStore()
        self.expected_user_ids: set[str] = set()
        self.expected_item_ids: set[str] = set()
        self.xtx_cache = SolverCache(self.x.get_vtv)
        self.yty_cache = SolverCache(self.y.get_vtv)

    def set_user_vector(self, user: str, vec: np.ndarray) -> None:
        self.x.set_vector(user, vec)
        self.expected_user_ids.discard(user)
        self.xtx_cache.set_dirty()

    def set_item_vector(self, item: str, vec: np.ndarray) -> None:
        self.y.set_vector(item, vec)
        self.expected_item_ids.discard(item)
        self.yty_cache.set_dirty()

    def retain_recent_and_user_ids(self, ids) -> None:
        self.x.retain_recent_and_ids(set(ids))
        self.xtx_cache.set_dirty()

    def retain_recent_and_item_ids(self, ids) -> None:
        self.y.retain_recent_and_ids(set(ids))
        self.yty_cache.set_dirty()

    def get_fraction_loaded(self) -> float:  # ALSSpeedModel.java:158-171
        total = self.x.size() + self.y.size() + len(self.expected_user_ids) + len(
            self.expected_item_ids
        )
        if total == 0:
            return 1.0
        return (self.x.size() + self.y.size()) / total


class ALSSpeedModelManager(AbstractSpeedModelManager):
    def __init__(self, config):
        self.config = config
        self.implicit = config.get_bool("oryx.als.implicit")
        self.log_strength = config.get_bool("oryx.als.logStrength")
        self.epsilon = config.get_float("oryx.als.hyperparams.epsilon")
        self.min_model_load_fraction = config.get_float("oryx.speed.min-model-load-fraction")
        # ALSSpeedModelManager.java:223-231: updates carry the interaction's
        # other ID so serving can track known items live, unless disabled
        self.no_known_items = config.get_bool("oryx.als.no-known-items")
        self.model: ALSSpeedModel | None = None
        self._log_rate = RateLimitCheck(60)

    # -- update-topic consumption (consumeKeyMessage:67-133) -----------------
    def consume_key_message(self, key: str, message: str) -> None:
        if key == "UP":
            if self.model is None:
                return  # ignore updates before the first model
            update = json.loads(message)
            kind, id_, vec = update[0], update[1], np.asarray(update[2], dtype=np.float32)
            if kind == "X":
                self.model.set_user_vector(id_, vec)
            elif kind == "Y":
                self.model.set_item_vector(id_, vec)
            else:
                raise ValueError(f"bad update type: {kind}")
        elif key in ("MODEL", "MODEL-REF"):
            pmml = read_pmml_from_update_key_message(key, message)
            meta = pmml_codec.pmml_to_meta(pmml)
            features = meta["features"]
            if self.model is None or self.model.features != features:
                log.info("new model (features=%d)", features)
                self.model = ALSSpeedModel(features, meta["implicit"])
                # presize the factor arenas: the handoff meta names every
                # expected row, so the fill skips doubling-growth copies
                self.model.x.reserve(len(meta["x_ids"]))
                self.model.y.reserve(len(meta["y_ids"]))
                self.model.expected_user_ids = set(meta["x_ids"])
                self.model.expected_item_ids = set(meta["y_ids"])
            else:
                self.model.retain_recent_and_user_ids(meta["x_ids"])
                self.model.retain_recent_and_item_ids(meta["y_ids"])
                self.model.expected_user_ids = set(meta["x_ids"]) - set(self.model.x.ids())
                self.model.expected_item_ids = set(meta["y_ids"]) - set(self.model.y.ids())
        else:
            raise ValueError(f"bad key: {key}")

    # -- microbatch fold-in (buildUpdates:135-221) ---------------------------
    def build_updates(self, new_data):
        model = self.model
        if model is None:
            return []
        fraction = model.get_fraction_loaded()
        if fraction < self.min_model_load_fraction:
            if self._log_rate.test():
                log.info("model not yet loaded enough (%.3f)", fraction)
            return []
        # pre-warm both solvers (precomputeSolvers :142)
        model.xtx_cache.compute_now()
        model.yty_cache.compute_now()

        # parse + aggregate through the (vectorized when plain-CSV) ingest
        # pipeline — identical semantics to aggregate() with no decay
        batch = als_data.prepare(
            [km.message for km in new_data], self.implicit,
            log_strength=self.log_strength, epsilon=self.epsilon,
        )
        if batch.nnz == 0:
            return []
        yty_solver = model.yty_cache.get(blocking=True)
        xtx_solver = model.xtx_cache.get(blocking=True)

        # gather the microbatch's vectors once (one read lock per store),
        # then fold in EVERY interaction with one batched solve per side —
        # B k×k solves collapse into two stacked-RHS matmuls instead of a
        # per-interaction host loop (the TPU answer to
        # ALSSpeedModelManager.java:198-220's parallelStream)
        u_ids, i_ids = batch.users.index_to_id, batch.items.index_to_id
        users_l = [u_ids[r] for r in batch.rows.tolist()]
        items_l = [i_ids[c] for c in batch.cols.tolist()]
        values = batch.vals.astype(np.float64)
        B, k = batch.nnz, model.features
        xus = np.zeros((B, k), dtype=np.float32)
        yis = np.zeros((B, k), dtype=np.float32)
        has_xu = np.zeros(B, dtype=bool)
        has_yi = np.zeros(B, dtype=bool)
        for b, xu in enumerate(model.x.get_vectors(users_l)):
            if xu is not None:
                xus[b], has_xu[b] = xu, True
        for b, yi in enumerate(model.y.get_vectors(items_l)):
            if yi is not None:
                yis[b], has_yi[b] = yi, True

        new_x = new_y = None
        changed_x = changed_y = None
        if yty_solver is not None:
            new_x, changed_x = foldin.compute_updated_batch(
                yty_solver, values, xus, has_xu, yis, has_yi, self.implicit
            )
        # symmetric item update (ALSSpeedModelManager.java:209-219)
        if xtx_solver is not None:
            new_y, changed_y = foldin.compute_updated_batch(
                xtx_solver, values, yis, has_yi, xus, has_xu, self.implicit
            )

        # wire format [matrix, ID, vector, [otherID]] — the 4th element feeds
        # serving's known-items live (ALSSpeedModelManager.java:223-231);
        # omitted entirely under oryx.als.no-known-items.
        # json.dumps per update was ~75% of the whole fold-in wall (2.8M
        # Python float serializations per 50k microbatch); the vectors are
        # formatted wholesale with one C-level '%.9g' pass per row instead
        # ('%.9g' round-trips float32 exactly; JSON accepts e-notation),
        # with IDs still json-escaped — they are arbitrary strings.
        updates: list[str] = []

        def emit(kind, new_v, changed, own_ids, other_ids):
            idx = np.flatnonzero(changed)
            if idx.size == 0:
                return
            rows = _format_rows(new_v[idx])
            for b, row in zip(idx.tolist(), rows):
                own = json.dumps(own_ids[b])
                if self.no_known_items:
                    updates.append(f'["{kind}",{own},[{row}]]')
                else:
                    other = json.dumps([other_ids[b]])
                    updates.append(f'["{kind}",{own},[{row}],{other}]')

        if new_x is not None:
            emit("X", new_x, changed_x, users_l, items_l)
        if new_y is not None:
            emit("Y", new_y, changed_y, items_l, users_l)
        return updates
