"""ALS model artifact: PMML-as-pointers + X/ Y/ factor part-files.

Wire-compatible with the reference's serialization
(ALSUpdate.mfModelToPMML:430-473, saveFeaturesRDD:490-499, readFeaturesRDD):
the PMML skeleton carries Extensions X="X/", Y="Y/", features, lambda,
implicit, alpha (iff implicit), logStrength, epsilon (iff logStrength), and
full XIDs/YIDs lists as extension content; the factor matrices live beside it
as gzipped text part-files of JSON lines ``["id", [v1, ..., vk]]``.
"""

from __future__ import annotations

import gzip
import json
from pathlib import Path
from typing import Iterator

import numpy as np

from oryx_tpu.common import ioutils
from oryx_tpu.pmml import pmmlutils

PARTS = 1  # single host writes one part per matrix; readers glob part-*


def save_features(path: Path, ids: list[str], matrix: np.ndarray) -> None:
    """Write one factor matrix as gzipped JSON lines (saveFeaturesRDD:490-499)."""
    ioutils.mkdirs(path)
    with gzip.open(path / "part-00000.gz", "wt", encoding="utf-8") as f:
        for i, id_ in enumerate(ids):
            f.write(json.dumps([id_, [float(v) for v in matrix[i]]]) + "\n")


def read_features(path: Path) -> Iterator[tuple[str, np.ndarray]]:
    """Read factor part-files back (readFeaturesRDD)."""
    for part in sorted(Path(path).glob("part-*")):
        opener = gzip.open if part.suffix == ".gz" else open
        with opener(part, "rt", encoding="utf-8") as f:
            for line in f:
                if line.strip():
                    id_, vec = json.loads(line)
                    yield str(id_), np.asarray(vec, dtype=np.float32)


def model_to_pmml(
    x: np.ndarray,
    y: np.ndarray,
    x_ids: list[str],
    y_ids: list[str],
    features: int,
    lam: float,
    alpha: float,
    implicit: bool,
    log_strength: bool,
    epsilon: float,
    candidate_path: Path,
):
    """Write X/ Y/ next to the model and return the pointer PMML
    (mfModelToPMML:430-473)."""
    candidate_path = Path(candidate_path)
    save_features(candidate_path / "X", x_ids, np.asarray(x))
    save_features(candidate_path / "Y", y_ids, np.asarray(y))
    pmml = pmmlutils.build_skeleton_pmml()
    pmmlutils.add_extension(pmml, "X", "X/")
    pmmlutils.add_extension(pmml, "Y", "Y/")
    pmmlutils.add_extension(pmml, "features", features)
    pmmlutils.add_extension(pmml, "lambda", lam)
    pmmlutils.add_extension(pmml, "implicit", str(implicit).lower())
    if implicit:
        pmmlutils.add_extension(pmml, "alpha", alpha)
    pmmlutils.add_extension(pmml, "logStrength", str(log_strength).lower())
    if log_strength:
        pmmlutils.add_extension(pmml, "epsilon", epsilon)
    pmmlutils.add_extension_content(pmml, "XIDs", x_ids)
    pmmlutils.add_extension_content(pmml, "YIDs", y_ids)
    return pmml


def pmml_to_meta(pmml) -> dict:
    """Decode the pointer PMML's hyperparameters + ID lists."""
    implicit = pmmlutils.get_extension_value(pmml, "implicit") == "true"
    log_strength = pmmlutils.get_extension_value(pmml, "logStrength") == "true"
    return {
        "x_dir": pmmlutils.get_extension_value(pmml, "X"),
        "y_dir": pmmlutils.get_extension_value(pmml, "Y"),
        "features": int(pmmlutils.get_extension_value(pmml, "features")),
        "lambda": float(pmmlutils.get_extension_value(pmml, "lambda")),
        "implicit": implicit,
        "alpha": float(pmmlutils.get_extension_value(pmml, "alpha") or 1.0),
        "logStrength": log_strength,
        "epsilon": float(pmmlutils.get_extension_value(pmml, "epsilon") or 1.0e-5),
        "x_ids": pmmlutils.get_extension_content(pmml, "XIDs") or [],
        "y_ids": pmmlutils.get_extension_content(pmml, "YIDs") or [],
    }
