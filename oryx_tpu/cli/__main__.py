from oryx_tpu.cli.main import main

raise SystemExit(main())
