"""oryx-run CLI: launch layers and manage topics from the command line.

Equivalent of the reference's deploy tier (deploy/oryx-{batch,speed,serving}
Main.java:30-37 and deploy/bin/oryx-run.sh:16-36): commands
``batch | speed | serving | broker | topic-setup | topic-tail |
topic-input``. Each layer command constructs its layer from the
(default-overlaid) config file, registers shutdown close, starts, and awaits
termination; the topic commands mirror ``kafka-setup`` / ``kafka-tail`` /
``kafka-input``; ``broker`` runs the ``tcp:`` network broker server (the
Kafka-broker-process equivalent, transport/netbroker.py).

Usage::

    python -m oryx_tpu.cli batch --conf myapp.conf
    python -m oryx_tpu.cli broker --port 2181 --dir /var/oryx/topics
    python -m oryx_tpu.cli topic-tail --conf myapp.conf --which update
    echo "a b c" | python -m oryx_tpu.cli topic-input --conf myapp.conf
"""

from __future__ import annotations

import argparse
import logging
import os
import signal
import sys

from oryx_tpu.common import config as cfg
from oryx_tpu.common.lockutils import close_at_shutdown
from oryx_tpu.transport import topic as tp

log = logging.getLogger(__name__)


def _load_config(path: "str | None"):
    if path:
        return cfg.Config.parse_file(path).overlay_on(cfg.get_default())
    return cfg.get_default()


def _run_layer(layer_cls_path: str, config) -> int:
    """Main.java pattern: construct, close-at-shutdown, start, await."""
    from oryx_tpu.parallel.distributed import initialize_from_config

    initialize_from_config(config)
    module_name, cls_name = layer_cls_path.rsplit(".", 1)
    import importlib

    layer_cls = getattr(importlib.import_module(module_name), cls_name)
    log.info("config:\n%s", config.pretty_print())
    # the exit handler installs BEFORE the layer constructs: layer
    # construction runs blackbox.configure, which (with a dump-dir set)
    # CHAINS a flight-recorder dump in front of whatever SIGTERM handler
    # exists — installing ours afterwards would silently drop the dump
    signal.signal(signal.SIGTERM, lambda *_: sys.exit(0))
    layer = layer_cls(config)
    close_at_shutdown(layer)
    layer.start()
    try:
        layer.await_termination()
    except KeyboardInterrupt:
        pass
    finally:
        layer.close()
    return 0


def _topics(config) -> dict[str, tuple[str, str]]:
    return {
        "input": (
            config.get_string("oryx.input-topic.broker"),
            config.get_string("oryx.input-topic.message.topic"),
        ),
        "update": (
            config.get_string("oryx.update-topic.broker"),
            config.get_string("oryx.update-topic.message.topic"),
        ),
    }


def cmd_topic_setup(config, args) -> int:
    """Create both topics if absent (oryx-run.sh kafka-setup)."""
    for which, (broker_url, name) in _topics(config).items():
        broker = tp.get_broker(broker_url)
        if broker.topic_exists(name):
            print(f"{which}: topic {name} exists")
        else:
            broker.create_topic(name)
            print(f"{which}: created topic {name}")
    return 0


def cmd_topic_tail(config, args) -> int:
    """Stream a topic's messages to stdout (oryx-run.sh kafka-tail).
    ``--max-messages N`` exits after N messages instead of tailing forever
    (scriptable inspection; the tcp smoke tests ride this)."""
    remaining = args.max_messages
    if remaining is not None and remaining <= 0:
        return 0  # nothing asked for: exit before the blocking iterator
    broker_url, name = _topics(config)[args.which]
    broker = tp.get_broker(broker_url)
    it = tp.ConsumeDataIterator(broker, name, "earliest")
    try:
        for km in it:
            print(f"{km.key}\t{km.message}", flush=True)
            if remaining is not None:
                remaining -= 1
                if remaining <= 0:
                    break
    except KeyboardInterrupt:
        pass
    finally:
        it.close()
    return 0


def cmd_broker(argv: "list[str]") -> int:
    """Run the ``tcp:`` network broker server (transport/netbroker.py): one
    process owns ``--dir`` durably (wrapping the file broker locally — the
    single-writer design that retires the shared-FS constraint) and serves
    it to any number of hosts on ``--port``. Foreground; SIGTERM/SIGINT
    stop it cleanly. Runbook: docs/admin.md "Broker selection"."""
    import threading

    parser = argparse.ArgumentParser(
        prog="oryx-run broker", description="Oryx TCP broker server"
    )
    parser.add_argument("--port", type=int, required=True,
                        help="TCP port to listen on (0 = ephemeral)")
    parser.add_argument("--dir", required=True,
                        help="topic storage directory this server owns")
    parser.add_argument("--host", default=None,
                        help="bind host (default: oryx.broker.tcp.server.host)")
    parser.add_argument("--group-ttl-sec", type=float, default=None,
                        help="consumer-group heartbeat TTL (default 30)")
    parser.add_argument("--conf", help="HOCON config file overlaid on defaults")
    parser.add_argument("--verbose", action="store_true")
    args = parser.parse_args(argv)
    logging.basicConfig(
        level=logging.DEBUG if args.verbose else logging.INFO,
        format="%(asctime)s %(levelname)s %(name)s: %(message)s",
    )
    config = _load_config(args.conf)
    from oryx_tpu.transport import netbroker

    netbroker.configure(config)
    # the server's inner FileBroker honors oryx.broker.file.* (fsync
    # durability policy, torn-tail recovery) exactly like a local file:
    tp.configure(config)
    server_cfg = config.get_config("oryx.broker.tcp.server")
    host = args.host or server_cfg.get_string("host", "0.0.0.0")
    stats_interval = server_cfg.get_float("stats-interval-sec", 60.0)
    server = netbroker.NetBrokerServer(
        args.dir, host=host, port=args.port,
        group_ttl_sec=args.group_ttl_sec,
        stats_interval_sec=stats_interval,
    )
    server.start_background()
    print(f"broker listening on {host}:{server.port} dir={args.dir}", flush=True)
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    try:
        while not stop.wait(3600):
            pass
    except KeyboardInterrupt:
        pass
    finally:
        server.close()
    return 0


def cmd_fleet_status(argv: "list[str]") -> int:
    """Fleet-wide observability console (common/federation.py): scrape N
    replicas' ``/metrics`` + ``/readyz`` + ``/trace`` +
    ``/metrics/history``, merge them soundly (counters sum, histograms add
    bucket-wise, gauges keep per-replica labels, down replicas report
    down), and render an operator table, a merged Prometheus ``fleet``
    exposition, or JSON. Rate columns prefer a replica's own server-side
    series from ``/metrics/history`` (with qps/freshness sparkline
    columns); ``--watch`` re-scrapes on an interval and keeps client-side
    delta derivation as the fallback for pre-history replicas in a mixed
    fleet. Replica list from ``--replicas`` (comma-separated, repeatable)
    or ``oryx.fleet.replicas``. Runbook: docs/slo.md."""
    parser = argparse.ArgumentParser(
        prog="oryx-run fleet-status",
        description="Oryx fleet observability console",
    )
    parser.add_argument(
        "--replicas", action="append", default=[],
        help="comma-separated replica targets (host:port or http URLs); "
             "repeatable; default: oryx.fleet.replicas",
    )
    parser.add_argument("--conf", help="HOCON config file overlaid on defaults")
    parser.add_argument(
        "--watch", type=float, default=0.0, metavar="SEC",
        help="re-scrape every SEC seconds (rate columns prefer server-side "
             "/metrics/history series, else scrape deltas); 0 = one shot",
    )
    parser.add_argument(
        "--format", choices=["table", "prom", "json"], default="table",
        help="table (operator view), prom (merged fleet exposition), json",
    )
    parser.add_argument("--timeout", type=float, default=None,
                        help="per-replica scrape budget (default: "
                             "oryx.fleet.scrape-timeout-sec)")
    args = parser.parse_args(argv)
    config = _load_config(args.conf)
    replicas = [
        entry.strip()
        for chunk in args.replicas for entry in chunk.split(",")
        if entry.strip()
    ]
    if not replicas:
        replicas = [str(r) for r in config.get_list("oryx.fleet.replicas", [])]
    if not replicas:
        print("fleet-status: no replicas (pass --replicas or set "
              "oryx.fleet.replicas)", file=sys.stderr)
        return 2
    timeout = args.timeout if args.timeout is not None else config.get_float(
        "oryx.fleet.scrape-timeout-sec", 5.0
    )
    from oryx_tpu.common import federation

    prev = None
    try:
        while True:
            snap = federation.scrape_fleet(replicas, timeout=timeout)
            if args.format == "prom":
                print(federation.render_prom(snap), end="")
            elif args.format == "json":
                import json as _json

                print(_json.dumps(federation.to_json(snap, prev)))
            else:
                rows = federation.table_rows(snap, prev)
                print(federation.render_table(rows), end="", flush=True)
            if args.watch <= 0:
                return 0
            prev = snap
            import time as _time

            _time.sleep(args.watch)
            print()
    except KeyboardInterrupt:
        return 0


def cmd_topic_input(config, args) -> int:
    """Feed stdin lines to the input topic (oryx-run.sh kafka-input)."""
    broker_url, name = _topics(config)["input"]
    producer = tp.TopicProducerImpl(broker_url, name)
    n = 0
    for line in sys.stdin:
        line = line.rstrip("\n")
        if line:
            producer.send(None, line)
            n += 1
    producer.close()
    print(f"sent {n} messages to {name}", file=sys.stderr)
    return 0


def main(argv: "list[str] | None" = None) -> int:
    args_in = sys.argv[1:] if argv is None else list(argv)
    if args_in and args_in[0] == "analyze":
        # static analysis has its own option surface (--format/--baseline/...)
        # and must not import jax; delegate before the layer parser runs
        from oryx_tpu.tools.analyze.cli import main as analyze_main

        return analyze_main(args_in[1:])
    if args_in and args_in[0] == "broker":
        # the tcp broker server is a pure-transport process: its own option
        # surface (--port/--dir/...), and it must never pay a jax import
        return cmd_broker(args_in[1:])
    if args_in and args_in[0] == "fleet-status":
        # the fleet aggregator is a pure-HTTP observer: its own option
        # surface (--replicas/--watch/--format), never a jax import
        return cmd_fleet_status(args_in[1:])
    parser = argparse.ArgumentParser(
        prog="oryx-run", description="Oryx TPU runner (oryx-run.sh equivalent)"
    )
    parser.add_argument("command", choices=[
        "batch", "speed", "serving", "topic-setup", "topic-tail", "topic-input",
        "config-dump",
    ])
    parser.add_argument("--conf", help="HOCON config file overlaid on defaults")
    parser.add_argument(
        "--which", choices=["input", "update"], default="update",
        help="which topic for topic-tail",
    )
    parser.add_argument(
        "--max-messages", type=int, default=None,
        help="topic-tail: exit after this many messages (default: tail forever)",
    )
    parser.add_argument("--verbose", action="store_true")
    args = parser.parse_args(argv)
    logging.basicConfig(
        level=logging.DEBUG if args.verbose else logging.INFO,
        format="%(asctime)s %(levelname)s %(name)s: %(message)s",
    )
    # honor JAX_PLATFORMS even when a site hook pre-imported jax and set the
    # platform list programmatically (env alone is ignored in that case)
    env_platforms = os.environ.get("JAX_PLATFORMS")
    if env_platforms:
        import jax

        jax.config.update("jax_platforms", env_platforms)
    config = _load_config(args.conf)
    # the topic tools talk to brokers directly (no layer construction runs
    # configure for them): adopt oryx.broker.tcp.* before any get_broker
    from oryx_tpu.transport import netbroker

    netbroker.configure(config)
    tp.configure(config)
    if args.command == "batch":
        return _run_layer("oryx_tpu.lambda_rt.batch.BatchLayer", config)
    if args.command == "speed":
        return _run_layer("oryx_tpu.lambda_rt.speed.SpeedLayer", config)
    if args.command == "serving":
        return _run_layer("oryx_tpu.serving.app.ServingLayer", config)
    if args.command == "topic-setup":
        return cmd_topic_setup(config, args)
    if args.command == "topic-tail":
        return cmd_topic_tail(config, args)
    if args.command == "config-dump":
        # resolved config as key=value properties (ConfigToProperties,
        # settings/ConfigToProperties.java:60 / oryx-run.sh:88)
        for key, value in sorted(config.to_properties().items()):
            print(f"{key}={value}")
        return 0
    return cmd_topic_input(config, args)


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
