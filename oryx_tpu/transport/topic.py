"""Topic transport: the framework's data plane.

TPU-native replacement for the reference's Kafka/ZooKeeper messaging layer
(framework/kafka-util/.../KafkaUtils.java:63-188 and
ConsumeDataIterator.java:30-77). Two backends behind one URL scheme:

  * ``memory:`` — in-process broker (a process-wide registry of append-only
    logs with condition-variable wakeup). The default for tests and
    single-process deployments, standing in for the reference ITs'
    LocalKafkaBroker.
  * ``file:<dir>`` — durable broker: each topic is an append-only JSONL log
    on disk, readable by other processes on the same filesystem; offsets are
    line indices. This is the host-side pub-sub that rides shared storage —
    cross-host deployments point it at a network filesystem (DCN transport),
    while device-side collectives stay inside pjit programs.

Semantics kept from the reference:
  * topics are append-only logs; consumers track offsets; layers persist
    consumed positions through the broker's OffsetStore *after* processing
    each batch (UpdateOffsetsFn semantics — see AbstractLayer), keyed by
    ``oryx.id``;
  * consuming from ``earliest`` replays the whole log (how speed/serving
    rebuild model state, SpeedLayer.java:108-110);
  * a blocking consume iterator with exponential poll backoff 1→1000 ms and
    wakeup-based close (ConsumeDataIterator.java:30-77);
  * producers enforce a transport-level max message size (Kafka
    max.request.size = 1<<26); topics support prefix truncation in lieu of
    Kafka retention.

FileBroker writes each record as one flock-guarded O_APPEND write (atomic
between cooperating local processes; NFS append atomicity is not guaranteed —
use one writer per topic there). Records use a **versioned framing** — magic
+ length prefix + CRC32 ahead of the JSON payload — so truncation and
bit-flips are detected, not silently consumed; legacy bare-JSON logs read
back-compatibly. Durability is policy-driven (``oryx.broker.file.fsync`` =
``never``/``interval``/``always``), and the first touch of each partition
runs **torn-tail recovery**: a trailing partial record (a writer killed
mid-append, or a crash under a lazy fsync policy) is scanned, truncated,
and counted (``oryx_broker_torn_tail_records_total``) before any new
append can splice into it. The ``tcp:`` netbroker wraps FileBroker as its
single writer, so it inherits all of this for free.
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
import uuid
import zlib
from pathlib import Path
from typing import Iterator

try:
    import fcntl
except ImportError:  # pragma: no cover — non-posix fallback (no flock)
    fcntl = None

from oryx_tpu.api.keymessage import KeyMessage
from oryx_tpu.common import blackbox
from oryx_tpu.common import faults
from oryx_tpu.common import ioutils
from oryx_tpu.common import metrics as metrics_mod
from oryx_tpu.common import resilience
from oryx_tpu.common import spans

log = spans.get_logger(__name__)

_PRODUCED = metrics_mod.default_registry().counter(
    "oryx_topic_produced_total",
    "Messages produced to a topic",
    ("topic",),
)
_SEND_FAILURES = metrics_mod.default_registry().counter(
    "oryx_topic_send_failures_total",
    "Producer sends that raised (oversize or broker append failure)",
    ("topic",),
)
_CONSUMED = metrics_mod.default_registry().counter(
    "oryx_topic_consumed_total",
    "Messages handed to consumers from a topic",
    ("topic",),
)
_FSYNCS = metrics_mod.default_registry().counter(
    "oryx_broker_fsyncs_total",
    "Log fsyncs issued by the file broker (oryx.broker.file.fsync policy)",
)
_TORN_TAIL = metrics_mod.default_registry().counter(
    "oryx_broker_torn_tail_records_total",
    "Partial trailing records truncated by open-time log recovery",
    ("topic",),
)
# same family the microbatch pump counts into (idempotent re-registration);
# the consumer iterator counts skipped corrupt records under tier="transport"
_CORRUPT_CONSUMED = metrics_mod.default_registry().counter(
    "oryx_corrupt_records_total",
    "Corrupt input-topic records dropped by the microbatch pump",
    ("tier",),
)


def configure(config) -> None:
    """Adopt ``oryx.broker.file.*`` process-wide (the resilience idiom:
    layers, the serving app, and the broker CLI all call this, so the fsync
    policy applies to every FileBroker instance — including the one inside
    a ``tcp:`` netbroker server — without per-instance plumbing)."""
    global _fsync_policy, _fsync_interval_sec
    policy = config.get_string("oryx.broker.file.fsync", "never")
    if policy not in ("never", "interval", "always"):
        raise TopicException(
            f"oryx.broker.file.fsync must be never/interval/always, "
            f"got {policy!r}"
        )
    interval_ms = config.get_float("oryx.broker.file.fsync-interval-ms", 100.0)
    _fsync_interval_sec = max(0.0, interval_ms) / 1000.0
    _fsync_policy = policy


#: process-wide fsync policy for FileBroker appends (see configure);
#: plain module globals written under the GIL, read per append
_fsync_policy = "never"
_fsync_interval_sec = 0.1


def _flock(fd: int, op: int) -> None:
    if fcntl is not None:
        fcntl.flock(fd, op)


class TopicException(Exception):
    """Transport-level failure. ``transient=True`` marks conditions a retry
    can reasonably outlast (broker briefly unreachable); the default False
    covers the permanent ones (topic missing, oversized message)."""

    def __init__(self, *args, transient: bool = False):
        super().__init__(*args)
        self.transient = transient


def transient_transport_error(exc: BaseException) -> bool:
    """The transport retry predicate: I/O errors (shared-FS hiccups under
    the ``file:`` broker, injected faults) and explicitly-transient
    TopicExceptions. Missing topics and oversize sends stay fatal."""
    if isinstance(exc, TopicException):
        return exc.transient
    return isinstance(exc, OSError)


def offset_op(fn, stop: "threading.Event | None" = None):
    """One offset-store read/write under the transport retry contract:
    fault site ``broker.offset``, transient failures retried by the process
    policy. THE shared commit-path wrapper — the lambda tiers, the serving
    layer's committed-resume loop, and the consumer's stored-offset lookup
    all ride this one definition, so the retry contract cannot silently
    diverge between tiers."""

    def _do():
        faults.maybe_fail("broker.offset")
        return fn()

    return resilience.default_policy().call(
        "broker.offset", _do, retryable=transient_transport_error, stop=stop,
    )


#: Seconds after which a consumer-group member with no heartbeat is dropped
#: from partition assignment (Kafka session.timeout.ms equivalent).
GROUP_MEMBER_TTL_SEC = 30.0


def partition_for_key(key, n_partitions: int, fallback: int = 0) -> int:
    """Stable key→partition routing (Kafka's hash-partitioner equivalent):
    same key always lands on the same partition, so per-key ordering holds.
    ``fallback`` routes None keys (callers pass a round-robin counter)."""
    if n_partitions <= 1:
        return 0
    if key is None:
        return fallback % n_partitions
    return zlib.crc32(str(key).encode("utf-8")) % n_partitions


def partitions_for_member(member_id: str, members: list[str], n_partitions: int) -> list[int]:
    """Deterministic round-robin partition assignment over the sorted live
    membership (the stand-in for Kafka's group rebalance protocol)."""
    if not members or member_id not in members:
        return []
    rank = sorted(members).index(member_id)
    return [p for p in range(n_partitions) if p % len(members) == rank]


#: Placeholder returned for a corrupt log record so offsets stay aligned;
#: ConsumeDataIterator filters it out by identity.
CORRUPT_RECORD = KeyMessage(None, None)


# ---------------------------------------------------------------------------
# FileBroker record framing (version 1)
# ---------------------------------------------------------------------------

#: v1 frame: ``O1 <payload_len> <crc32:08x> <json payload>\n``. The length
#: prefix catches truncation/splices, the CRC catches bit-flips, and the
#: line stays newline-terminated so the byte index and offset model are
#: unchanged. Legacy logs (bare ``{...}`` JSON lines) read back-compatibly.
_FRAME_MAGIC = b"O1 "


def frame_record(payload: bytes) -> bytes:
    """One framed, newline-terminated log line for a JSON payload."""
    return b"O1 %d %08x " % (len(payload), zlib.crc32(payload)) + payload + b"\n"


def decode_record(raw: bytes, topic: str = "?") -> KeyMessage:
    """One log line (no trailing newline) → KeyMessage, or CORRUPT_RECORD.

    v1 frames are validated (length prefix AND CRC32) before the JSON is
    trusted; bare ``{`` lines take the legacy path. Anything else — torn
    splices, flipped bits, foreign garbage — maps to CORRUPT_RECORD so
    offsets stay aligned and consumers skip exactly the bad record."""
    payload = raw
    if raw.startswith(_FRAME_MAGIC):
        parts = raw.split(b" ", 3)
        if len(parts) != 4:
            log.warning("corrupt framed record in topic %s (bad header)", topic)
            return CORRUPT_RECORD
        _, len_s, crc_s, payload = parts
        try:
            want_len, want_crc = int(len_s), int(crc_s, 16)
        except ValueError:
            log.warning("corrupt framed record in topic %s (bad header)", topic)
            return CORRUPT_RECORD
        if len(payload) != want_len or zlib.crc32(payload) != want_crc:
            log.warning(
                "corrupt framed record in topic %s (CRC/length mismatch)",
                topic,
            )
            return CORRUPT_RECORD
    try:
        d = json.loads(payload)
        return KeyMessage(d["k"], d["m"], d.get("h"))
    except (json.JSONDecodeError, KeyError, UnicodeDecodeError, TypeError):
        log.warning("skipping corrupt record in topic %s", topic)
        return CORRUPT_RECORD


# ---------------------------------------------------------------------------
# Broker interface + registry
# ---------------------------------------------------------------------------


class Broker:
    """create/delete/exists + partitioned log access for one transport
    endpoint (KafkaUtils equivalent). Topics are sets of append-only partition
    logs; producers route by key hash (partition_for_key), consumers read
    per-partition offsets. Single-partition topics (the default) behave as one
    plain log."""

    def create_topic(self, name: str, partitions: int = 1) -> None:
        raise NotImplementedError

    def delete_topic(self, name: str) -> None:
        raise NotImplementedError

    def topic_exists(self, name: str) -> bool:
        raise NotImplementedError

    def num_partitions(self, name: str) -> int:
        raise NotImplementedError

    def append(self, topic: str, key, message, headers: "dict | None" = None,
               token: "str | None" = None) -> None:
        """Route by key hash to a partition and append (None key round-robins).
        ``headers`` is transport metadata delivered back on the KeyMessage
        (trace context rides here, never inside the payload). ``token`` is an
        optional idempotence token: retry wrappers pass ONE token per logical
        send, and a broker MAY dedup repeated appends bearing it (the tcp
        broker does — a retry after a lost response must not double-append).
        In-process/file brokers ignore it: their 'failed' appends never
        applied, so retries are naturally safe."""
        raise NotImplementedError

    def read(
        self, topic: str, offset: int, max_items: int = 1024, partition: int = 0
    ) -> list[KeyMessage]:
        raise NotImplementedError

    def size(self, topic: str, partition: int = 0) -> int:
        """Latest offset of one partition (messages ever appended to it)."""
        raise NotImplementedError

    def total_size(self, topic: str) -> int:
        """Sum of all partition sizes (poll-wakeup bookkeeping)."""
        return sum(self.size(topic, p) for p in range(self.num_partitions(topic)))

    def truncate(self, topic: str, before_offset: int, partition: int = 0) -> None:
        """Drop messages below the given offset (retention stand-in). Offsets
        are stable: reads below the new base return nothing."""
        raise NotImplementedError

    def wait_for_data(self, topic: str, seen_total: int, timeout: float, stop=None) -> None:
        """Block until the topic's total size may exceed ``seen_total``,
        timeout elapses, or ``stop`` (a threading.Event) is set."""
        if stop is not None:
            stop.wait(timeout)
        else:
            time.sleep(timeout)

    def wake(self, topic: str) -> None:
        """Wake blocked wait_for_data callers (consumer.wakeup())."""

    # offset store (ZK-equivalent control plane, KafkaUtils.java:120-188)
    def get_offset(self, group: str, topic: str, partition: int = 0) -> int | None:
        raise NotImplementedError

    def set_offset(self, group: str, topic: str, offset: int, partition: int = 0) -> None:
        raise NotImplementedError

    # consumer groups (partition fan-out across cooperating consumers,
    # KafkaUtils.java:63-107 / Kafka group membership equivalent)
    def join_group(self, group: str, topic: str, member_id: str) -> None:
        """Register/heartbeat a member; call at least every GROUP_MEMBER_TTL_SEC."""
        raise NotImplementedError

    def leave_group(self, group: str, topic: str, member_id: str) -> None:
        raise NotImplementedError

    def group_members(self, group: str, topic: str) -> list[str]:
        """Live (heartbeat within TTL) member ids, sorted."""
        raise NotImplementedError


_memory_brokers: dict[str, "MemoryBroker"] = {}
_memory_lock = threading.Lock()
_tcp_clients: dict[str, Broker] = {}
_tcp_lock = threading.Lock()


def get_broker(url: str) -> Broker:
    """Resolve a broker from a config URL: ``memory:[name]`` (in-process),
    ``file:<dir>`` (shared-filesystem durable log), or ``tcp://host:port``
    (network broker server — transport/netbroker.py; docs/admin.md has the
    selection guide)."""
    if url.startswith("memory:"):
        name = url[len("memory:"):] or "default"
        with _memory_lock:
            b = _memory_brokers.get(name)
            if b is None:
                b = _memory_brokers[name] = MemoryBroker()
            return b
    if url.startswith("tcp://"):
        # one shared client per URL: threads each get their own socket
        # inside it, and every producer/consumer in the process reuses the
        # same connection pool instead of minting new ones per component
        from oryx_tpu.transport import netbroker

        with _tcp_lock:
            c = _tcp_clients.get(url)
            if c is None:
                c = _tcp_clients[url] = netbroker.client_from_url(url)
            return c
    if url.startswith("file:"):
        return FileBroker(url[len("file:"):])
    raise TopicException(f"unknown broker url: {url}")


def reset_memory_brokers() -> None:
    """Drop all in-process brokers (test isolation)."""
    with _memory_lock:
        _memory_brokers.clear()


def reset_tcp_clients() -> None:
    """Drop cached tcp clients (test isolation across server restarts)."""
    with _tcp_lock:
        _tcp_clients.clear()


class _MemoryPartition:
    __slots__ = ("log", "base")

    def __init__(self):
        self.log: list[KeyMessage] = []
        self.base = 0  # offset of log[0]; advances on truncate


class _MemoryTopic:
    __slots__ = ("partitions", "cond", "rr")

    def __init__(self, n_partitions: int):
        self.partitions = [_MemoryPartition() for _ in range(n_partitions)]
        self.cond = threading.Condition()  # one condition per topic
        self.rr = itertools.count()  # round-robin for None keys


class MemoryBroker(Broker):
    def __init__(self):
        self._topics: dict[str, _MemoryTopic] = {}
        self._offsets: dict[tuple[str, str, int], int] = {}
        self._groups: dict[tuple[str, str], dict[str, float]] = {}
        self._lock = threading.Lock()

    def _topic(self, name: str) -> _MemoryTopic:
        with self._lock:
            t = self._topics.get(name)
            if t is None:
                raise TopicException(f"topic does not exist: {name}")
            return t

    def _partition(self, name: str, partition: int) -> "tuple[_MemoryTopic, _MemoryPartition]":
        """Topic + bounds-checked partition. Every partitioned accessor
        routes through here so an out-of-range partition raises a TYPED
        TopicException, never a bare IndexError — the tcp server maps these
        onto the wire as typed errors, not stack traces."""
        t = self._topic(name)
        if not 0 <= partition < len(t.partitions):
            raise TopicException(f"no partition {partition} in topic {name}")
        return t, t.partitions[partition]

    def create_topic(self, name: str, partitions: int = 1) -> None:
        with self._lock:
            self._topics.setdefault(name, _MemoryTopic(max(1, partitions)))

    def delete_topic(self, name: str) -> None:
        with self._lock:
            self._topics.pop(name, None)

    def topic_exists(self, name: str) -> bool:
        with self._lock:
            return name in self._topics

    def num_partitions(self, name: str) -> int:
        return len(self._topic(name).partitions)

    def append(self, topic: str, key, message, headers: "dict | None" = None,
               token: "str | None" = None) -> None:
        t = self._topic(topic)
        with t.cond:
            p = partition_for_key(key, len(t.partitions), next(t.rr))
            t.partitions[p].log.append(KeyMessage(key, message, headers))
            t.cond.notify_all()

    def read(
        self, topic: str, offset: int, max_items: int = 1024, partition: int = 0
    ) -> list[KeyMessage]:
        t, part = self._partition(topic, partition)
        with t.cond:
            lo = max(offset - part.base, 0)
            return part.log[lo:lo + max_items]

    def size(self, topic: str, partition: int = 0) -> int:
        t, part = self._partition(topic, partition)
        with t.cond:
            return part.base + len(part.log)

    def total_size(self, topic: str) -> int:
        t = self._topic(topic)
        with t.cond:
            return sum(p.base + len(p.log) for p in t.partitions)

    def truncate(self, topic: str, before_offset: int, partition: int = 0) -> None:
        t, part = self._partition(topic, partition)
        with t.cond:
            drop = min(max(before_offset - part.base, 0), len(part.log))
            if drop:
                del part.log[:drop]
                part.base += drop

    def wait_for_data(self, topic: str, seen_total: int, timeout: float, stop=None) -> None:
        t = self._topic(topic)
        with t.cond:
            total = sum(p.base + len(p.log) for p in t.partitions)
            if total <= seen_total and not (stop is not None and stop.is_set()):
                t.cond.wait(timeout)

    def wake(self, topic: str) -> None:
        try:
            t = self._topic(topic)
        except TopicException:
            return
        with t.cond:
            t.cond.notify_all()

    def get_offset(self, group: str, topic: str, partition: int = 0) -> int | None:
        with self._lock:
            return self._offsets.get((group, topic, partition))

    def set_offset(self, group: str, topic: str, offset: int, partition: int = 0) -> None:
        with self._lock:
            self._offsets[(group, topic, partition)] = offset

    def join_group(self, group: str, topic: str, member_id: str) -> None:
        with self._lock:
            self._groups.setdefault((group, topic), {})[member_id] = time.monotonic()

    def leave_group(self, group: str, topic: str, member_id: str) -> None:
        with self._lock:
            self._groups.get((group, topic), {}).pop(member_id, None)

    def group_members(self, group: str, topic: str) -> list[str]:
        now = time.monotonic()
        with self._lock:
            members = self._groups.get((group, topic), {})
            return sorted(
                m for m, hb in members.items() if now - hb < GROUP_MEMBER_TTL_SEC
            )


class FileBroker(Broker):
    """Append-only framed-record logs (one per partition) under a directory.

    Appends are flock-guarded O_APPEND writes of v1-framed lines (magic +
    length prefix + CRC32 + JSON; legacy bare-JSON lines read
    back-compatibly), with durability set by ``oryx.broker.file.fsync``.
    Reads keep a per-partition byte index that extends incrementally, so
    polling cost is O(new bytes), not O(log size). The first touch of a
    partition runs torn-tail recovery (truncate + count a trailing partial
    record); an in-flight writer's partial line is protected by the append
    flock and simply left for the next read; corrupt interior lines map to
    CORRUPT_RECORD with offsets aligned. Consumer-group membership rides
    heartbeat files (.groups/) with an mtime TTL, so cooperating processes
    see each other without a coordinator.
    """

    def __init__(self, root: str):
        self._root = Path(root)
        ioutils.mkdirs(self._root)
        self._lock = threading.Lock()
        # (topic, partition) -> line-start byte offsets incl. next-append pos
        self._index: dict[tuple[str, int], list[int]] = {}
        self._rr = itertools.count()  # per-process round-robin for None keys
        # partitions whose tail this instance already recovered (first
        # touch runs torn-tail truncation once; later partials belong to
        # live flock-holding writers and are left alone). Values are
        # completion events: a second thread racing the first touch WAITS
        # for recovery instead of appending past a still-torn tail (its
        # record would splice onto the partial and read back corrupt).
        self._recovered: dict[tuple[str, int], threading.Event] = {}
        # (topic, partition) -> monotonic time of the last fsync (the
        # "interval" policy's due-date bookkeeping)
        self._fsync_last: dict[tuple[str, int], float] = {}

    def _log_path(self, name: str, partition: int = 0) -> Path:
        return self._root / name / f"{partition:05d}.jsonl"

    def create_topic(self, name: str, partitions: int = 1) -> None:
        d = self._root / name
        ioutils.mkdirs(d)
        for p in range(max(1, partitions)):
            self._log_path(name, p).touch(exist_ok=True)

    def delete_topic(self, name: str) -> None:
        ioutils.delete_recursively(self._root / name)
        with self._lock:
            for key in [k for k in self._index if k[0] == name]:
                del self._index[key]
            for key in [k for k in self._recovered if k[0] == name]:
                del self._recovered[key]

    def topic_exists(self, name: str) -> bool:
        return self._log_path(name, 0).exists()

    def num_partitions(self, name: str) -> int:
        d = self._root / name
        if not d.is_dir():
            raise TopicException(f"topic does not exist: {name}")
        return max(1, len(list(d.glob("[0-9]*.jsonl"))))

    def append(self, topic: str, key, message, headers: "dict | None" = None,
               token: "str | None" = None) -> None:
        if isinstance(message, (bytes, bytearray)):
            # the JSONL record format carries str payloads only; fail TYPED
            # (and permanent) instead of leaking json.dumps's TypeError —
            # memory: accepts bytes, but anything durable/wire must not
            raise TopicException(
                "bytes messages are not supported by the file:/tcp: "
                "brokers (JSON record format); encode to str first"
            )
        n_parts = self.num_partitions(topic)
        part = partition_for_key(key, n_parts, next(self._rr))
        p = self._log_path(topic, part)
        if not p.exists():
            raise TopicException(f"topic does not exist: {topic}")
        self._ensure_recovered(topic, part, p)
        record = {"k": key, "m": message}
        if headers:
            record["h"] = headers
        data = frame_record(
            json.dumps(record, separators=(",", ":")).encode("utf-8")
        )
        fd = os.open(p, os.O_WRONLY | os.O_APPEND)
        try:
            # the whole record writes under an exclusive flock: a short-write
            # loop can no longer interleave with another process's append,
            # and open-time recovery (which also takes the lock) can never
            # truncate a LIVE writer's half-written record
            _flock(fd, fcntl.LOCK_EX if fcntl else 0)
            written = os.write(fd, data)
            while written < len(data):
                written += os.write(fd, data[written:])
            self._maybe_fsync(fd, topic, part)
        finally:
            if fcntl is not None:
                _flock(fd, fcntl.LOCK_UN)
            os.close(fd)

    def _maybe_fsync(self, fd: int, topic: str, part: int) -> None:
        """Apply the configured durability policy after one append. An
        fsync failure (disk error, injected ``broker.fsync`` fault) costs
        durability for that window, never availability: the append already
        landed in the page cache, so raising here would make the producer's
        retry DOUBLE-append a record that was never lost."""
        policy = _fsync_policy
        if policy == "never":
            return
        if policy == "interval":
            now = time.monotonic()
            with self._lock:
                last = self._fsync_last.get((topic, part), 0.0)
                if now - last < _fsync_interval_sec:
                    return
                self._fsync_last[(topic, part)] = now
        try:
            faults.maybe_fail("broker.fsync")
            os.fsync(fd)
        except OSError:
            log.warning(
                "log fsync failed for %s/%d (durability degraded for this "
                "window; append already applied)", topic, part, exc_info=True,
            )
            return
        _FSYNCS.inc()

    # -- torn-tail recovery ---------------------------------------------------
    def _ensure_recovered(self, topic: str, part: int, p: Path) -> None:
        key = (topic, part)
        with self._lock:
            done = self._recovered.get(key)
            if done is None:
                done = self._recovered[key] = threading.Event()
                owner = True
            else:
                owner = False
        if owner:
            try:
                self._recover_tail(topic, part, p)
            finally:
                done.set()
        else:
            # block until the owner truncated the tail: appending before
            # that would splice a good record onto the torn partial
            done.wait()

    def _recover_tail(self, topic: str, part: int, p: Path) -> None:
        """Open-time crash recovery: scan the log tail and truncate a
        trailing PARTIAL record (no terminating newline — a writer killed
        mid-append, or a post-crash torn page under a lazy fsync policy),
        counting what it dropped. Complete-but-corrupt interior records are
        deliberately NOT touched here: they surface as CORRUPT_RECORD with
        offsets aligned, so a mid-log bit-flip never costs the records
        after it. Runs under the append flock, so an in-flight writer's
        unfinished record is invisible to it."""
        try:
            fd = os.open(p, os.O_RDWR)
        except FileNotFoundError:
            return
        try:
            _flock(fd, fcntl.LOCK_EX if fcntl else 0)
            size = os.lseek(fd, 0, os.SEEK_END)
            if size == 0:
                return
            # scan backwards for the last newline (chunked: a partial
            # record can be as large as the max message size)
            pos, last_nl, chunk = size, -1, 1 << 16
            while pos > 0 and last_nl < 0:
                lo = max(0, pos - chunk)
                os.lseek(fd, lo, os.SEEK_SET)
                buf = os.read(fd, pos - lo)
                nl = buf.rfind(b"\n")
                if nl >= 0:
                    last_nl = lo + nl
                pos = lo
            cut = last_nl + 1  # 0 when the whole file is one partial record
            if cut == size:
                return  # clean, newline-terminated tail
            os.ftruncate(fd, cut)
            os.fsync(fd)
            _TORN_TAIL.labels(topic).inc()
            blackbox.record_event(
                "broker.torn_tail", severity="warning",
                topic=topic, partition=part, truncated_bytes=size - cut,
            )
            log.warning(
                "torn-tail recovery on %s/%d: truncated %d byte(s) of "
                "partial trailing record", topic, part, size - cut,
            )
        except OSError:
            log.warning(
                "torn-tail recovery failed on %s/%d (reads still stop "
                "before the partial tail)", topic, part, exc_info=True,
            )
        finally:
            if fcntl is not None:
                _flock(fd, fcntl.LOCK_UN)
            os.close(fd)

    def _refresh_index(self, topic: str, partition: int = 0) -> list[int]:
        """Extend the line index over bytes appended since the last call."""
        p = self._log_path(topic, partition)
        if not p.exists():
            raise TopicException(f"topic/partition does not exist: {topic}/{partition}")
        self._ensure_recovered(topic, partition, p)
        with self._lock:
            idx = self._index.setdefault((topic, partition), [0])
            scanned = idx[-1]
            file_size = p.stat().st_size
            if file_size <= scanned:
                return idx
            with open(p, "rb") as f:
                f.seek(scanned)
                data = f.read(file_size - scanned)
            pos = 0
            while True:
                nl = data.find(b"\n", pos)
                if nl == -1:
                    break  # partial trailing line stays unindexed
                idx.append(scanned + nl + 1)
                pos = nl + 1
            return idx

    def read(
        self, topic: str, offset: int, max_items: int = 1024, partition: int = 0
    ) -> list[KeyMessage]:
        idx = self._refresh_index(topic, partition)
        n = len(idx) - 1  # complete lines
        if offset >= n:
            return []
        end = min(offset + max_items, n)
        p = self._log_path(topic, partition)
        out: list[KeyMessage] = []
        with open(p, "rb") as f:
            f.seek(idx[offset])
            blob = f.read(idx[end] - idx[offset])
        lines = blob.split(b"\n")
        if lines and not lines[-1]:
            lines.pop()  # trailing newline artifact only; blank interior
            # lines must still produce CORRUPT_RECORD to keep offsets aligned
        for raw in lines:
            if not raw.strip():
                out.append(CORRUPT_RECORD)
                continue
            out.append(decode_record(raw, topic))  # keeps offsets aligned
        return out[: end - offset]

    def size(self, topic: str, partition: int = 0) -> int:
        return len(self._refresh_index(topic, partition)) - 1

    def truncate(self, topic: str, before_offset: int, partition: int = 0) -> None:
        """Rewrite the partition log without the truncated prefix. Offsets
        shift to 0-based on disk but this broker instance keeps serving stable
        offsets only for fresh reads; cross-process readers should truncate
        during quiet periods (retention maintenance)."""
        idx = self._refresh_index(topic, partition)
        n = len(idx) - 1
        cut = min(max(before_offset, 0), n)
        if cut == 0:
            return
        p = self._log_path(topic, partition)
        with open(p, "rb") as f:
            f.seek(idx[cut])
            rest = f.read()
        # atomic rename (unique temp + fsync): a retention pass killed
        # mid-rewrite must never leave a truncated half-log behind
        ioutils.atomic_write_bytes(p, rest)
        with self._lock:
            self._index.pop((topic, partition), None)

    def _offset_path(self, group: str, topic: str, partition: int) -> Path:
        # partition 0 keeps the legacy filename so old deployments resume
        suffix = "" if partition == 0 else f"__p{partition}"
        return self._root / ".offsets" / f"{group}__{topic}{suffix}.json"

    def get_offset(self, group: str, topic: str, partition: int = 0) -> int | None:
        p = self._offset_path(group, topic, partition)
        if not p.exists():
            return None
        return json.loads(p.read_text())["offset"]

    def set_offset(self, group: str, topic: str, offset: int, partition: int = 0) -> None:
        # write-temp + fsync + os.replace (unique temp name): a replica
        # killed mid-commit leaves the old offset intact, never a torn JSON
        # that would corrupt resume positions for the whole group — and two
        # concurrent committers cannot interleave bytes in one temp file
        p = self._offset_path(group, topic, partition)
        ioutils.mkdirs(p.parent)
        ioutils.atomic_write_text(p, json.dumps({"offset": offset}))

    def _group_dir(self, group: str, topic: str) -> Path:
        return self._root / ".groups" / f"{group}__{topic}"

    def join_group(self, group: str, topic: str, member_id: str) -> None:
        d = self._group_dir(group, topic)
        ioutils.mkdirs(d)
        (d / f"{member_id}.hb").touch()

    def leave_group(self, group: str, topic: str, member_id: str) -> None:
        try:
            (self._group_dir(group, topic) / f"{member_id}.hb").unlink()
        except FileNotFoundError:
            pass

    def group_members(self, group: str, topic: str) -> list[str]:
        d = self._group_dir(group, topic)
        if not d.is_dir():
            return []
        now = time.time()
        return sorted(
            p.name[: -len(".hb")]
            for p in d.glob("*.hb")
            if now - p.stat().st_mtime < GROUP_MEMBER_TTL_SEC
        )


# ---------------------------------------------------------------------------
# Producer + consume iterator (TopicProducer / ConsumeDataIterator)
# ---------------------------------------------------------------------------

#: Fixed transport-level message cap (TopicProducerImpl.java sets Kafka
#: max.request.size = 1<<26). The *configured* update-topic max-size only
#: drives MLUpdate's inline-vs-MODEL-REF decision, not producer enforcement.
MAX_REQUEST_SIZE = 1 << 26


class TopicProducerImpl:
    """Producer for one topic (framework/oryx-lambda/.../TopicProducerImpl.java).
    Enforces the transport cap; oversized sends raise, and callers fall back to
    the MODEL-REF by-reference protocol (ml/MLUpdate publish path)."""

    def __init__(self, broker_url: str, topic: str, max_size: int | None = MAX_REQUEST_SIZE):
        self._broker_url = broker_url
        self._topic = topic
        self._max_size = max_size
        self._broker: Broker | None = None  # lazy, like the reference
        # set by close(): aborts an in-flight send's retry backoff sleeps so
        # teardown never waits out the retry budget against a dead broker
        self._closed = threading.Event()

    def get_update_broker(self) -> str:
        return self._broker_url

    def get_topic(self) -> str:
        return self._topic

    def send(self, key, message, headers: "dict | None" = None) -> None:
        if self._broker is None:
            self._broker = get_broker(self._broker_url)
            self._closed.clear()  # a send after close() reopens (lazy, as ever)
        # trace propagation: the producer injects the caller's current span
        # as a traceparent header (W3C format), so a trace minted at HTTP
        # ingress crosses the topic hop into whichever tier consumes this
        headers = spans.inject_headers(headers)
        # ONE idempotence token per logical send, OUTSIDE the retry: a
        # network broker that applied the append but lost the response
        # dedups the retried attempt instead of double-appending
        token = uuid.uuid4().hex

        def _append():
            faults.maybe_fail("broker.append")
            self._broker.append(self._topic, key, message, headers,
                                token=token)

        try:
            # bytes payloads must honor the cap exactly like str ones — the
            # str-only check let arbitrarily large bytes blobs bypass the
            # transport limit entirely (and blow the tcp broker's frame cap
            # downstream instead of failing typed at the producer)
            if (
                self._max_size is not None
                and isinstance(message, (str, bytes, bytearray))
                and len(message) > self._max_size
            ):
                raise TopicException(
                    f"message of {len(message)} bytes exceeds max {self._max_size}"
                )
            # transient append failures (file-broker I/O, injected faults)
            # retry under the process policy; a send raises only once the
            # budget is spent — retries are visible in oryx_retries_total
            resilience.default_policy().call(
                "broker.append", _append, retryable=transient_transport_error,
                stop=self._closed,
            )
        except Exception:
            _SEND_FAILURES.labels(self._topic).inc()
            raise
        _PRODUCED.labels(self._topic).inc()

    def close(self) -> None:
        self._closed.set()
        self._broker = None


class ConsumeDataIterator(Iterator[KeyMessage]):
    """Blocking iterator over a topic's partitions from starting offsets, with
    exponential poll backoff 1→1000 ms and wakeup-based close
    (kafka-util/.../ConsumeDataIterator.java:30-77).

    ``start_offset``: "earliest" (0), "latest" (current end), "committed"
    (per-partition positions stored in the broker's offset store under
    ``offset_group`` — falling back to ``group`` — looked up LAZILY when a
    partition is first touched, so partitions acquired mid-flight by a
    rebalance resume from the group's committed position instead of
    re-delivering from 0), an int (only valid when consuming exactly one
    partition), or a {partition: offset} dict. ``partitions`` restricts
    consumption to a fixed subset; ``group`` joins a consumer group instead
    — the broker's live membership splits the topic's partitions
    round-robin (partitions_for_member), re-evaluated every poll so
    consumers that join/leave rebalance without a coordinator.

    Offset *persistence* is deliberately not done here: layers commit consumed
    positions after processing (UpdateOffsetsFn semantics) via
    Broker.set_offset. Commit :attr:`processed_offsets` — the position past
    the last message HANDED OUT — never :attr:`offsets` (the read position,
    which runs ahead of processing by whatever sits in the prefetch buffer;
    committing it would silently skip buffered-but-unprocessed messages on
    a crash-resume).
    """

    _MIN_BACKOFF = 0.001
    _MAX_BACKOFF = 1.0
    _HEARTBEAT_SEC = 1.0

    def __init__(
        self,
        broker: Broker | str,
        topic: str,
        start_offset: "int | str | dict" = "earliest",
        partitions: "list[int] | None" = None,
        group: "str | None" = None,
        member_id: "str | None" = None,
        offset_group: "str | None" = None,
    ):
        self._broker = get_broker(broker) if isinstance(broker, str) else broker
        self._topic = topic
        self._group = group
        self._member_id = member_id or f"consumer-{os.getpid()}-{id(self):x}"
        self._n_parts = self._broker.num_partitions(topic)
        self._partitions = partitions
        if group is not None:
            self._broker.join_group(group, topic, self._member_id)
        self._last_heartbeat = time.monotonic()
        self._start = start_offset
        self._offset_group = offset_group if offset_group is not None else group
        self._offsets: dict[int, int] = {}
        if isinstance(start_offset, dict):
            self._offsets.update({int(p): int(o) for p, o in start_offset.items()})
        elif start_offset == "latest":
            # pin "latest" at subscribe time, for every partition — anything
            # produced after construction must be seen even if the first poll
            # is slow to schedule
            for p in range(self._n_parts):
                self._offsets[p] = self._broker.size(topic, p)
        elif start_offset == "committed":
            # positions resolve lazily per partition in _offset_of, so a
            # partition inherited from a dead group member resumes from the
            # group's committed offset, not from 0
            if not self._offset_group:
                raise TopicException(
                    "start_offset='committed' needs an offset_group (or "
                    "group) naming the stored positions"
                )
        elif start_offset != "earliest":
            static = partitions if partitions is not None else list(range(self._n_parts))
            if group is None and len(static) == 1:
                self._offsets[static[0]] = int(start_offset)
            elif group is None and self._n_parts == 1:
                self._offsets[0] = int(start_offset)
            else:
                raise TopicException(
                    "int start_offset is ambiguous over multiple partitions; "
                    "pass a {partition: offset} dict"
                )
        # prefetched messages with provenance: (message, partition, offset
        # AFTER this message) — __next__ pops one and advances _processed
        self._buffer: list[tuple[KeyMessage, int, int]] = []
        self._processed: dict[int, int] = {}
        self._closed = threading.Event()
        # last assignment actually used (rebalance-hysteresis baseline)
        self._last_assigned: "list[int] | None" = None

    # -- partition assignment -------------------------------------------------
    def _assigned(self) -> list[int]:
        if self._group is not None:
            now = time.monotonic()
            if now - self._last_heartbeat >= self._HEARTBEAT_SEC:
                self._broker.join_group(self._group, self._topic, self._member_id)
                self._last_heartbeat = now
            assigned = self._assignment_from_view()
            if (
                self._last_assigned is not None
                and set(assigned) - set(self._last_assigned)
                and self._closed.is_set()
            ):
                # a CLOSING consumer must never claim new partitions — in
                # any window. close() racing a peer's leave_group used to
                # take the raw expanded view here (the hysteresis below
                # was skipped exactly because closed was set), re-read the
                # departed member's partitions from 0, and hand out
                # duplicates before StopIteration landed.
                assigned = [
                    p for p in assigned if p in set(self._last_assigned)
                ]
            elif (
                self._last_assigned is not None
                and set(assigned) - set(self._last_assigned)
            ):
                # rebalance hysteresis (ISSUE 11): GROWING the assignment on
                # a single membership read is how a transient view (a
                # heartbeat racing the TTL sweep, a blipped members RPC)
                # turns into duplicate consumption — this member would claim
                # partitions a live peer is still draining, and in earliest
                # mode replay them from 0. Expansion must survive a second
                # read one beat later; shrinking (a peer JOINED) stays
                # immediate so two growers cannot overlap. Genuine takeover
                # of a dead member's partitions just lands ~50 ms later.
                self._closed.wait(0.05)
                if self._closed.is_set():
                    # a CLOSING consumer must never claim new partitions:
                    # close() racing a peer's leave_group used to let the
                    # expansion proceed here, re-reading the departed
                    # member's partitions from 0 and handing out duplicate
                    # messages in the teardown window before StopIteration
                    assigned = [
                        p for p in assigned if p in set(self._last_assigned)
                    ]
                else:
                    confirm = self._assignment_from_view()
                    if set(confirm) - set(self._last_assigned):
                        assigned = confirm
                    else:
                        assigned = [p for p in assigned if p in set(confirm)]
            self._last_assigned = assigned
            # rebalance hygiene: a partition lost to another member leaves
            # no residue — a stale _processed entry would let this member's
            # commit loop clobber the new owner's (higher) committed offset,
            # and in committed mode a stale read position would shadow the
            # store's offset if the partition ever came back
            for p in [p for p in self._processed if p not in assigned]:
                del self._processed[p]
            if self._start == "committed":
                for p in [p for p in self._offsets if p not in assigned]:
                    del self._offsets[p]
            return assigned
        if self._partitions is not None:
            return list(self._partitions)
        return list(range(self._n_parts))

    def _assignment_from_view(self) -> list[int]:
        """One membership read -> this member's partition list (static
        ``partitions=`` filter applied)."""
        members = self._broker.group_members(self._group, self._topic)
        assigned = partitions_for_member(self._member_id, members, self._n_parts)
        if self._partitions is not None:
            assigned = [p for p in assigned if p in self._partitions]
        return assigned

    def _offset_of(self, partition: int) -> int:
        off = self._offsets.get(partition)
        if off is None:
            if self._start == "committed":
                stored = self._stored_offset(partition)
                off = stored if stored is not None else 0
            else:
                off = 0
            self._offsets[partition] = off
        return off

    def _stored_offset(self, partition: int) -> "int | None":
        """Committed position lookup (first touch of a partition in
        "committed" mode) — the shared offset-op retry contract."""
        return offset_op(
            lambda: self._broker.get_offset(
                self._offset_group, self._topic, partition
            ),
            stop=self._closed,
        )

    def _read_with_retry(self, partition: int, offset: int) -> list:
        """One partition poll, retried through transient broker failures
        (stop-aware: a close() mid-backoff aborts the sleep). Exhausting the
        budget raises out of the consumer — supervised consumers restart."""

        def _read():
            faults.maybe_fail("broker.read")
            return self._broker.read(self._topic, offset, partition=partition)

        return resilience.default_policy().call(
            "broker.read", _read, retryable=transient_transport_error,
            stop=self._closed,
        )

    @property
    def offset(self) -> int:
        """Single-partition position (back-compat for 1-partition topics)."""
        return self._offset_of(0)

    @property
    def offsets(self) -> dict[int, int]:
        """READ positions (they run ahead of processing by the prefetch
        buffer — commit :attr:`processed_offsets`, not these)."""
        return dict(self._offsets)

    @property
    def processed_offsets(self) -> dict[int, int]:
        """Per-partition position past the last message HANDED OUT by
        ``__next__`` — the safe value for after-processing offset commits
        (UpdateOffsetsFn semantics): resuming from it neither re-delivers a
        processed message nor skips a prefetched-but-unprocessed one.
        Partitions lost to a group rebalance drop out on the next poll, so
        a commit loop writing these wholesale never clobbers the new
        owner's position."""
        return dict(self._processed)

    def messages_behind(self, total: int) -> int:
        """Advisory consumer lag against a topic-total snapshot: messages
        not yet handed out (read positions rolled back by the prefetch
        buffer). Correct in every start mode — a "committed" consumer's
        positions resolve on its first poll, so a caught-up restarted
        replica reads ~0 here, not the topic length. Before the first poll
        (no positions resolved) this reads 0: the backlog is unknown, and
        a replica that has not polled yet is covered by the lag-seconds
        gauge, not this one. A CLOSED iterator reads 0: it is being torn
        down (its supervised replacement re-registers the gauges), and a
        stale scrape callback must not report a dead pipeline's backlog."""
        if self._closed.is_set() or not self._offsets:
            return 0
        read = sum(self._offsets.values())
        return max(0, int(total) - read + len(self._buffer))

    def __iter__(self) -> "ConsumeDataIterator":
        return self

    def __next__(self) -> KeyMessage:
        backoff = self._MIN_BACKOFF
        while not self._buffer:
            if self._closed.is_set():
                raise StopIteration
            progressed = False
            for p in self._assigned():
                off = self._offset_of(p)
                batch = self._read_with_retry(p, off)
                if batch:
                    self._offsets[p] = off + len(batch)
                    n_corrupt = sum(1 for km in batch if km is CORRUPT_RECORD)
                    if n_corrupt:
                        # each corrupt offset is consumed (skipped) exactly
                        # once per consumer — counted here, not in read(),
                        # where re-polls would inflate the count
                        _CORRUPT_CONSUMED.labels("transport").inc(n_corrupt)
                    self._buffer.extend(
                        (km, p, off + i + 1)
                        for i, km in enumerate(batch)
                        if km is not CORRUPT_RECORD
                    )
                    progressed = True
            if self._buffer:
                break
            if progressed:
                continue  # consumed only corrupt records; poll again
            # total_size rides the retry policy too: an idle consumer must
            # not crash (and in earliest mode trigger a full replay) because
            # the broker blipped between two polls — the same contract the
            # read path already has (no fault hook: this probe is advisory)
            total = resilience.default_policy().call(
                "broker.read",
                lambda: self._broker.total_size(self._topic),
                retryable=transient_transport_error, stop=self._closed,
            )
            self._broker.wait_for_data(
                self._topic, total, backoff, stop=self._closed,
            )
            backoff = min(backoff * 2, self._MAX_BACKOFF)
        _CONSUMED.labels(self._topic).inc()
        km, p, next_off = self._buffer.pop(0)
        self._processed[p] = next_off
        return km

    def close(self) -> None:
        """Wake up and terminate a blocked iteration (consumer.wakeup())."""
        self._closed.set()
        if self._group is not None:
            try:
                self._broker.leave_group(self._group, self._topic, self._member_id)
            except Exception:  # noqa: BLE001 — best-effort on teardown
                log.debug("leave_group failed on close", exc_info=True)
        self._broker.wake(self._topic)


def maybe_create_topics(config, *topic_keys: str) -> None:
    """Assert/create the configured topics with their configured partition
    counts (AbstractSparkLayer.java:178-185 + oryx-run.sh kafka-setup:345-358).
    topic_keys like 'input-topic', 'update-topic'."""
    for tk in topic_keys:
        broker = get_broker(config.get_string(f"oryx.{tk}.broker"))
        name = config.get_string(f"oryx.{tk}.message.topic")
        if not broker.topic_exists(name):
            parts = config.get_int(f"oryx.{tk}.message.partitions", 1) or 1
            broker.create_topic(name, parts)
