"""Topic transport: the framework's data plane.

TPU-native replacement for the reference's Kafka/ZooKeeper messaging layer
(framework/kafka-util/.../KafkaUtils.java:63-188 and
ConsumeDataIterator.java:30-77). Two backends behind one URL scheme:

  * ``memory:`` — in-process broker (a process-wide registry of append-only
    logs with condition-variable wakeup). The default for tests and
    single-process deployments, standing in for the reference ITs'
    LocalKafkaBroker.
  * ``file:<dir>`` — durable broker: each topic is an append-only JSONL log
    on disk, readable by other processes on the same filesystem; offsets are
    line indices. This is the host-side pub-sub that rides shared storage —
    cross-host deployments point it at a network filesystem (DCN transport),
    while device-side collectives stay inside pjit programs.

Semantics kept from the reference:
  * topics are append-only logs; consumers track offsets; layers persist
    consumed positions through the broker's OffsetStore *after* processing
    each batch (UpdateOffsetsFn semantics — see AbstractLayer), keyed by
    ``oryx.id``;
  * consuming from ``earliest`` replays the whole log (how speed/serving
    rebuild model state, SpeedLayer.java:108-110);
  * a blocking consume iterator with exponential poll backoff 1→1000 ms and
    wakeup-based close (ConsumeDataIterator.java:30-77);
  * producers enforce a transport-level max message size (Kafka
    max.request.size = 1<<26); topics support prefix truncation in lieu of
    Kafka retention.

FileBroker writes each record as one O_APPEND write syscall (atomic between
cooperating local processes; NFS append atomicity is not guaranteed — use one
writer per topic there) and tolerates a partial trailing line from an
in-flight writer by stopping before it.
"""

from __future__ import annotations

import json
import os
import threading
import time
from pathlib import Path
from typing import Iterator

from oryx_tpu.api.keymessage import KeyMessage
from oryx_tpu.common import ioutils


class TopicException(Exception):
    pass


#: Placeholder returned for a corrupt log record so offsets stay aligned;
#: ConsumeDataIterator filters it out by identity.
CORRUPT_RECORD = KeyMessage(None, None)


# ---------------------------------------------------------------------------
# Broker interface + registry
# ---------------------------------------------------------------------------


class Broker:
    """create/delete/exists + log access for one transport endpoint
    (KafkaUtils equivalent)."""

    def create_topic(self, name: str, partitions: int = 1) -> None:
        raise NotImplementedError

    def delete_topic(self, name: str) -> None:
        raise NotImplementedError

    def topic_exists(self, name: str) -> bool:
        raise NotImplementedError

    def append(self, topic: str, key, message) -> None:
        raise NotImplementedError

    def read(self, topic: str, offset: int, max_items: int = 1024) -> list[KeyMessage]:
        raise NotImplementedError

    def size(self, topic: str) -> int:
        """Latest offset (number of messages ever appended)."""
        raise NotImplementedError

    def truncate(self, topic: str, before_offset: int) -> None:
        """Drop messages below the given offset (retention stand-in). Offsets
        are stable: reads below the new base return nothing."""
        raise NotImplementedError

    def wait_for_data(self, topic: str, offset: int, timeout: float, stop=None) -> None:
        """Block until new data may exist, timeout elapses, or ``stop``
        (a threading.Event) is set."""
        if stop is not None:
            stop.wait(timeout)
        else:
            time.sleep(timeout)

    def wake(self, topic: str) -> None:
        """Wake blocked wait_for_data callers (consumer.wakeup())."""

    # offset store (ZK-equivalent control plane, KafkaUtils.java:120-188)
    def get_offset(self, group: str, topic: str) -> int | None:
        raise NotImplementedError

    def set_offset(self, group: str, topic: str, offset: int) -> None:
        raise NotImplementedError


_memory_brokers: dict[str, "MemoryBroker"] = {}
_memory_lock = threading.Lock()


def get_broker(url: str) -> Broker:
    """Resolve a broker from a config URL: ``memory:[name]`` or ``file:<dir>``."""
    if url.startswith("memory:"):
        name = url[len("memory:"):] or "default"
        with _memory_lock:
            b = _memory_brokers.get(name)
            if b is None:
                b = _memory_brokers[name] = MemoryBroker()
            return b
    if url.startswith("file:"):
        return FileBroker(url[len("file:"):])
    raise TopicException(f"unknown broker url: {url}")


def reset_memory_brokers() -> None:
    """Drop all in-process brokers (test isolation)."""
    with _memory_lock:
        _memory_brokers.clear()


class _MemoryTopic:
    __slots__ = ("log", "base", "cond")

    def __init__(self):
        self.log: list[KeyMessage] = []
        self.base = 0  # offset of log[0]; advances on truncate
        self.cond = threading.Condition()


class MemoryBroker(Broker):
    def __init__(self):
        self._topics: dict[str, _MemoryTopic] = {}
        self._offsets: dict[tuple[str, str], int] = {}
        self._lock = threading.Lock()

    def _topic(self, name: str) -> _MemoryTopic:
        with self._lock:
            t = self._topics.get(name)
            if t is None:
                raise TopicException(f"topic does not exist: {name}")
            return t

    def create_topic(self, name: str, partitions: int = 1) -> None:
        with self._lock:
            self._topics.setdefault(name, _MemoryTopic())

    def delete_topic(self, name: str) -> None:
        with self._lock:
            self._topics.pop(name, None)

    def topic_exists(self, name: str) -> bool:
        with self._lock:
            return name in self._topics

    def append(self, topic: str, key, message) -> None:
        t = self._topic(topic)
        with t.cond:
            t.log.append(KeyMessage(key, message))
            t.cond.notify_all()

    def read(self, topic: str, offset: int, max_items: int = 1024) -> list[KeyMessage]:
        t = self._topic(topic)
        with t.cond:
            lo = max(offset - t.base, 0)
            return t.log[lo:lo + max_items]

    def size(self, topic: str) -> int:
        t = self._topic(topic)
        with t.cond:
            return t.base + len(t.log)

    def truncate(self, topic: str, before_offset: int) -> None:
        t = self._topic(topic)
        with t.cond:
            drop = min(max(before_offset - t.base, 0), len(t.log))
            if drop:
                del t.log[:drop]
                t.base += drop

    def wait_for_data(self, topic: str, offset: int, timeout: float, stop=None) -> None:
        t = self._topic(topic)
        with t.cond:
            if t.base + len(t.log) <= offset and not (stop is not None and stop.is_set()):
                t.cond.wait(timeout)

    def wake(self, topic: str) -> None:
        try:
            t = self._topic(topic)
        except TopicException:
            return
        with t.cond:
            t.cond.notify_all()

    def get_offset(self, group: str, topic: str) -> int | None:
        with self._lock:
            return self._offsets.get((group, topic))

    def set_offset(self, group: str, topic: str, offset: int) -> None:
        with self._lock:
            self._offsets[(group, topic)] = offset


class FileBroker(Broker):
    """Append-only JSONL log per topic under a directory.

    Appends are single O_APPEND write syscalls, atomic between cooperating
    processes on a local filesystem. Reads keep a per-topic byte index that
    extends incrementally, so polling cost is O(new bytes), not O(log size).
    A partial trailing line (in-flight writer) is left for the next read;
    corrupt interior lines are skipped with a warning.
    """

    def __init__(self, root: str):
        self._root = Path(root)
        ioutils.mkdirs(self._root)
        self._lock = threading.Lock()
        # topic -> (line-start byte offsets incl. next-append position)
        self._index: dict[str, list[int]] = {}

    def _log_path(self, name: str) -> Path:
        return self._root / name / "00000.jsonl"

    def create_topic(self, name: str, partitions: int = 1) -> None:
        p = self._log_path(name)
        ioutils.mkdirs(p.parent)
        p.touch(exist_ok=True)

    def delete_topic(self, name: str) -> None:
        ioutils.delete_recursively(self._root / name)
        with self._lock:
            self._index.pop(name, None)

    def topic_exists(self, name: str) -> bool:
        return self._log_path(name).exists()

    def append(self, topic: str, key, message) -> None:
        p = self._log_path(topic)
        if not p.exists():
            raise TopicException(f"topic does not exist: {topic}")
        data = (json.dumps({"k": key, "m": message}, separators=(",", ":")) + "\n").encode("utf-8")
        fd = os.open(p, os.O_WRONLY | os.O_APPEND)
        try:
            written = os.write(fd, data)
            # loop on short writes; only the first write is append-atomic, but
            # a torn tail is better than a silently dropped one
            while written < len(data):
                written += os.write(fd, data[written:])
        finally:
            os.close(fd)

    def _refresh_index(self, topic: str) -> list[int]:
        """Extend the line index over bytes appended since the last call."""
        p = self._log_path(topic)
        if not p.exists():
            raise TopicException(f"topic does not exist: {topic}")
        with self._lock:
            idx = self._index.setdefault(topic, [0])
            scanned = idx[-1]
            file_size = p.stat().st_size
            if file_size <= scanned:
                return idx
            with open(p, "rb") as f:
                f.seek(scanned)
                data = f.read(file_size - scanned)
            pos = 0
            while True:
                nl = data.find(b"\n", pos)
                if nl == -1:
                    break  # partial trailing line stays unindexed
                idx.append(scanned + nl + 1)
                pos = nl + 1
            return idx

    def read(self, topic: str, offset: int, max_items: int = 1024) -> list[KeyMessage]:
        idx = self._refresh_index(topic)
        n = len(idx) - 1  # complete lines
        if offset >= n:
            return []
        end = min(offset + max_items, n)
        p = self._log_path(topic)
        out: list[KeyMessage] = []
        with open(p, "rb") as f:
            f.seek(idx[offset])
            blob = f.read(idx[end] - idx[offset])
        lines = blob.split(b"\n")
        if lines and not lines[-1]:
            lines.pop()  # trailing newline artifact only; blank interior
            # lines must still produce CORRUPT_RECORD to keep offsets aligned
        for raw in lines:
            if not raw.strip():
                out.append(CORRUPT_RECORD)
                continue
            try:
                d = json.loads(raw)
                out.append(KeyMessage(d["k"], d["m"]))
            except (json.JSONDecodeError, KeyError):
                import logging

                logging.getLogger(__name__).warning(
                    "skipping corrupt record in topic %s", topic
                )
                out.append(CORRUPT_RECORD)  # keep offsets aligned
        return out[: end - offset]

    def size(self, topic: str) -> int:
        return len(self._refresh_index(topic)) - 1

    def truncate(self, topic: str, before_offset: int) -> None:
        """Rewrite the log without the truncated prefix. Offsets shift to
        0-based on disk but this broker instance keeps serving stable offsets
        only for fresh reads; cross-process readers should truncate during
        quiet periods (retention maintenance)."""
        idx = self._refresh_index(topic)
        n = len(idx) - 1
        cut = min(max(before_offset, 0), n)
        if cut == 0:
            return
        p = self._log_path(topic)
        with open(p, "rb") as f:
            f.seek(idx[cut])
            rest = f.read()
        tmp = p.with_suffix(".tmp")
        tmp.write_bytes(rest)
        tmp.replace(p)
        with self._lock:
            self._index.pop(topic, None)

    def get_offset(self, group: str, topic: str) -> int | None:
        p = self._root / ".offsets" / f"{group}__{topic}.json"
        if not p.exists():
            return None
        return json.loads(p.read_text())["offset"]

    def set_offset(self, group: str, topic: str, offset: int) -> None:
        p = self._root / ".offsets" / f"{group}__{topic}.json"
        ioutils.mkdirs(p.parent)
        tmp = p.with_suffix(".tmp")
        tmp.write_text(json.dumps({"offset": offset}))
        tmp.replace(p)


# ---------------------------------------------------------------------------
# Producer + consume iterator (TopicProducer / ConsumeDataIterator)
# ---------------------------------------------------------------------------

#: Fixed transport-level message cap (TopicProducerImpl.java sets Kafka
#: max.request.size = 1<<26). The *configured* update-topic max-size only
#: drives MLUpdate's inline-vs-MODEL-REF decision, not producer enforcement.
MAX_REQUEST_SIZE = 1 << 26


class TopicProducerImpl:
    """Producer for one topic (framework/oryx-lambda/.../TopicProducerImpl.java).
    Enforces the transport cap; oversized sends raise, and callers fall back to
    the MODEL-REF by-reference protocol (ml/MLUpdate publish path)."""

    def __init__(self, broker_url: str, topic: str, max_size: int | None = MAX_REQUEST_SIZE):
        self._broker_url = broker_url
        self._topic = topic
        self._max_size = max_size
        self._broker: Broker | None = None  # lazy, like the reference

    def get_update_broker(self) -> str:
        return self._broker_url

    def get_topic(self) -> str:
        return self._topic

    def send(self, key, message) -> None:
        if self._broker is None:
            self._broker = get_broker(self._broker_url)
        if self._max_size is not None and isinstance(message, str) and len(message) > self._max_size:
            raise TopicException(
                f"message of {len(message)} bytes exceeds max {self._max_size}"
            )
        self._broker.append(self._topic, key, message)

    def close(self) -> None:
        self._broker = None


class ConsumeDataIterator(Iterator[KeyMessage]):
    """Blocking iterator over a topic from a starting offset, with exponential
    poll backoff 1→1000 ms and wakeup-based close
    (kafka-util/.../ConsumeDataIterator.java:30-77).

    ``start_offset``: int offset, or "earliest" (0), or "latest" (current end).
    Offset *persistence* is deliberately not done here: layers commit consumed
    positions after processing (UpdateOffsetsFn semantics) via
    Broker.set_offset.
    """

    _MIN_BACKOFF = 0.001
    _MAX_BACKOFF = 1.0

    def __init__(
        self,
        broker: Broker | str,
        topic: str,
        start_offset: "int | str" = "earliest",
    ):
        self._broker = get_broker(broker) if isinstance(broker, str) else broker
        self._topic = topic
        if start_offset == "earliest":
            self._offset = 0
        elif start_offset == "latest":
            self._offset = self._broker.size(topic)
        else:
            self._offset = int(start_offset)
        self._buffer: list[KeyMessage] = []
        self._closed = threading.Event()

    @property
    def offset(self) -> int:
        return self._offset

    def __iter__(self) -> "ConsumeDataIterator":
        return self

    def __next__(self) -> KeyMessage:
        backoff = self._MIN_BACKOFF
        while not self._buffer:
            if self._closed.is_set():
                raise StopIteration
            batch = self._broker.read(self._topic, self._offset)
            if batch:
                self._offset += len(batch)
                self._buffer = [km for km in batch if km is not CORRUPT_RECORD]
                if not self._buffer:
                    continue
                break
            self._broker.wait_for_data(self._topic, self._offset, backoff, stop=self._closed)
            backoff = min(backoff * 2, self._MAX_BACKOFF)
        return self._buffer.pop(0)

    def close(self) -> None:
        """Wake up and terminate a blocked iteration (consumer.wakeup())."""
        self._closed.set()
        self._broker.wake(self._topic)


def maybe_create_topics(config, *topic_keys: str) -> None:
    """Assert/create the configured topics (AbstractSparkLayer.java:178-185 +
    oryx-run.sh kafka-setup). topic_keys like 'input-topic', 'update-topic'."""
    for tk in topic_keys:
        broker = get_broker(config.get_string(f"oryx.{tk}.broker"))
        name = config.get_string(f"oryx.{tk}.message.topic")
        if not broker.topic_exists(name):
            broker.create_topic(name)
