"""``tcp:`` network broker: the transport's cross-host backend.

The ``memory:``/``file:`` brokers coordinate through process memory or a
shared filesystem, which walls every cross-host story (replica fleets,
rolling restarts) behind NFS (docs/admin.md, the v0 decision). This module
is the wall coming down: an asyncio TCP **server** that owns a topic
directory by wrapping a local :class:`~oryx_tpu.transport.topic.FileBroker`
— one process is the single writer, which also retires the file broker's
NFS append-atomicity caveat — plus a thread-safe **client** registered
under ``tcp://host:port`` in :func:`~oryx_tpu.transport.topic.get_broker`,
implementing the entire :class:`~oryx_tpu.transport.topic.Broker` contract:
create/delete/exists/num_partitions, key-hash-routed append with headers
(traceparent propagation unchanged), offset-paged reads, truncation, atomic
offset commits, and consumer-group sessions with **server-side** heartbeat
TTL so ``partitions_for_member`` rebalance works across hosts.

Wire protocol: length-prefixed JSON frames (4-byte big-endian length +
UTF-8 JSON body). Requests are ``{"id": n, "op": ..., <args>}``; responses
``{"id": n, "ok": true, "result": ...}`` or ``{"id": n, "ok": false,
"error": ..., "transient": bool}`` — server-side ``TopicException``s cross
the wire TYPED, so a client sees the same exception class (and transience
flag) it would from an in-process broker, and the existing
``resilience.default_policy()``/``transient_transport_error`` retry
contract carries over unchanged. Connection failures surface as plain
``OSError`` (transient by predicate); the client drops its per-thread
socket on any error and reconnects on the next call, so a broker restart
costs one retried RPC, never a stuck consumer.

Push wakeup: ``wait_for_data`` is a server-side long-poll — the caller
parks on an asyncio condition until an append (or an explicit ``wake``)
notifies it, so an idle ``tcp:`` consumer receives new data at network RTT
while a ``file:`` consumer sleeps out its poll backoff (the sub-ms state
propagation pattern of low-latency serverless dataflows, PAPERS.md
arXiv:2007.05832). Run the server with ``python -m oryx_tpu.cli broker
--port N --dir D``; counters (connections, frames, bytes, per-RPC latency
histogram) live in the process metrics registry, scrapeable over the wire
through the ``metrics`` RPC (``NetBrokerClient.server_metrics()``).
"""

from __future__ import annotations

import asyncio
import contextlib
import json
import socket
import struct
import threading
import time
from collections import OrderedDict

from oryx_tpu.common import metrics as metrics_mod
from oryx_tpu.common import spans
from oryx_tpu.transport import topic as tp

log = spans.get_logger(__name__)

#: Header bytes on every frame: big-endian unsigned length of the JSON body.
_LEN = struct.Struct(">I")

#: Server-side cap on one long-poll park (clients re-issue; a lost client
#: must never pin a waiter task forever).
_MAX_WAIT_SEC = 60.0

#: Extra client-socket patience on top of a long-poll's requested timeout.
_WAIT_GRACE_SEC = 5.0

#: Producer idempotence window: recently-applied append tokens kept for
#: retry dedup (a retry after a lost response must not double-append).
_MAX_APPLIED_TOKENS = 8192

#: Headroom reserved for the response envelope when packing read results
#: into one frame (the rest of max_frame_bytes is message budget).
_READ_FRAME_MARGIN = 65536


class _OversizeRequest(Exception):
    """A request frame over the server cap: drained and answered TYPED
    (non-transient) instead of cutting the socket — a cut would read as
    transient to the client and fuel a pointless retry storm."""

_CONNECTIONS = metrics_mod.default_registry().counter(
    "oryx_netbroker_connections_total",
    "TCP connections ever accepted by the broker server",
)
_ACTIVE = metrics_mod.default_registry().gauge(
    "oryx_netbroker_connections_active",
    "TCP connections currently open on the broker server",
)
_FRAMES = metrics_mod.default_registry().counter(
    "oryx_netbroker_frames_total",
    "RPC frames handled by the broker server, by op",
    ("op",),
)
_BYTES = metrics_mod.default_registry().counter(
    "oryx_netbroker_bytes_total",
    "Bytes moved over broker connections by direction (in=requests, "
    "out=responses)",
    ("direction",),
)
_RPC_LATENCY = metrics_mod.default_registry().histogram(
    "oryx_netbroker_rpc_latency_seconds",
    "Server-side handling latency per RPC op (frame decoded to response "
    "written)",
    ("op",),
)

#: Process defaults for tcp clients, shaped by :func:`configure` from
#: ``oryx.broker.tcp.*`` (the same configure() idiom as resilience/metrics).
_DEFAULTS = {
    "connect_timeout_sec": 10.0,
    "request_timeout_sec": 30.0,
    "max_frame_bytes": tp.MAX_REQUEST_SIZE,
}
_defaults_lock = threading.Lock()


def configure(config) -> None:
    """Adopt ``oryx.broker.tcp.*`` as process-wide client defaults
    (idempotent; every layer entry point calls this, like resilience)."""
    t = config.get_config("oryx.broker.tcp")
    with _defaults_lock:
        _DEFAULTS["connect_timeout_sec"] = t.get_float("connect-timeout-sec", 10.0)
        _DEFAULTS["request_timeout_sec"] = t.get_float("request-timeout-sec", 30.0)
        _DEFAULTS["max_frame_bytes"] = t.get_int(
            "max-frame-bytes", tp.MAX_REQUEST_SIZE
        )


# ---------------------------------------------------------------------------
# Server
# ---------------------------------------------------------------------------


class NetBrokerServer:
    """Asyncio TCP broker server owning one topic directory.

    All durable state delegates to an inner :class:`FileBroker` — every
    blocking file op hops off the event loop through ``asyncio.to_thread``,
    and per-connection frames are handled strictly in order, so one
    connection's appends keep their order while connections stay
    independent. Consumer-group membership is held in server memory with a
    monotonic heartbeat TTL (``group_ttl_sec``): a member whose process
    died simply stops heartbeating and drops out of ``group_members`` after
    the TTL, triggering client-side rebalance — no coordinator, no shared
    filesystem, works across hosts.
    """

    def __init__(self, root: str, host: str = "0.0.0.0", port: int = 0,
                 group_ttl_sec: "float | None" = None,
                 max_frame_bytes: "int | None" = None,
                 stats_interval_sec: float = 0.0):
        self._inner = tp.FileBroker(root)
        self.root = str(root)
        self.host = host
        self.port = port  # 0 = ephemeral; resolved once serving
        self.group_ttl_sec = (
            float(group_ttl_sec) if group_ttl_sec is not None
            else tp.GROUP_MEMBER_TTL_SEC
        )
        self.max_frame_bytes = int(
            max_frame_bytes if max_frame_bytes is not None
            else _DEFAULTS["max_frame_bytes"]
        )
        self.stats_interval_sec = float(stats_interval_sec)
        # loop-confined state (touched only from the server's event loop)
        self._groups: dict[tuple[str, str], dict[str, float]] = {}
        self._conds: dict[str, asyncio.Condition] = {}
        self._wake_epoch: dict[str, int] = {}
        self._applied_tokens: "OrderedDict[str, None]" = OrderedDict()
        self._server: "asyncio.base_events.Server | None" = None
        self._loop: "asyncio.AbstractEventLoop | None" = None
        self._thread: "threading.Thread | None" = None
        self._closed = threading.Event()
        # plain tallies for the periodic stats log line (loop-confined)
        self._n_connections = 0
        self._n_frames = 0
        self._n_bytes_in = 0
        self._n_bytes_out = 0

    # -- lifecycle -----------------------------------------------------------
    async def start_serving(self) -> None:
        """Bind and start accepting (call from the owning event loop)."""
        self._loop = asyncio.get_running_loop()
        self._server = await asyncio.start_server(
            self._handle, self.host, self.port
        )
        self.port = self._server.sockets[0].getsockname()[1]
        if self.stats_interval_sec > 0:
            self._loop.create_task(self._stats_loop())
        log.info("netbroker serving %s on %s:%d", self.root, self.host, self.port)

    def start_background(self) -> "NetBrokerServer":
        """Run the server on its own thread+loop (tests, benches, and the
        ``cli broker`` foreground both ride this)."""
        started = threading.Event()
        failure: list[BaseException] = []

        def run():
            loop = asyncio.new_event_loop()
            asyncio.set_event_loop(loop)
            try:
                loop.run_until_complete(self.start_serving())
            except BaseException as e:  # noqa: BLE001
                log.exception("netbroker failed to bind %s:%d",
                              self.host, self.port)
                failure.append(e)  # re-raised by the starting thread below
                started.set()
                loop.close()
                return
            started.set()
            try:
                loop.run_forever()
            finally:
                self._server.close()
                loop.run_until_complete(self._server.wait_closed())
                # connection handlers (and parked long-polls) still pending
                # get a clean cancel — never destroyed with the loop
                pending = asyncio.all_tasks(loop)
                for task in pending:
                    task.cancel()
                if pending:
                    loop.run_until_complete(
                        asyncio.gather(*pending, return_exceptions=True)
                    )
                loop.close()

        self._thread = threading.Thread(
            target=run, name="OryxNetBrokerServer", daemon=True
        )
        self._thread.start()
        if not started.wait(15):
            raise RuntimeError("netbroker server failed to start within 15s")
        if failure:
            raise failure[0]
        return self

    def close(self) -> None:
        self._closed.set()
        if self._loop is not None:
            with contextlib.suppress(RuntimeError):  # loop already closed
                self._loop.call_soon_threadsafe(self._loop.stop)
        if self._thread is not None and self._thread is not threading.current_thread():
            self._thread.join(timeout=10)
            if self._thread.is_alive():
                log.warning("netbroker server thread did not stop within 10s")

    # -- connection handling ---------------------------------------------------
    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        _CONNECTIONS.inc()
        _ACTIVE.inc()
        self._n_connections += 1
        try:
            while True:
                try:
                    frame = await self._read_frame(reader)
                except _OversizeRequest as e:
                    # the oversize body was drained, so the stream is still
                    # in sync: answer typed (unaddressed — the client maps
                    # it onto its in-flight request) and keep serving
                    body = json.dumps(
                        {"id": None, "ok": False, "error": str(e),
                         "transient": False},
                        separators=(",", ":"),
                    ).encode("utf-8")
                    writer.write(_LEN.pack(len(body)) + body)
                    await writer.drain()
                    continue
                if frame is None:
                    return  # peer closed cleanly
                t0 = time.perf_counter()
                op = frame.get("op", "?")
                resp = await self._dispatch(frame, op)
                body = json.dumps(resp, separators=(",", ":")).encode("utf-8")
                writer.write(_LEN.pack(len(body)) + body)
                await writer.drain()
                self._n_frames += 1
                self._n_bytes_out += len(body) + _LEN.size
                _FRAMES.labels(op).inc()
                _BYTES.labels("out").inc(len(body) + _LEN.size)
                _RPC_LATENCY.labels(op).observe(time.perf_counter() - t0)
        except (asyncio.IncompleteReadError, ConnectionError, TimeoutError):
            log.debug("netbroker connection dropped mid-frame", exc_info=True)
        except Exception:  # noqa: BLE001 — one bad connection must not kill accept
            log.exception("netbroker connection handler failed")
        finally:
            _ACTIVE.dec()
            writer.close()
            with contextlib.suppress(Exception):
                await writer.wait_closed()

    async def _read_frame(self, reader: asyncio.StreamReader) -> "dict | None":
        try:
            head = await reader.readexactly(_LEN.size)
        except asyncio.IncompleteReadError as e:
            if not e.partial:
                return None  # clean EOF between frames
            raise
        (length,) = _LEN.unpack(head)
        if length > self.max_frame_bytes:
            # drain the refused body so the next frame parses cleanly
            remaining = length
            while remaining:
                chunk = await reader.read(min(remaining, 1 << 20))
                if not chunk:
                    raise asyncio.IncompleteReadError(b"", remaining)
                remaining -= len(chunk)
            raise _OversizeRequest(
                f"request frame of {length} bytes exceeds server max "
                f"{self.max_frame_bytes}"
            )
        body = await reader.readexactly(length)
        self._n_bytes_in += length + _LEN.size
        _BYTES.labels("in").inc(length + _LEN.size)
        return json.loads(body)

    async def _dispatch(self, frame: dict, op: str) -> dict:
        rid = frame.get("id")
        handler = self._OPS.get(op)
        try:
            if handler is None:
                raise tp.TopicException(f"unknown broker op: {op!r}")
            result = await handler(self, frame)
            return {"id": rid, "ok": True, "result": result}
        except tp.TopicException as e:
            # typed over the wire: the client re-raises the same class with
            # the same transience, so retry classification is identical to
            # an in-process broker
            return {"id": rid, "ok": False, "error": str(e),
                    "transient": bool(e.transient)}
        except OSError as e:
            log.warning("netbroker op %s hit I/O error: %s", op, e)
            return {"id": rid, "ok": False,
                    "error": f"{type(e).__name__}: {e}", "transient": True}
        except Exception as e:  # noqa: BLE001 — a server bug answers typed, not a cut socket
            log.exception("netbroker op %s failed", op)
            return {"id": rid, "ok": False,
                    "error": f"{type(e).__name__}: {e}", "transient": False}

    # -- ops -------------------------------------------------------------------
    async def _op_ping(self, f: dict) -> dict:
        return {"dir": self.root, "group_ttl_sec": self.group_ttl_sec}

    async def _op_create_topic(self, f: dict) -> None:
        await asyncio.to_thread(
            self._inner.create_topic, f["topic"], int(f.get("partitions", 1))
        )

    async def _op_delete_topic(self, f: dict) -> None:
        await asyncio.to_thread(self._inner.delete_topic, f["topic"])
        await self._notify(f["topic"], wake=True)

    async def _op_topic_exists(self, f: dict) -> bool:
        return await asyncio.to_thread(self._inner.topic_exists, f["topic"])

    async def _op_num_partitions(self, f: dict) -> int:
        return await asyncio.to_thread(self._inner.num_partitions, f["topic"])

    async def _op_append(self, f: dict) -> "dict | None":
        # producer idempotence: a retried append bearing a token the server
        # already applied (response lost in flight) is acknowledged, not
        # re-appended — retries over the wire stay duplicate-free like the
        # in-process brokers, where a failed append never applied at all
        token = f.get("token")
        if token is not None and token in self._applied_tokens:
            return {"dup": True}
        await asyncio.to_thread(
            self._inner.append, f["topic"], f.get("key"), f.get("message"),
            f.get("headers"),
        )
        if token is not None:
            self._applied_tokens[token] = None
            while len(self._applied_tokens) > _MAX_APPLIED_TOKENS:
                self._applied_tokens.popitem(last=False)
        await self._notify(f["topic"])
        return None

    async def _op_read(self, f: dict) -> list:
        def read_bounded() -> list:
            msgs = self._inner.read(
                f["topic"], int(f["offset"]),
                int(f.get("max_items", 1024)), int(f.get("partition", 0)),
            )
            # byte-bound the response to the frame cap (minus envelope
            # headroom): 1024 near-cap messages would otherwise build a
            # frame the client must refuse, wedging that offset forever —
            # a trimmed read just means the next poll continues from where
            # this one stopped. At least one message always goes through
            # (any message that ARRIVED through this broker fit in an
            # append frame, so it fits here too).
            budget = self.max_frame_bytes - _READ_FRAME_MARGIN
            out: list = []
            used = 0
            for km in msgs:
                item = (
                    {"corrupt": True} if km is tp.CORRUPT_RECORD
                    else {"k": km.key, "m": km.message, "h": km.headers}
                )
                size = len(json.dumps(item, separators=(",", ":")))
                if out and used + size > budget:
                    break
                out.append(item)
                used += size
            return out

        return await asyncio.to_thread(read_bounded)

    async def _op_size(self, f: dict) -> int:
        return await asyncio.to_thread(
            self._inner.size, f["topic"], int(f.get("partition", 0))
        )

    async def _op_total_size(self, f: dict) -> int:
        return await asyncio.to_thread(self._inner.total_size, f["topic"])

    async def _op_truncate(self, f: dict) -> None:
        await asyncio.to_thread(
            self._inner.truncate, f["topic"], int(f["before_offset"]),
            int(f.get("partition", 0)),
        )

    async def _op_get_offset(self, f: dict) -> "int | None":
        return await asyncio.to_thread(
            self._inner.get_offset, f["group"], f["topic"],
            int(f.get("partition", 0)),
        )

    async def _op_set_offset(self, f: dict) -> None:
        await asyncio.to_thread(
            self._inner.set_offset, f["group"], f["topic"], int(f["offset"]),
            int(f.get("partition", 0)),
        )

    async def _op_join_group(self, f: dict) -> None:
        # server-side session: the heartbeat clock is THIS process's
        # monotonic time, so membership works across hosts with no shared
        # filesystem and no client clock agreement
        key = (f["group"], f["topic"])
        self._groups.setdefault(key, {})[f["member_id"]] = time.monotonic()

    async def _op_leave_group(self, f: dict) -> None:
        self._groups.get((f["group"], f["topic"]), {}).pop(f["member_id"], None)

    async def _op_group_members(self, f: dict) -> list:
        now = time.monotonic()
        members = self._groups.get((f["group"], f["topic"]), {})
        live = sorted(m for m, hb in members.items()
                      if now - hb < self.group_ttl_sec)
        # drop expired sessions eagerly so the table stays bounded
        for m in list(members):
            if now - members[m] >= self.group_ttl_sec:
                del members[m]
        return live

    async def _op_wait_for_data(self, f: dict) -> dict:
        """Long-poll: parked on the topic's condition until an append (or an
        explicit wake) notifies, the timeout lapses, or the cap trips. The
        push path that makes ``tcp:`` wakeups land at RTT instead of the
        file broker's sleep backoff."""
        name = f["topic"]
        seen = int(f["seen_total"])
        timeout = min(max(float(f.get("timeout", 0.0)), 0.0), _MAX_WAIT_SEC)
        cond = self._cond(name)
        loop = asyncio.get_running_loop()
        deadline = loop.time() + timeout
        while True:
            # epoch BEFORE the size check: every notify bumps it, so an
            # append that lands between the total_size below and the
            # cond acquisition flips the epoch and the re-check under the
            # lock skips the wait — no lost wakeup, no timeout-length stall
            epoch = self._wake_epoch.get(name, 0)
            total = await asyncio.to_thread(self._inner.total_size, name)
            if total > seen or self._wake_epoch.get(name, 0) != epoch:
                return {"woken": True, "total": total}
            remaining = deadline - loop.time()
            if remaining <= 0:
                return {"woken": False, "total": total}
            async with cond:
                if self._wake_epoch.get(name, 0) != epoch:
                    continue
                try:
                    await asyncio.wait_for(cond.wait(), remaining)
                except (asyncio.TimeoutError, TimeoutError):
                    return {"woken": False, "total": total}

    async def _op_wake(self, f: dict) -> None:
        await self._notify(f["topic"], wake=True)

    async def _op_metrics(self, f: dict) -> dict:
        return {"text": metrics_mod.default_registry().render()}

    _OPS = {
        "ping": _op_ping,
        "create_topic": _op_create_topic,
        "delete_topic": _op_delete_topic,
        "topic_exists": _op_topic_exists,
        "num_partitions": _op_num_partitions,
        "append": _op_append,
        "read": _op_read,
        "size": _op_size,
        "total_size": _op_total_size,
        "truncate": _op_truncate,
        "get_offset": _op_get_offset,
        "set_offset": _op_set_offset,
        "join_group": _op_join_group,
        "leave_group": _op_leave_group,
        "group_members": _op_group_members,
        "wait_for_data": _op_wait_for_data,
        "wake": _op_wake,
        "metrics": _op_metrics,
    }

    # -- wakeup plumbing -------------------------------------------------------
    def _cond(self, name: str) -> asyncio.Condition:
        cond = self._conds.get(name)
        if cond is None:
            cond = self._conds[name] = asyncio.Condition()
        return cond

    async def _notify(self, name: str, wake: bool = False) -> None:
        # every notify bumps the epoch (append, delete, explicit wake):
        # parked waiters distinguish "something happened while I was between
        # my size check and cond.wait" from a quiet topic (loop-confined)
        self._wake_epoch[name] = self._wake_epoch.get(name, 0) + 1
        cond = self._cond(name)
        async with cond:
            cond.notify_all()

    async def _stats_loop(self) -> None:
        while True:
            await asyncio.sleep(self.stats_interval_sec)
            log.info(
                "netbroker stats: connections=%d active=%d frames=%d "
                "bytes_in=%d bytes_out=%d",
                self._n_connections, int(_ACTIVE.value), self._n_frames,
                self._n_bytes_in, self._n_bytes_out,
            )


# ---------------------------------------------------------------------------
# Client
# ---------------------------------------------------------------------------


class NetBrokerClient(tp.Broker):
    """Thread-safe ``tcp://`` broker client.

    One lazily-connected socket per calling thread (a consumer's long-poll
    never blocks a producer's append), strictly sequential request/response
    per socket. Any transport failure drops that thread's socket and
    surfaces as ``OSError`` (transient by ``transient_transport_error``);
    the next call reconnects — so the producer/consumer retry wrappers
    absorb broker restarts without new machinery. Typed server errors
    re-raise as :class:`TopicException` with the server's transience flag.
    """

    def __init__(self, host: str, port: int,
                 connect_timeout_sec: "float | None" = None,
                 request_timeout_sec: "float | None" = None,
                 max_frame_bytes: "int | None" = None):
        self.host = host
        self.port = int(port)
        # explicit overrides win; otherwise the PROCESS defaults are read
        # at call time, not snapshotted here — get_broker caches clients
        # forever, and a client built before configure() ran must still
        # honor the config once it has (layer startup order varies)
        self._connect_timeout_override = connect_timeout_sec
        self._request_timeout_override = request_timeout_sec
        self._max_frame_override = max_frame_bytes
        self._local = threading.local()

    @property
    def connect_timeout_sec(self) -> float:
        if self._connect_timeout_override is not None:
            return float(self._connect_timeout_override)
        with _defaults_lock:
            return float(_DEFAULTS["connect_timeout_sec"])

    @property
    def request_timeout_sec(self) -> float:
        if self._request_timeout_override is not None:
            return float(self._request_timeout_override)
        with _defaults_lock:
            return float(_DEFAULTS["request_timeout_sec"])

    @property
    def max_frame_bytes(self) -> int:
        if self._max_frame_override is not None:
            return int(self._max_frame_override)
        with _defaults_lock:
            return int(_DEFAULTS["max_frame_bytes"])

    # -- socket plumbing -------------------------------------------------------
    def _sock(self) -> socket.socket:
        s = getattr(self._local, "sock", None)
        if s is None:
            s = socket.create_connection(
                (self.host, self.port), timeout=self.connect_timeout_sec
            )
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            s.settimeout(self.request_timeout_sec)
            self._local.sock = s
            self._local.rid = 0
        return s

    def _drop(self) -> None:
        s = getattr(self._local, "sock", None)
        self._local.sock = None
        if s is not None:
            with contextlib.suppress(OSError):
                s.close()

    @staticmethod
    def _recv_exactly(s: socket.socket, n: int) -> bytes:
        buf = bytearray()
        while len(buf) < n:
            chunk = s.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("broker closed the connection")
            buf += chunk
        return bytes(buf)

    def _rpc(self, op: str, sock_timeout: "float | None" = None, **args):
        """One request/response round trip on this thread's socket."""
        payload = {"op": op, **args}
        try:
            s = self._sock()
            rid = self._local.rid = self._local.rid + 1
            payload["id"] = rid
            body = json.dumps(payload, separators=(",", ":")).encode("utf-8")
            if len(body) > self.max_frame_bytes:
                raise tp.TopicException(
                    f"request frame of {len(body)} bytes exceeds max "
                    f"{self.max_frame_bytes}"
                )
            # per-call timeout: re-read every RPC so a configure() after
            # this client was cached still takes effect
            s.settimeout(
                sock_timeout if sock_timeout is not None
                else self.request_timeout_sec
            )
            s.sendall(_LEN.pack(len(body)) + body)
            head = self._recv_exactly(s, _LEN.size)
            (length,) = _LEN.unpack(head)
            if length > self.max_frame_bytes:
                raise tp.TopicException(
                    f"response frame of {length} bytes exceeds max "
                    f"{self.max_frame_bytes}", transient=True,
                )
            resp = json.loads(self._recv_exactly(s, length))
        except (tp.TopicException, OSError):
            self._drop()
            raise
        except ValueError as e:
            # undecodable response = protocol desync: reconnect and retry
            self._drop()
            raise tp.TopicException(
                f"broker protocol error: {e}", transient=True
            ) from e
        if resp.get("id") != payload["id"]:
            if resp.get("id") is None and not resp.get("ok", True):
                # unaddressed error frame: the server refused the request
                # before it could parse an id (frame over the server cap).
                # Requests are strictly sequential per socket and the body
                # was drained server-side, so it applies to THIS request
                # and the stream is still in sync — typed raise, keep the
                # socket, honor the server's transience verdict
                raise tp.TopicException(
                    str(resp.get("error")),
                    transient=bool(resp.get("transient")),
                )
            self._drop()
            raise tp.TopicException(
                f"broker response id mismatch ({resp.get('id')!r} != "
                f"{payload['id']!r})", transient=True,
            )
        if not resp.get("ok"):
            raise tp.TopicException(
                str(resp.get("error")), transient=bool(resp.get("transient"))
            )
        return resp.get("result")

    # -- Broker interface ------------------------------------------------------
    def ping(self) -> dict:
        return self._rpc("ping")

    def create_topic(self, name: str, partitions: int = 1) -> None:
        self._rpc("create_topic", topic=name, partitions=partitions)

    def delete_topic(self, name: str) -> None:
        self._rpc("delete_topic", topic=name)

    def topic_exists(self, name: str) -> bool:
        return bool(self._rpc("topic_exists", topic=name))

    def num_partitions(self, name: str) -> int:
        return int(self._rpc("num_partitions", topic=name))

    def append(self, topic: str, key, message, headers: "dict | None" = None,
               token: "str | None" = None) -> None:
        if isinstance(message, (bytes, bytearray)):
            # JSON frames carry str payloads only — fail typed and local,
            # like the file broker, not with json.dumps's TypeError
            raise tp.TopicException(
                "bytes messages are not supported by the tcp: broker "
                "(JSON frame format); encode to str first"
            )
        args = {"topic": topic, "key": key, "message": message,
                "headers": headers}
        if token is not None:
            # idempotence token (one per logical send, TopicProducerImpl):
            # the server dedups a retried append whose response was lost
            args["token"] = token
        self._rpc("append", **args)

    def read(self, topic: str, offset: int, max_items: int = 1024,
             partition: int = 0) -> list:
        records = self._rpc("read", topic=topic, offset=offset,
                            max_items=max_items, partition=partition)
        return [
            tp.CORRUPT_RECORD if r.get("corrupt")
            else tp.KeyMessage(r.get("k"), r.get("m"), r.get("h"))
            for r in records
        ]

    def size(self, topic: str, partition: int = 0) -> int:
        return int(self._rpc("size", topic=topic, partition=partition))

    def total_size(self, topic: str) -> int:
        return int(self._rpc("total_size", topic=topic))

    def truncate(self, topic: str, before_offset: int, partition: int = 0) -> None:
        self._rpc("truncate", topic=topic, before_offset=before_offset,
                  partition=partition)

    def get_offset(self, group: str, topic: str, partition: int = 0) -> "int | None":
        result = self._rpc("get_offset", group=group, topic=topic,
                           partition=partition)
        return None if result is None else int(result)

    def set_offset(self, group: str, topic: str, offset: int, partition: int = 0) -> None:
        self._rpc("set_offset", group=group, topic=topic, offset=offset,
                  partition=partition)

    def join_group(self, group: str, topic: str, member_id: str) -> None:
        self._rpc("join_group", group=group, topic=topic, member_id=member_id)

    def leave_group(self, group: str, topic: str, member_id: str) -> None:
        self._rpc("leave_group", group=group, topic=topic, member_id=member_id)

    def group_members(self, group: str, topic: str) -> list:
        return list(self._rpc("group_members", group=group, topic=topic))

    def wait_for_data(self, topic: str, seen_total: int, timeout: float,
                      stop=None) -> None:
        """Server-side long-poll with idempotent re-subscribe: each call is
        a fresh subscription, so a reconnect (or a restarted server) costs
        nothing to re-establish. Errors degrade to a short local wait — the
        consumer's read path (which rides the retry policy) is where a dead
        broker becomes loud, never the advisory wait."""
        if stop is not None and stop.is_set():
            return
        try:
            self._rpc(
                "wait_for_data",
                # socket patience covers the server-side park plus RTT
                sock_timeout=min(timeout, _MAX_WAIT_SEC) + _WAIT_GRACE_SEC,
                topic=topic, seen_total=seen_total, timeout=timeout,
            )
        except (tp.TopicException, OSError):
            log.debug("tcp wait_for_data degraded to local wait", exc_info=True)
            # brief local wait so a down broker doesn't hot-spin the poll loop
            pause = min(max(timeout, 0.0), 0.05)
            if stop is not None:
                stop.wait(pause)
            elif pause > 0:
                time.sleep(pause)

    def wake(self, topic: str) -> None:
        try:
            self._rpc("wake", topic=topic)
        except (tp.TopicException, OSError):
            log.debug("tcp wake failed (best-effort)", exc_info=True)

    def server_metrics(self) -> str:
        """The server process's Prometheus text exposition, over the wire
        (the ``/metrics``-equivalent for a broker with no HTTP surface)."""
        return str(self._rpc("metrics")["text"])

    def close(self) -> None:
        """Drop this THREAD's socket (others close lazily on next error)."""
        self._drop()


def client_from_url(url: str) -> NetBrokerClient:
    """``tcp://host:port`` -> client (get_broker's tcp hook)."""
    rest = url[len("tcp://"):]
    host, sep, port_s = rest.rpartition(":")
    if not sep or not host or not port_s.isdigit():
        raise tp.TopicException(f"bad tcp broker url: {url} "
                                "(expected tcp://host:port)")
    return NetBrokerClient(host, int(port_s))
