"""Pallas TPU kernels for the framework's hot ops.

``kmeans_assign_accumulate`` fuses one full Lloyd-sweep accumulation —
squared-distance evaluation, nearest-center argmin, and weighted
sum/count/cost accumulation — into a single pass over point tiles. The
unfused XLA formulation (models/kmeans/train.py lloyd step) materializes the
(N, k) distance matrix and a second (N, k) one-hot indicator in HBM between
ops; here both live only tile-at-a-time in VMEM:

  grid = point tiles; per step:  d² tile = |p|² − 2 p·Cᵀ + |c|²   (MXU)
                                 indicator = (d² == row-min)       (VPU)
                                 sums   += indicatorᵀ · p          (MXU)
                                 counts += Σ indicator, cost += Σ min d²

Outputs revisit the same block every grid step (constant index map), the
standard Pallas accumulation pattern: initialized at step 0 with ``pl.when``,
accumulated thereafter. Off-TPU callers run the same kernel under
``interpret=True`` (that is how the test suite exercises it on CPU).

Tile sizes honor the f32 (8, 128) VMEM tiling: points tiles are
(TILE_N, D_pad) with D and K padded to lane multiples by the wrapper.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

TILE_N = 512
_LANE = 128
# coordinate pushing padded centers beyond any real distance (squares to
# ~f32-max without overflowing the distance expansion)
FAR_AWAY = 3.4e38 ** 0.5


def _pad_dim(n: int, multiple: int) -> int:
    return ((n + multiple - 1) // multiple) * multiple


def _kernel(points_ref, weights_ref, centers_ref, sums_ref, counts_ref, cost_ref):
    step = pl.program_id(0)

    @pl.when(step == 0)
    def _():
        sums_ref[:] = jnp.zeros_like(sums_ref)
        counts_ref[:] = jnp.zeros_like(counts_ref)
        cost_ref[:] = jnp.zeros_like(cost_ref)

    p = points_ref[:]  # (T, D)
    w = weights_ref[:]  # (T, 1); 0 marks padding rows
    c = centers_ref[:]  # (K, D)

    # squared distances, one MXU matmul per tile
    p_sq = jnp.sum(p * p, axis=1, keepdims=True)  # (T, 1)
    c_sq = jnp.sum(c * c, axis=1)[None, :]  # (1, K)
    cross = jnp.dot(p, c.T, preferred_element_type=jnp.float32)  # (T, K)
    d2 = jnp.maximum(p_sq - 2.0 * cross + c_sq, 0.0)

    # nearest center as a one-hot indicator without host round trips;
    # ties broken toward the lowest index like argmin
    min_d2 = jnp.min(d2, axis=1, keepdims=True)  # (T, 1)
    is_min = (d2 <= min_d2).astype(jnp.float32)
    k_ids = jax.lax.broadcasted_iota(jnp.int32, d2.shape, dimension=1)
    first_min = jnp.min(
        jnp.where(is_min > 0, k_ids, jnp.iinfo(jnp.int32).max), axis=1, keepdims=True
    )
    indicator = (k_ids == first_min).astype(jnp.float32) * w  # (T, K)

    sums_ref[:] += jnp.dot(indicator.T, p, preferred_element_type=jnp.float32)
    counts_ref[:] += jnp.sum(indicator, axis=0, keepdims=True)
    cost_ref[:] += jnp.sum(min_d2 * w, axis=0, keepdims=True)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _call(points, weights, centers, *, interpret: bool):
    n_pad, d_pad = points.shape
    k_pad = centers.shape[0]
    grid = (n_pad // TILE_N,)
    return pl.pallas_call(
        _kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((TILE_N, d_pad), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((TILE_N, 1), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((k_pad, d_pad), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((k_pad, d_pad), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, k_pad), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((k_pad, d_pad), jnp.float32),
            jax.ShapeDtypeStruct((1, k_pad), jnp.float32),
            jax.ShapeDtypeStruct((1, 1), jnp.float32),
        ],
        interpret=interpret,
    )(points, weights, centers)


def kmeans_assign_accumulate(
    points, weights, centers, *, interpret: "bool | None" = None
):
    """Fused Lloyd accumulation.

    Args: points (N, D) f32, weights (N,) f32 (0 = padding), centers (K, D).
    Returns (sums (K, D), counts (K,), cost scalar) as jax arrays.
    """
    points = jnp.asarray(points, dtype=jnp.float32)
    weights = jnp.asarray(weights, dtype=jnp.float32)
    centers = jnp.asarray(centers, dtype=jnp.float32)
    n, d = points.shape
    k = centers.shape[0]
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    n_pad = _pad_dim(max(n, 1), TILE_N)
    d_pad = _pad_dim(d, _LANE)
    k_pad = _pad_dim(k, 8)
    pts = jnp.zeros((n_pad, d_pad), jnp.float32).at[:n, :d].set(points)
    # padding centers sit at +inf distance: give them huge coordinates is
    # wrong (inf*0 NaN); instead pad with zeros and mask padded-k columns by
    # adding a large constant to their distances via c_sq — achieved by
    # placing padded centers far away on an unused axis
    ctr = jnp.full((k_pad, d_pad), 0.0, jnp.float32).at[:k, :d].set(centers)
    if k_pad > k:
        ctr = ctr.at[k:, 0].set(FAR_AWAY)
    wts = jnp.zeros((n_pad, 1), jnp.float32).at[:n, 0].set(weights)

    sums, counts, cost = _call(pts, wts, ctr, interpret=bool(interpret))
    return sums[:k, :d], counts[0, :k], cost[0, 0]
