"""Pallas TPU kernels for the framework's hot ops.

``spd_solve_batched`` solves many small SPD systems (the per-row normal
equations of ALS — reference hot spot ALSUpdate.java:141-152) by Gauss-Jordan
elimination with the whole batch tile VMEM-resident. XLA's batched
``cholesky`` + ``cho_solve`` on TPU lower to ~3·k sequential steps that each
stream the full (B, k, k) operand through HBM — measured 5.8 s for the
1M-user half-iteration at k=50, ~47× the Gramian accumulation it follows.
Here the k elimination steps run against VMEM, so HBM sees one read of the
Gramians and one write of the solutions:

  grid = batch tiles; per step:  load A (T, k, k), b (T, k) into VMEM
                                 k × {pivot-normalize, rank-1 eliminate} (VPU)
                                 store x (T, k)

No pivoting: operands are regularized SPD (diagonal shift λ·n ≥ λ), for
which diagonal pivots are bounded away from zero.

``gather_gramian_accumulate`` fuses the ALS trainer's entire Gramian
accumulation — the opposite-factor gather, the per-slot (k, k) Gramian/RHS
contraction, and the slot→row merge — into one pass over the slotted COO
(train._solve_block). The XLA formulation materializes the (Sc, T, k)
``y[cs]`` gather in HBM, streams it back for the einsum, writes the
(Sc, k, k) per-slot Gramians, and streams THOSE back through segment_sum —
three HBM round-trips per scan chunk while the MXU idles (measured MFU
0.15%: the loop is gather-bandwidth-bound, and bf16 inputs buy only 17%).
Here each factor row crosses HBM exactly once:

  grid = slots; per step:  DMA-gather the slot's T factor rows → VMEM
                           (rows are column-sorted within the slot, so the
                           gather walks HBM in address order; ring of
                           ``_GG_BUFS`` in-flight copies)
                           Gramian (k,T)·(T,k) + RHS (1,T)·(T,k)   (MXU)
                           accumulate into the slot's OWNER ROW's
                           (1, k, k)/(1, k) output block in VMEM

Slots arrive row-sorted (the pack guarantees it), so the per-row output
block — selected by a scalar-prefetched ``srow`` index map — is revisited
across every slot of a row and flushed to HBM once per row, replacing the
whole segment-sum pass. Rows with no slots keep the donated zero input
(``input_output_aliases``), which also makes never-visited blocks
deterministic under interpret mode.

``kmeans_assign_accumulate`` fuses one full Lloyd-sweep accumulation —
squared-distance evaluation, nearest-center argmin, and weighted
sum/count/cost accumulation — into a single pass over point tiles. The
unfused XLA formulation (models/kmeans/train.py lloyd step) materializes the
(N, k) distance matrix and a second (N, k) one-hot indicator in HBM between
ops; here both live only tile-at-a-time in VMEM:

  grid = point tiles; per step:  d² tile = |p|² − 2 p·Cᵀ + |c|²   (MXU)
                                 indicator = (d² == row-min)       (VPU)
                                 sums   += indicatorᵀ · p          (MXU)
                                 counts += Σ indicator, cost += Σ min d²

Outputs revisit the same block every grid step (constant index map), the
standard Pallas accumulation pattern: initialized at step 0 with ``pl.when``,
accumulated thereafter. Off-TPU callers run the same kernel under
``interpret=True`` (that is how the test suite exercises it on CPU).

Tile sizes honor the f32 (8, 128) VMEM tiling: points tiles are
(TILE_N, D_pad) with D and K padded to lane multiples by the wrapper.
"""

from __future__ import annotations

import functools
import logging

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

log = logging.getLogger(__name__)

TILE_N = 512
_LANE = 128
# coordinate pushing padded centers beyond any real distance (squares to
# ~f32-max without overflowing the distance expansion)
FAR_AWAY = 3.4e38 ** 0.5


def _pad_dim(n: int, multiple: int) -> int:
    return ((n + multiple - 1) // multiple) * multiple


def _spd_solve_kernel(a_ref, b_ref, x_ref, aug_ref):
    k = a_ref.shape[-1]
    aug_ref[:, :, :k] = a_ref[:]
    aug_ref[:, :, k:] = b_ref[:][..., None]
    row_ids = jax.lax.broadcasted_iota(jnp.int32, (1, k, 1), 1)
    lane_ids = jax.lax.broadcasted_iota(jnp.int32, (1, 1, k + 1), 2)

    def step(j, carry):
        # The pivot row comes out as a cheap sublane-dynamic ref load
        # (dynamic_slice on VALUES has no Mosaic lowering; ref indexing
        # does); pivot and fac are single masked lane reductions. The whole
        # elimination step is then ONE fused pass over aug: subtracting
        # (fac − e_j)⊗piv_row eliminates column j in every row AND lands row
        # j exactly on the normalized pivot row — no separate row-write.
        aug = aug_ref[:]
        row_j = aug_ref[:, pl.ds(j, 1), :]  # (T, 1, k+1)
        is_lane_j = lane_ids == j
        pivot = jnp.sum(jnp.where(is_lane_j, row_j, 0.0), axis=2,
                        keepdims=True)  # (T, 1, 1)
        piv_row = row_j / pivot
        fac = jnp.sum(jnp.where(is_lane_j, aug, 0.0), axis=2,
                      keepdims=True)  # (T, k, 1)
        fac = fac - (row_ids == j).astype(jnp.float32)
        aug_ref[:] = aug - fac * piv_row
        return carry

    jax.lax.fori_loop(0, k, step, 0)
    x_ref[:] = aug_ref[:, :, k]


@functools.partial(jax.jit, static_argnames=("tile_b", "interpret"))
def _spd_solve_call(a, b, *, tile_b: int, interpret: bool):
    b_pad, k = b.shape
    grid = (b_pad // tile_b,)
    return pl.pallas_call(
        _spd_solve_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((tile_b, k, k), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tile_b, k), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((tile_b, k), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((b_pad, k), jnp.float32),
        scratch_shapes=[pltpu.VMEM((tile_b, k, k + 1), jnp.float32)],
        interpret=interpret,
    )(a, b)


# Batch-tile sizing for the SPD solve: the largest single VMEM buffer is
# the augmented scratch (tile_b, k, k+1) — its k+1 lanes pad to the NEXT
# 128 multiple (at k=128 that is 256, not 128) — and the scoped-VMEM stack
# limit is 16 MB, so budget ~3.5 MB for that largest buffer. The budget and
# cap below are pinned against the static kernel model's padded-byte math
# (tools/analyze/kernelmodel.py + oryx.analyze.kernel.scoped-budget-bytes)
# by tests/test_kernel_differential.py: drift in either direction fails
# tier-1.
_SPD_SCOPED_BUDGET_BYTES = (7 << 17) * 4
_SPD_MAX_TILE = 256


def spd_tile_b(k: int) -> int:
    """The batch-tile height the SPD kernel runs at for ``k`` features: the
    largest multiple of 8 (≤ ``_SPD_MAX_TILE``) whose augmented scratch
    tile_b × pad8(k) × pad128(k+1) × 4 B fits the scoped-VMEM budget.
    Below 8 the kernel does not fit and callers fall back to cholesky."""
    k_padded = _pad_dim(k, 8) * _pad_dim(k + 1, _LANE)
    return min(_SPD_MAX_TILE,
               (_SPD_SCOPED_BUDGET_BYTES // (4 * max(1, k_padded))) & ~7)


def spd_solve_batched(a, b, *, interpret: "bool | None" = None):
    """Solve ``a[i] @ x[i] = b[i]`` for a batch of SPD k×k systems.

    Args: a (B, k, k) f32 regularized-SPD, b (B, k) f32.
    Returns x (B, k) f32. Padding batch rows (if any) are solved against
    identity so no NaN escapes the pad region.
    """
    a = jnp.asarray(a, dtype=jnp.float32)
    b = jnp.asarray(b, dtype=jnp.float32)
    n, k = b.shape
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    tile_b = spd_tile_b(k)
    if tile_b < 8:
        # k so large (~>=300 features with this budget) that even an 8-row
        # tile risks overflowing the scoped-VMEM stack: fall back to XLA's
        # cholesky rather than fail to compile — and say so, because the
        # performance difference is large
        log.info(
            "spd_solve_batched: k=%d exceeds the VMEM tile budget; using "
            "the XLA cholesky fallback", k,
        )
        chol = jax.scipy.linalg.cholesky(a, lower=True)
        return jax.scipy.linalg.cho_solve((chol, True), b[..., None])[..., 0]
    n_pad = _pad_dim(max(n, 1), tile_b)
    if n_pad != n:
        eye = jnp.broadcast_to(jnp.eye(k, dtype=jnp.float32),
                               (n_pad - n, k, k))
        a = jnp.concatenate([a, eye], axis=0)
        b = jnp.concatenate([b, jnp.zeros((n_pad - n, k), jnp.float32)],
                            axis=0)
    x = _spd_solve_call(a, b, tile_b=tile_b, interpret=bool(interpret))
    return x[:n]


# in-flight DMA ring depth for the per-slot factor-row gather: deep enough
# to hide one row's HBM latency behind the previous rows' copies, shallow
# enough that the semaphore array stays trivially within hardware limits
_GG_BUFS = 4
# The pack's slot width T is a power of two in [8, 512] (train.py
# _auto_slot_width) — the kernel's resident budget is evaluated at the cap.
_GG_SLOT_WIDTH_MAX = 512
# Features past this would push the kernel's resident VMEM state — the
# double-buffered (1, k, k)/(1, k) accumulator blocks, the (T, k) gather
# scratch, and the (1, T) weight blocks — past the resident-state budget
# (oryx.analyze.kernel.resident-budget-bytes, 1.5 MB); callers fall back to
# the einsum formulation (same numerics, more HBM traffic). The value is
# the max k whose padded footprint at T = _GG_SLOT_WIDTH_MAX fits that
# budget, pinned against the static kernel model by
# tests/test_kernel_differential.py so the constant can never silently
# drift from the kernel it guards.
_GG_MAX_FEATURES = 256


def gather_gramian_supported(features: int) -> bool:
    """Whether the fused gather-Gramian kernel fits its VMEM budget."""
    return features <= _GG_MAX_FEATURES


def _make_gather_gramian_kernel(t: int, k: int):
    def kernel(srow_ref, scols_ref, slens_ref, w_ref, coef_ref, y_ref,
               a0_ref, b0_ref, a_ref, b_ref, yg, sems):
        i = pl.program_id(0)
        row = srow_ref[i]
        prev_row = srow_ref[jnp.maximum(i - 1, 0)]

        # first slot of a new output row: the (1, k, k)/(1, k) blocks just
        # rotated in (their VMEM content is undefined) — zero before the
        # first accumulation. Slots are row-sorted, so a row's block stays
        # resident for all of its slots and flushes to HBM exactly once.
        @pl.when(jnp.logical_or(i == 0, prev_row != row))
        def _():
            a_ref[:] = jnp.zeros_like(a_ref)
            b_ref[:] = jnp.zeros_like(b_ref)

        ls = slens_ref[0, 0]

        # pad slots (no valid entries) skip the gather AND the matmuls:
        # their owner is the spill row, initialized above and sliced off by
        # the caller — issuing T DMAs of row 0 for them would only burn
        # bandwidth
        @pl.when(ls > 0)
        def _():
            def dma(tt):
                # one factor row per copy; within a slot the column indices
                # are ascending (pack sorts by (row, col)), so consecutive
                # copies walk y in HBM address order
                return pltpu.make_async_copy(
                    y_ref.at[scols_ref[0, tt]], yg.at[tt],
                    sems.at[tt % _GG_BUFS],
                )

            for tt in range(min(_GG_BUFS, t)):
                dma(tt).start()

            def body(tt, carry):
                # wait BEFORE reusing the slot's semaphore: copy tt+BUFS
                # signals sems[tt % BUFS] too, and a counting semaphore
                # can't tell whose bytes released the wait — issuing it
                # first would let a faster tt+BUFS copy satisfy this wait
                # while row tt is still in flight
                dma(tt).wait()

                @pl.when(tt + _GG_BUFS < t)
                def _():
                    dma(tt + _GG_BUFS).start()

                return carry

            jax.lax.fori_loop(0, t, body, 0, unroll=True)

            ygv = yg[:]  # (T, k), y's dtype (bf16 = MXU-native inputs)
            cd = ygv.dtype
            # per-entry weights arrive precomputed (confidence/mask algebra
            # is cheap VPU work best left to XLA); cast to the gather dtype
            # so bf16 inputs hit the MXU's bf16×bf16→f32 path like the
            # einsum formulation does
            wcol = w_ref[:].reshape(t, 1).astype(cd)
            ga = jax.lax.dot_general(
                ygv * wcol, ygv, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )  # (k, k): sum_t w_t · y_t ⊗ y_t
            gb = jnp.dot(coef_ref[:].astype(cd), ygv,
                         preferred_element_type=jnp.float32)  # (1, k)
            a_ref[:] = a_ref[:] + ga[None]
            b_ref[:] = b_ref[:] + gb

    return kernel


def gather_gramian_accumulate(y, srow, scols, w, coef, slens, *, block: int,
                              interpret: bool):
    """Fused gather → per-slot Gramian → per-row accumulate for one block.

    Args:
      y: (R, k) opposite-side factors (f32 or bf16), HBM-resident.
      srow: (S,) int32 block-local owner row per slot, SORTED ascending,
        pad = ``block`` (the spill row).
      scols: (S, T) int32 gather indices into ``y`` (column-ascending
        within each slot).
      w / coef: (S, T) f32 per-entry Gramian / RHS weights, zero on padding
        entries (the mask and confidence algebra are applied by the caller).
      slens: (S,) int32 valid entries per slot (0 = pad slot).
      block: rows per block; outputs carry the extra spill row.

    Returns (big_a (block+1, k, k) f32, big_b (block+1, k) f32). Rows with
    no slots return exact zeros (donated zero inputs).
    """
    s, t = scols.shape
    k = y.shape[1]
    a0 = jnp.zeros((block + 1, k, k), jnp.float32)
    b0 = jnp.zeros((block + 1, k), jnp.float32)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,  # srow drives the output index maps
        grid=(s,),
        in_specs=[
            # gather indices + lengths are scalars (DMA addresses / loop
            # bounds): SMEM, one slot per grid step
            pl.BlockSpec((1, t), lambda i, sr: (i, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((1, 1), lambda i, sr: (i, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((1, t), lambda i, sr: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, t), lambda i, sr: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.ANY),  # y stays in HBM
            pl.BlockSpec(memory_space=pltpu.ANY),  # big_a zero donor
            pl.BlockSpec(memory_space=pltpu.ANY),  # big_b zero donor
        ],
        out_specs=[
            pl.BlockSpec((1, k, k), lambda i, sr: (sr[i], 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, k), lambda i, sr: (sr[i], 0),
                         memory_space=pltpu.VMEM),
        ],
        scratch_shapes=[
            pltpu.VMEM((t, k), y.dtype),  # gathered factor rows
            pltpu.SemaphoreType.DMA((_GG_BUFS,)),
        ],
    )
    return pl.pallas_call(
        _make_gather_gramian_kernel(t, k),
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((block + 1, k, k), jnp.float32),
            jax.ShapeDtypeStruct((block + 1, k), jnp.float32),
        ],
        # zero donors alias the outputs: rows no slot ever visits keep
        # exact zeros — deterministic on hardware AND under interpret
        input_output_aliases={6: 0, 7: 1},
        interpret=interpret,
    )(srow, scols, slens.reshape(s, 1), w, coef, y, a0, b0)


def _kernel(points_ref, weights_ref, centers_ref, sums_ref, counts_ref, cost_ref):
    step = pl.program_id(0)

    @pl.when(step == 0)
    def _():
        sums_ref[:] = jnp.zeros_like(sums_ref)
        counts_ref[:] = jnp.zeros_like(counts_ref)
        cost_ref[:] = jnp.zeros_like(cost_ref)

    p = points_ref[:]  # (T, D)
    w = weights_ref[:]  # (T, 1); 0 marks padding rows
    c = centers_ref[:]  # (K, D)

    # squared distances, one MXU matmul per tile
    p_sq = jnp.sum(p * p, axis=1, keepdims=True)  # (T, 1)
    c_sq = jnp.sum(c * c, axis=1)[None, :]  # (1, K)
    cross = jnp.dot(p, c.T, preferred_element_type=jnp.float32)  # (T, K)
    d2 = jnp.maximum(p_sq - 2.0 * cross + c_sq, 0.0)

    # nearest center as a one-hot indicator without host round trips;
    # ties broken toward the lowest index like argmin
    min_d2 = jnp.min(d2, axis=1, keepdims=True)  # (T, 1)
    is_min = (d2 <= min_d2).astype(jnp.float32)
    k_ids = jax.lax.broadcasted_iota(jnp.int32, d2.shape, dimension=1)
    first_min = jnp.min(
        jnp.where(is_min > 0, k_ids, jnp.iinfo(jnp.int32).max), axis=1, keepdims=True
    )
    indicator = (k_ids == first_min).astype(jnp.float32) * w  # (T, K)

    sums_ref[:] += jnp.dot(indicator.T, p, preferred_element_type=jnp.float32)
    counts_ref[:] += jnp.sum(indicator, axis=0, keepdims=True)
    cost_ref[:] += jnp.sum(min_d2 * w, axis=0, keepdims=True)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _call(points, weights, centers, *, interpret: bool):
    n_pad, d_pad = points.shape
    k_pad = centers.shape[0]
    grid = (n_pad // TILE_N,)
    return pl.pallas_call(
        _kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((TILE_N, d_pad), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((TILE_N, 1), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((k_pad, d_pad), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((k_pad, d_pad), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, k_pad), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((k_pad, d_pad), jnp.float32),
            jax.ShapeDtypeStruct((1, k_pad), jnp.float32),
            jax.ShapeDtypeStruct((1, 1), jnp.float32),
        ],
        interpret=interpret,
    )(points, weights, centers)


def kmeans_assign_accumulate(
    points, weights, centers, *, interpret: "bool | None" = None
):
    """Fused Lloyd accumulation.

    Args: points (N, D) f32, weights (N,) f32 (0 = padding), centers (K, D).
    Returns (sums (K, D), counts (K,), cost scalar) as jax arrays.
    """
    points = jnp.asarray(points, dtype=jnp.float32)
    weights = jnp.asarray(weights, dtype=jnp.float32)
    centers = jnp.asarray(centers, dtype=jnp.float32)
    n, d = points.shape
    k = centers.shape[0]
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    n_pad = _pad_dim(max(n, 1), TILE_N)
    d_pad = _pad_dim(d, _LANE)
    k_pad = _pad_dim(k, 8)
    pts = jnp.zeros((n_pad, d_pad), jnp.float32).at[:n, :d].set(points)
    # padding centers sit at +inf distance: give them huge coordinates is
    # wrong (inf*0 NaN); instead pad with zeros and mask padded-k columns by
    # adding a large constant to their distances via c_sq — achieved by
    # placing padded centers far away on an unused axis
    ctr = jnp.full((k_pad, d_pad), 0.0, jnp.float32).at[:k, :d].set(centers)
    if k_pad > k:
        ctr = ctr.at[k:, 0].set(FAR_AWAY)
    wts = jnp.zeros((n_pad, 1), jnp.float32).at[:n, 0].set(weights)

    sums, counts, cost = _call(pts, wts, ctr, interpret=bool(interpret))
    return sums[:k, :d], counts[0, :k], cost[0, 0]
