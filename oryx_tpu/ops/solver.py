"""Linear-system solving over Gramians, with singularity detection.

Equivalent of the reference's LinearSystemSolver / Solver / SolverCache
(framework/oryx-common/.../math/LinearSystemSolver.java:39-81, Solver.java:33-51;
app/oryx-app-common/.../als/SolverCache.java:36-120).

The reference RRQR-decomposes the packed Gramian on the driver and throws
``SingularMatrixSolverException`` with the apparent rank when the matrix is
singular past threshold 1e-5. Here the k×k Gramian (k ≤ a few hundred) is
SVD-factorized in float64 on host — it is tiny, and host float64 keeps the
rank test exact; the large batched solves on the ALS training path use their
own on-device f32 Cholesky kernels (oryx_tpu/models/als). ``Solver.solve``
maps one RHS vector or a batch of stacked RHS rows in a single matmul.

``SolverCache`` keeps the reference's single-flight async-recompute semantics:
a dirty flag set on writes, one background recompute at a time, and a blocking
first ``get`` gated on a latch.
"""

from __future__ import annotations

import logging
import threading
from typing import Callable



import numpy as np

log = logging.getLogger(__name__)

SINGULARITY_THRESHOLD = 1.0e-5  # LinearSystemSolver.java:34 (SINGULARITY_ERROR_TOLERANCE)


class SingularMatrixSolverException(Exception):
    """Carries apparent rank, like the reference's exception
    (math/SingularMatrixSolverException.java)."""

    def __init__(self, apparent_rank: int, message: str = ""):
        super().__init__(message or f"singular matrix; apparent rank {apparent_rank}")
        self.apparent_rank = apparent_rank


class Solver:
    """Wraps a factorized Gramian; solve() maps RHS → solution
    (math/Solver.java:33-51)."""

    def __init__(self, u: np.ndarray, s: np.ndarray, vt: np.ndarray):
        self._u = u
        self._s_inv = np.divide(1.0, s, out=np.zeros_like(s), where=s > 0)
        self._vt = vt

    def solve_d_to_d(self, b) -> np.ndarray:
        return np.asarray(self.solve(b), dtype=np.float64)

    def solve_f_to_f(self, b) -> np.ndarray:
        return np.asarray(self.solve(b), dtype=np.float32)

    def solve(self, b) -> np.ndarray:
        """Solve A x = b for one RHS vector or a batch of stacked RHS rows:
        x = V diag(1/s) U^T b."""
        b = np.asarray(b, dtype=np.float64)
        return (b @ self._u * self._s_inv) @ self._vt


def get_solver(gramian) -> Solver:
    """Factorize a symmetric k×k Gramian; raise SingularMatrixSolverException
    on rank deficiency (LinearSystemSolver.getSolver, :39-81)."""
    m = np.asarray(gramian, dtype=np.float64)
    if m.ndim != 2 or m.shape[0] != m.shape[1]:
        raise ValueError(f"not square: {m.shape}")
    u, s, vt = np.linalg.svd(m, full_matrices=False)
    max_s = float(s[0]) if s.size else 0.0
    if max_s <= 0.0:
        raise SingularMatrixSolverException(0)
    apparent_rank = int(np.sum(s > SINGULARITY_THRESHOLD * max_s))
    if apparent_rank < m.shape[0]:
        raise SingularMatrixSolverException(
            apparent_rank,
            f"apparent rank {apparent_rank} < dimension {m.shape[0]}; "
            "more data, or better data, is needed",
        )
    return Solver(u, s, vt)


class SolverCache:
    """Dirty-flag + single-flight async recompute of the Gramian solver
    (app/oryx-app-common/.../als/SolverCache.java:36-120).

    ``compute_fn`` returns the current Gramian (or None if no vectors yet).
    ``set_dirty`` is called whenever underlying vectors change; ``compute_now``
    triggers an async recompute if dirty; ``get(blocking)`` returns the latest
    solver, blocking first use until one exists.
    """

    def __init__(self, compute_fn: "Callable[[], np.ndarray | None]"):
        self._compute_fn = compute_fn
        self._solver: Solver | None = None
        self._dirty = True
        self._in_flight = False
        self._lock = threading.Lock()
        self._first_ready = threading.Event()

    def set_dirty(self) -> None:
        with self._lock:
            self._dirty = True

    def compute_now(self) -> None:
        self._maybe_launch(wait=False)

    def _maybe_launch(self, wait: bool) -> None:
        with self._lock:
            if not self._dirty or self._in_flight:
                launch = False
            else:
                self._dirty = False
                self._in_flight = True
                launch = True
        if not launch:
            return
        if wait:
            self._recompute()
        else:
            threading.Thread(target=self._recompute, name="OryxSolverCache", daemon=True).start()

    def _recompute(self) -> None:
        try:
            gramian = self._compute_fn()
            if gramian is not None:
                try:
                    solver = get_solver(gramian)
                except SingularMatrixSolverException as e:
                    log.warning("Gramian is singular (%s); keeping previous solver", e)
                    with self._lock:
                        solver = self._solver
                with self._lock:
                    self._solver = solver
        finally:
            # Unblock first-get waiters even on no-data/failure, like the
            # reference's finally { solverInitialized.countDown(); }
            self._first_ready.set()
            with self._lock:
                self._in_flight = False

    def get(self, blocking: bool = True) -> Solver | None:
        with self._lock:
            solver = self._solver
            dirty = self._dirty
        if solver is None:
            if not blocking:
                self._maybe_launch(wait=False)
                return None
            self._maybe_launch(wait=True)
            with self._lock:
                solver = self._solver
            if solver is None:
                # another thread may be computing; wait for first result
                self._first_ready.wait(timeout=60)
                with self._lock:
                    solver = self._solver
            return solver
        if dirty:
            self._maybe_launch(wait=False)  # serve stale while refreshing
        return solver
