"""Vector/matrix math kernel, jnp-based.

Equivalent of the reference's VectorMath (framework/oryx-common/.../math/
VectorMath.java:38-128): dot, norm, cosine similarity, Gramian (X^T X), random
unit vectors. The reference's hot spot — the packed BLAS ``dspr`` rank-1
accumulation in ``transposeTimesSelf`` — becomes a single ``X.T @ X`` matmul so
XLA can tile it onto the MXU; callers batch rows into one array instead of
looping vectors.

Functions accept numpy or jax arrays and stay functional (no in-place state);
everything is float32 by default (the reference stores float[] factors).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def dot(x, y):
    """Dot product (VectorMath.dot, VectorMath.java:38)."""
    return jnp.dot(jnp.asarray(x), jnp.asarray(y))


def norm(x):
    """L2 norm (VectorMath.norm, VectorMath.java:49)."""
    return jnp.linalg.norm(jnp.asarray(x))


def cosine_similarity(x, y, norm_y=None):
    """Cosine similarity; optionally with precomputed ||y||
    (VectorMath.cosineSimilarity, VectorMath.java:79)."""
    x = jnp.asarray(x)
    y = jnp.asarray(y)
    ny = jnp.linalg.norm(y) if norm_y is None else norm_y
    return jnp.dot(x, y) / (jnp.linalg.norm(x) * ny)


def cosine_similarities(rows, y, norm_y=None) -> np.ndarray:
    """Cosine similarity of EVERY row of ``rows`` against ``y`` in one
    device call, returned as a host float32 array. The batched form of
    :func:`cosine_similarity` for the similarity/because endpoints: a
    per-pair loop costs one dispatch plus one blocking device→host sync
    PER ITEM (the host-device-transfer checker's per-element class), where
    this is one matvec and one transfer for the whole list."""
    rows = jnp.asarray(np.asarray(rows, dtype=np.float32))
    y = jnp.asarray(y)
    ny = jnp.linalg.norm(y) if norm_y is None else norm_y
    sims = (rows @ y) / (jnp.linalg.norm(rows, axis=1) * ny)
    return np.asarray(sims, dtype=np.float32)


@jax.jit
def _gramian(x):
    xf = x.astype(jnp.float32)
    return xf.T @ xf


def transpose_times_self(rows) -> jnp.ndarray | None:
    """Gramian X^T X of a collection/array of row vectors
    (VectorMath.transposeTimesSelf, VectorMath.java:94-110 — there a packed
    ``dspr`` loop; here one MXU matmul). Returns None for empty input, matching
    the reference's null return."""
    if rows is None:
        return None
    if not isinstance(rows, (np.ndarray, jnp.ndarray)):
        rows = list(rows)
        if not rows:
            return None
        rows = np.asarray(rows, dtype=np.float32)
    if rows.size == 0:
        return None
    if rows.ndim == 1:
        rows = rows[None, :]
    return _gramian(jnp.asarray(rows))


def random_vector_f(features: int, rng: np.random.Generator) -> np.ndarray:
    """Random unit vector (VectorMath.randomVectorF, VectorMath.java:128)."""
    v = rng.standard_normal(features).astype(np.float32)
    n = np.linalg.norm(v)
    if n == 0:
        return random_vector_f(features, rng)
    return v / n


def parse_vector(tokens) -> np.ndarray:
    """float[] from string tokens (VectorMath.parseVector)."""
    return np.asarray([float(t) for t in tokens], dtype=np.float32)


class DoubleWeightedMean:
    """Streaming weighted mean (math/DoubleWeightedMean.java). Host-side;
    used by evaluation aggregation."""

    def __init__(self):
        self._count = 0
        self._total_weight = 0.0
        self._mean = 0.0

    def increment(self, value: float, weight: float = 1.0) -> None:
        if weight <= 0:
            raise ValueError("weight must be positive")
        self._count += 1
        self._total_weight += weight
        self._mean += (weight / self._total_weight) * (value - self._mean)

    @property
    def result(self) -> float:
        return self._mean if self._count else float("nan")

    @property
    def count(self) -> int:
        return self._count

    def __repr__(self) -> str:  # pragma: no cover
        return f"DoubleWeightedMean({self.result})"
