"""Embedded HTML console served at the context root.

Equivalent of the reference's AbstractConsoleResource + per-app Console
classes (app/oryx-app-serving/.../AbstractConsoleResource.java:36-60,
als/Console.java, kmeans/Console.java, rdf/Console.java): each app family
serves a small self-contained HTML page at ``/`` for poking its endpoints
from a browser. Where the reference ships static resource files, this renders
the page from the app's endpoint table so it never drifts from the routes.
"""

from __future__ import annotations

import html

from aiohttp import web

_PAGE = """<!DOCTYPE html>
<html>
<head><title>{title}</title>
<style>
body {{ font-family: sans-serif; margin: 2em; }}
h1 {{ font-size: 1.4em; }}
table {{ border-collapse: collapse; }}
td, th {{ border: 1px solid #ccc; padding: 4px 10px; text-align: left; }}
code {{ background: #f4f4f4; padding: 1px 4px; }}
form {{ margin: 0; }}
</style></head>
<body>
<h1>{title}</h1>
<p>Model status: <a href="ready">/ready</a></p>
<table>
<tr><th>Method</th><th>Endpoint</th><th>Description</th><th>Try</th></tr>
{rows}
</table>
</body></html>
"""

_ROW = (
    "<tr><td>{method}</td><td><code>{path}</code></td><td>{doc}</td>"
    "<td>{form}</td></tr>"
)


def make_console(title: str, endpoints: "list[tuple[str, str, str]]"):
    """Build the `/` handler from (method, path, description) rows."""
    rows = []
    for method, path, doc in endpoints:
        form = ""
        if method == "GET" and "{" not in path:
            form = f'<a href="{html.escape(path.lstrip("/"))}">open</a>'
        rows.append(
            _ROW.format(
                method=html.escape(method),
                path=html.escape(path),
                doc=html.escape(doc),
                form=form,
            )
        )
    page = _PAGE.format(title=html.escape(title), rows="\n".join(rows))

    async def console(request: web.Request) -> web.Response:
        return web.Response(text=page, content_type="text/html")

    return console


def register_console(
    app: web.Application, title: str, endpoints: "list[tuple[str, str, str]]"
) -> None:
    app.router.add_get("/", make_console(title, endpoints))
