"""Serving resource plumbing: readiness gating, input sending, rendering.

Equivalent of the reference's AbstractOryxResource + CSVMessageBodyWriter +
OryxExceptionMapper (app/oryx-app-serving/.../AbstractOryxResource.java:58-182,
framework/oryx-lambda-serving/.../CSVMessageBodyWriter.java:33-41): handlers
pull the model manager and input producer out of the app context, gate on
``min-model-load-fraction`` (503 until loaded), send input keyed by a hex hash
of the message, and render responses as JSON or CSV by Accept header.
"""

from __future__ import annotations

import asyncio
import gzip
import hashlib
import io
import json
import zipfile
from typing import Any

from aiohttp import web

from oryx_tpu.api.serving import OryxServingException
from oryx_tpu.common import resilience
from oryx_tpu.common import spans

log = spans.get_logger(__name__)

MANAGER_KEY = "oryx.model-manager"
INPUT_PRODUCER_KEY = "oryx.input-producer"
CONFIG_KEY = "oryx.config"
COALESCER_KEY = "oryx.top-n-coalescer"


def get_manager(request: web.Request):
    return request.app[MANAGER_KEY]


def get_serving_model(request: web.Request):
    """Readiness gate (AbstractOryxResource.getServingModel:75-97)."""
    manager = get_manager(request)
    config = request.app[CONFIG_KEY]
    min_fraction = config.get_float("oryx.serving.min-model-load-fraction")
    model = manager.get_model()
    if model is not None and model.get_fraction_loaded() >= min_fraction:
        return model
    raise OryxServingException(503, "model not yet available; try again soon")


def send_input(request: web.Request, message: str) -> None:
    """Write to the input topic, key = hex hash of message
    (AbstractOryxResource.sendInput:65-69).

    Synchronous — on ``file:`` brokers the send does file I/O under the
    broker lock, so async handlers must use :func:`send_input_async` /
    :func:`send_input_many` instead of calling this on the event loop
    (oryx-analyze: blocking-async)."""
    manager = get_manager(request)
    if manager.is_read_only():
        raise OryxServingException(403, "serving layer is read-only")
    producer = request.app.get(INPUT_PRODUCER_KEY)
    if producer is None:
        raise OryxServingException(503, "no input producer")
    key = format(int.from_bytes(hashlib.md5(message.encode()).digest()[:4], "big"), "08x")
    producer.send(key, message)


async def send_input_async(request: web.Request, message: str) -> None:
    """send_input off the event loop (one executor hop per message).

    ``asyncio.to_thread`` — NOT ``run_in_executor``, which drops contextvars
    on this Python — so the producer in the worker thread still sees the
    request's ingress span and stamps the message's traceparent header:
    span continuity across the executor."""
    await asyncio.to_thread(send_input, request, message)


async def send_input_many(request: web.Request, messages: "list[str]") -> None:
    """Bulk send in ONE executor hop — /ingest-sized bodies would otherwise
    pay a loop→executor round-trip per line."""

    def send_all() -> None:
        for m in messages:
            send_input(request, m)

    await asyncio.to_thread(send_all)


def check(condition: bool, message: str, status: int = 400) -> None:
    """(AbstractOryxResource.check:134-154)"""
    if not condition:
        raise OryxServingException(status, message)


def check_exists(value, what: str) -> Any:
    if value is None:
        raise OryxServingException(404, f"{what} not found")
    return value


# ---------------------------------------------------------------------------
# Rendering: JSON default, CSV on Accept: text/csv
# ---------------------------------------------------------------------------


def _to_csv_row(item: Any) -> str:
    from oryx_tpu.common import textutils

    if isinstance(item, dict):
        return textutils.join_delimited(list(item.values()))
    if isinstance(item, (list, tuple)):
        return textutils.join_delimited(item)
    return str(item)


def render(request: web.Request, payload: Any, status: int = 200) -> web.Response:
    accept = request.headers.get("Accept", "")
    if "text/csv" in accept:
        if payload is None:
            body = ""
        elif isinstance(payload, (list, tuple)):
            body = "\n".join(_to_csv_row(i) for i in payload)
            if body:
                body += "\n"
        else:
            body = _to_csv_row(payload) + "\n"
        return web.Response(text=body, status=status, content_type="text/csv")
    return web.json_response(payload, status=status)


def id_value(id_: str, value: float) -> dict:
    """IDValue response type (app/serving/IDValue.java)."""
    return {"id": id_, "value": value}


def id_count(id_: str, count: int) -> dict:
    return {"id": id_, "count": count}


# ---------------------------------------------------------------------------
# Request helpers
# ---------------------------------------------------------------------------


def get_how_many_offset(request: web.Request) -> tuple[int, int]:
    how_many = int(request.query.get("howMany", "10"))
    offset = int(request.query.get("offset", "0"))
    check(how_many > 0, "howMany must be positive")
    check(offset >= 0, "offset must be non-negative")
    return how_many, offset


def get_rescorer_params(request: web.Request) -> list[str]:
    return request.query.getall("rescorerParams", [])


def split_path_list(rest: str) -> list[str]:
    """Parse multi-segment path lists like /similarity/i1/i2/i3."""
    from urllib.parse import unquote

    parts = [unquote(p) for p in rest.split("/") if p != ""]
    check(bool(parts), "path requires at least one value")
    return parts


def parse_id_value_pairs(parts: list[str]) -> list[tuple[str, float]]:
    """itemID=value path segments, value defaulting to 1
    (RecommendToAnonymous/EstimateForAnonymous semantics)."""
    out = []
    for p in parts:
        if "=" in p:
            id_, v = p.split("=", 1)
            try:
                out.append((id_, float(v)))
            except ValueError as e:
                raise OryxServingException(400, f"bad value in {p}") from e
        else:
            out.append((p, 1.0))
    return out


async def read_body_lines(request: web.Request) -> list[str]:
    """Request body → lines, handling gzip/zip and multipart form data
    (AbstractOryxResource.java:99-132,164-179)."""
    content_type = request.headers.get("Content-Type", "")
    if content_type.startswith("multipart/"):
        lines: list[str] = []
        reader = await request.multipart()
        async for part in reader:
            data = await part.read(decode=False)
            lines.extend(_decode_maybe_compressed(data, part.headers.get("Content-Type", "")))
        return lines
    data = await request.read()
    return _decode_maybe_compressed(data, content_type)


def _decode_maybe_compressed(data: bytes, content_type: str) -> list[str]:
    # sniff by magic bytes: aiohttp already transparently decompresses
    # Content-Encoding bodies, so the header alone is not trustworthy
    if data[:2] == b"\x1f\x8b":
        data = gzip.decompress(data)
    elif "zip" in content_type or data[:4] == b"PK\x03\x04":
        with zipfile.ZipFile(io.BytesIO(data)) as zf:
            chunks = [zf.read(n) for n in zf.namelist()]
        data = b"\n".join(chunks)
    text = data.decode("utf-8", errors="replace")
    return [line for line in text.splitlines() if line.strip()]


@web.middleware
async def error_middleware(request: web.Request, handler):
    """OryxServingException → HTTP status (OryxExceptionMapper). Shed
    requests (OverloadedException) additionally carry a ``Retry-After``
    hint; an expired request deadline maps to 504 with the partial trace
    id, so the operator can pull up exactly how far the request got."""
    try:
        return await handler(request)
    except OryxServingException as e:
        headers = {}
        retry_after = getattr(e, "retry_after_sec", None)
        if retry_after is not None:
            headers["Retry-After"] = str(max(1, int(retry_after)))
        accept = request.headers.get("Accept", "")
        if "text/csv" in accept:
            return web.Response(text=e.message, status=e.status,
                                content_type="text/plain", headers=headers)
        return web.json_response({"error": e.message, "status": e.status},
                                 status=e.status, headers=headers)
    except resilience.DeadlineExceeded as e:
        return web.json_response({
            "error": str(e) or "request deadline exceeded",
            "status": 504,
            # the PARTIAL trace: every span recorded before the budget ran
            # out is already in the ring, retrievable by this id
            "trace_id": spans.current_trace_id(),
        }, status=504)
    except web.HTTPException:
        raise
    except Exception as e:  # noqa: BLE001 - uniform 500 mapping
        log.exception("unhandled error in %s", request.path)
        return web.json_response({"error": str(e), "status": 500}, status=500)
