"""Serving runtime: HTTP app factory + layer lifecycle.

Equivalent of the reference's ServingLayer + ModelManagerListener +
OryxApplication (framework/oryx-lambda-serving/.../ServingLayer.java:121-337,
ModelManagerListener.java:81-225, OryxApplication.java:54-96): where the
reference embeds Tomcat and reflection-scans JAX-RS resources, this builds an
aiohttp application, imports the configured ``application-resources`` modules
and calls their ``register(app)`` hooks, wires the model-manager lifecycle
(update-topic consumer thread from ``earliest``, input producer unless
read-only), and serves with optional basic auth, TLS, and a context path.
"""

from __future__ import annotations

import asyncio
import base64
import concurrent.futures
import contextlib
import hashlib
import hmac
import importlib
import os
import re
import secrets
import ssl
import threading
import time

from aiohttp import web

from oryx_tpu.api.serving import ServingModelManager
from oryx_tpu.common import blackbox
from oryx_tpu.common import classutils
from oryx_tpu.common import compilecache
from oryx_tpu.common import faults
from oryx_tpu.common import ioutils
from oryx_tpu.common import lineage
from oryx_tpu.common import metrics as metrics_mod
from oryx_tpu.common import profiling
from oryx_tpu.common import resilience
from oryx_tpu.common import slo
from oryx_tpu.common import spans
from oryx_tpu.common import tsdb
from oryx_tpu.serving import resource as rsrc
from oryx_tpu.transport import netbroker
from oryx_tpu.transport import topic as tp
from oryx_tpu.transport.topic import (
    ConsumeDataIterator,
    TopicProducerImpl,
    get_broker,
    offset_op as tp_offset_op,
)

log = spans.get_logger(__name__)

DEFAULT_RESOURCES = ["oryx_tpu.serving.resources.common"]

_REQUESTS = metrics_mod.default_registry().counter(
    "oryx_serving_requests_total",
    "HTTP requests by route template, method, and response status",
    ("route", "method", "status"),
)
_REQUEST_LATENCY = metrics_mod.default_registry().histogram(
    "oryx_serving_request_latency_seconds",
    "End-to-end HTTP request latency by route template",
    ("route",),
)
_IN_FLIGHT = metrics_mod.default_registry().gauge(
    "oryx_serving_requests_in_flight",
    "HTTP requests currently being handled",
)
_UPDATES_CONSUMED = metrics_mod.default_registry().counter(
    "oryx_serving_updates_consumed_total",
    "Update-topic messages consumed by the serving layer",
)
_UPDATE_LAG_MESSAGES = metrics_mod.default_registry().gauge(
    "oryx_serving_update_lag_messages",
    "Update-topic messages behind the broker head (consumer lag)",
)
_UPDATE_LAG_SECONDS = metrics_mod.default_registry().gauge(
    "oryx_serving_update_lag_seconds",
    "Seconds since the update consumer last made progress; while idle on "
    "an empty topic it reports the lineage watermark's data age instead "
    "(0 when no watermark is known)",
)
_CONSUMER_RESTARTS = metrics_mod.default_registry().counter(
    "oryx_serving_consumer_restarts_total",
    "Supervised restarts of the update-consumer thread after a crash",
)

#: Healthy consumption this long refunds the consumer restart budget and
#: resets its backoff: supervisor semantics are restarts-per-unhealthy-WINDOW,
#: not per process lifetime — isolated weekly crashes must never accumulate
#: into a max-restarts give-up months later.
_CONSUMER_HEALTHY_RESET_SEC = 60.0


def _route_template(request: web.Request) -> str:
    """Matched route template (bounded label cardinality — never the raw
    path, which would mint one label set per user/item id)."""
    resource = getattr(request.match_info.route, "resource", None)
    return getattr(resource, "canonical", None) or "unmatched"


def _attach_generation(response, route: str) -> None:
    """Stamp ``x-oryx-model-generation`` on every model-backed response
    (all four app families flow through this middleware), so any served
    answer is attributable to a model generation after the fact. Probe and
    ops routes are exempt — a /readyz poll is not a model query, and must
    not count as one in the adoption timeline."""
    if slo.is_ops_route(route):
        return
    gen = lineage.tracker().note_query()
    if gen and "x-oryx-model-generation" not in response.headers:
        response.headers["x-oryx-model-generation"] = gen


@web.middleware
async def _metrics_middleware(request, handler):
    """Outermost middleware: per-route request count/latency/status plus an
    in-flight gauge, and the request's INGRESS SPAN. Counts what the client
    saw — auth 401s, mapped errors, and 404s included.

    Tracing: an incoming W3C ``traceparent`` header continues the caller's
    trace, otherwise a fresh trace is minted; the span is current for the
    whole handler (asyncio carries the contextvar; executor hops go through
    asyncio.to_thread, which copies it). The response echoes the trace via
    ``traceparent``/``x-oryx-trace-id`` so a slow client call can be pulled
    up by id from ``GET /trace``, and the request-latency histogram records
    the trace id as its bucket exemplar — a bad bucket points at a trace.

    Chaos: an armed ``serving.request`` fault schedule fires HERE (inside
    the accounting, so injected 500s land in the SLO's availability counts
    — the game-day site that drives a burn-rate alert on one replica).
    Probe/ops routes are exempt: sabotaging /readyz or /metrics would blind
    the very observability a drill exercises. The disarmed cost is one
    global read per request; latency mode runs in a worker thread so an
    injected sleep never stalls the event loop."""
    record = metrics_mod.default_registry().enabled
    tracing = spans.enabled()
    route = _route_template(request)

    async def _handle():
        # site_armed, not armed(): a drill aimed at broker.append must not
        # tax every HTTP request with the injection's executor hop
        if faults.site_armed("serving.request") and not slo.is_ops_route(route):
            await asyncio.to_thread(faults.maybe_fail, "serving.request")
        return await handler(request)

    if not record and not tracing:
        response = await _handle()
        _attach_generation(response, route)
        return response
    if record:
        _IN_FLIGHT.inc()
    t0 = time.perf_counter()
    status = 500
    trace_id = None
    try:
        with spans.span(
            f"http {request.method} {route}",
            parent=spans.parse_traceparent(
                request.headers.get(spans.TRACEPARENT)
            ),
            attributes={"route": route, "method": request.method},
        ) as sp:
            trace_id = sp.trace_id or None
            response = await _handle()
            status = response.status
            sp.set_attribute("status", status)
            if trace_id:
                response.headers[spans.TRACEPARENT] = sp.context.to_traceparent()
                response.headers["x-oryx-trace-id"] = trace_id
            _attach_generation(response, route)
            return response
    except web.HTTPException as e:
        status = e.status
        if trace_id:
            # errors are exactly the responses an operator wants to pull up
            # by id — the 404/401/4xx must carry the trace like any 200
            e.headers[spans.TRACEPARENT] = sp.context.to_traceparent()
            e.headers["x-oryx-trace-id"] = trace_id
        _attach_generation(e, route)
        raise
    except asyncio.CancelledError:
        # client disconnect/timeout cancels the handler task: no response
        # was ever produced, so counting it as 500 would fake a 5xx spike
        status = "cancelled"
        raise
    finally:
        if record:
            _IN_FLIGHT.dec()
            _REQUEST_LATENCY.labels(route).observe(
                time.perf_counter() - t0, exemplar=trace_id
            )
            _REQUESTS.labels(route, request.method, str(status)).inc()


def _lag_seconds_fn(metered_ref):
    """Scrape-time gauge callback over a WEAK iterator ref: a strong ref
    (or a bound method) would pin a closed layer's iterator/broker for the
    process lifetime and keep reporting lag for a consumer that no longer
    exists — same pattern as the ALS load-fraction gauge."""

    def fn() -> float:
        metered = metered_ref()
        if metered is None:
            return 0.0
        if metered._waiting:
            # blocked in the broker pop = healthy and idle, not WEDGED — but
            # "0 forever" also hid a stalled batch tier. With a provenance
            # watermark known, idle reports the age of the data actually
            # serving (the speed tier's stamped deltas keep it advancing
            # between batch generations); without one (no stamped model
            # yet), quiet stays 0 as before. /readyz is unaffected either
            # way: stale additionally requires messages waiting behind the
            # head, and an idle consumer has none.
            freshness = lineage.freshness_seconds()
            return freshness if freshness is not None else 0.0
        return max(0.0, time.time() - metered._last_walltime)

    return fn


def _lag_messages_fn(metered_ref):
    """Scrape-time messages-behind-head callback (weak ref, as above). The
    broker probe runs at READ time, never on the consumer hot path — and a
    WEDGED consumer still reports a live backlog, which an at-consume-time
    ``set()`` could never do (its last value froze with the consumer)."""

    def fn() -> float:
        metered = metered_ref()
        if metered is None:
            return 0.0
        try:
            # lag from the iterator's own read positions, not a consumed
            # count: a "committed" consumer starts mid-topic, so
            # total - consumed would report the whole history as backlog
            # forever on a healthy caught-up replica
            lag = metered._iterator.messages_behind(
                metered._broker.total_size(metered._topic)
            )
        except Exception:  # noqa: BLE001  # analyze: ignore[swallowed-exception] -- scrape-time lag probe is advisory; a log line per scrape would flood
            return 0.0
        return float(max(0, lag))

    return fn


class _MeteredUpdates:
    """Iterator bridge feeding consumer-lag metrics from the update-consumer
    thread: messages consumed, plus two scrape-time gauge callbacks —
    messages behind the broker head and seconds since the consumer last
    made progress (consumer start until the first message). Both evaluate
    at READ time, so they stay truthful for a wedged consumer and /readyz
    works even with the metrics kill switch off.

    ``broker`` must be the SAME instance the iterator consumes from (for
    ``file:`` brokers a fresh instance would rebuild a duplicate line index
    just to answer total_size).

    ``commit`` (optional, the ``update-resume = "committed"`` path) runs at
    the TOP of each ``__next__`` — the moment the manager asks for more is
    the proof it finished the previous message, which is exactly when
    UpdateOffsetsFn semantics say the position may be persisted. A commit
    that ran any earlier could lose a generation to a crash mid-apply."""

    def __init__(self, updates, broker, topic: str, commit=None):
        import weakref

        # the raw ConsumeDataIterator: the lag gauge reads its per-partition
        # positions (messages_behind), which stay truthful in BOTH resume
        # modes — a consumed count would misread "committed" starts
        self._iterator = updates
        # trace continuation: a consumed message bearing a traceparent header
        # is processed under a span continuing the trace minted at ingress
        # (the span closes when the manager asks for the next message)
        self._updates = iter(spans.trace_consumed(
            updates, "serving.consume_update", route="update-topic",
            attributes={"topic": topic},
        ))
        self._broker = broker
        self._topic = topic
        self._commit = commit
        self._consumed = 0
        # baseline at consumer start: "seconds since progress" must grow for
        # a consumer that wedges before its FIRST message, not read 0 forever
        self._last_walltime: float = time.time()
        # True while blocked in the broker pop: healthy-idle, not lagging
        # (plain bool, single-store/single-load atomic under the GIL)
        self._waiting: bool = False
        ref = weakref.ref(self)
        _UPDATE_LAG_SECONDS.set_function(_lag_seconds_fn(ref))
        _UPDATE_LAG_MESSAGES.set_function(_lag_messages_fn(ref))

    def __iter__(self) -> "_MeteredUpdates":
        return self

    def __next__(self):
        # offset-keyed resume: persist the position past everything already
        # processed (BEFORE the chaos hook — an injected consumer crash
        # must never un-commit finished work)
        if self._commit is not None:
            self._commit()
        # chaos hook: an armed "serving.update_consume" schedule crashes the
        # consumer HERE, through the exact path a poison update or broker
        # fault would take (the supervised restart loop absorbs it)
        faults.maybe_fail("serving.update_consume")
        # entering = the manager finished the previous message: progress.
        # The timestamps are NOT behind the metrics kill switch — /readyz
        # derives staleness from them, and readiness must not depend on
        # metrics. What still reads as stale is a consumer stuck INSIDE
        # one message with more queued — size ready-max-lag-sec above the
        # worst-case model-apply time.
        self._last_walltime = time.time()
        self._waiting = True
        try:
            km = next(self._updates)  # blocks on the consumer thread, never the loop
        finally:
            self._waiting = False
        self._consumed += 1
        self._last_walltime = time.time()
        if metrics_mod.default_registry().enabled:
            _UPDATES_CONSUMED.inc()
        return km


def _deadline_middleware(config):
    """Per-request deadline (``oryx.serving.api.request-timeout-sec``): the
    budget is set as the request's :class:`resilience.Deadline` contextvar
    (downstream code — the coalescer dispatch — refuses to START work past
    it) and enforced at this level with ``asyncio.wait_for``. A blown
    budget answers 504 carrying the PARTIAL trace id: every span the
    request recorded before cancellation is already in the ring, so the
    operator can see exactly where the time went. None when disabled."""
    budget = config.get_float("oryx.serving.api.request-timeout-sec", 0.0)
    if budget <= 0:
        return None

    @web.middleware
    async def deadline_mw(request, handler):
        with resilience.deadline(budget):
            try:
                return await asyncio.wait_for(handler(request), timeout=budget)
            except asyncio.TimeoutError:
                return web.json_response({
                    "error": f"request exceeded its {budget:.3f}s budget",
                    "status": 504,
                    "trace_id": spans.current_trace_id(),
                }, status=504)

    return deadline_mw


@web.middleware
async def _compression_middleware(request, handler):
    """Negotiated gzip/deflate response bodies (the reference registers
    Jersey EncodingFilter+Gzip/DeflateEncoder, OryxApplication.java:88-93)."""
    response = await handler(request)
    try:
        if response.body is not None and len(response.body) >= 512:
            response.enable_compression()
    except AttributeError:  # streaming/file responses
        pass
    return response


def make_app(config, manager, input_producer=None) -> web.Application:
    """Build the aiohttp application with resources from config
    (OryxApplication.java:54-96)."""
    metrics_mod.configure(config)
    spans.configure(config)
    compilecache.configure(config)
    resilience.configure(config)
    faults.configure(config)
    # flight recorder (event ring, dump-dir, SIGTERM dump) and the SLO
    # burn-rate engine (scrape-evaluated objectives; /readyz embeds the
    # active-alert list) — both per-process, like the metrics registry
    blackbox.configure(config)
    slo.configure(config)
    # time-series sampler (oryx.tsdb.*): history rings behind
    # GET /metrics/history, the pre-incident window in blackbox bundles,
    # and the trend-alert early warning (docs/observability.md)
    tsdb.configure(config)
    # model-lineage tracker (adoption timeline + freshness watermark behind
    # GET /lineage, the freshness gauges and the x-oryx-model-generation
    # response header)
    lineage.configure(config)
    netbroker.configure(config)  # tcp:// client timeouts/frame caps
    tp.configure(config)  # file-broker fsync durability policy
    # factor-arena sizing (oryx.serving.arena.*): new vector stores built by
    # model handoffs in this process pick the slab seed/compaction knobs up
    from oryx_tpu.models.als import vectors as als_vectors

    als_vectors.configure(config)
    # roofline peaks + device-memory gauges + the profiler session config
    # (after the others: jax is imported by now, so peak auto-detection and
    # per-device gauge wiring can see the live backend)
    profiling.configure(config)
    # concurrency-sanitizer thresholds (oryx.sanitize.*): install happened
    # at import when ORYX_SANITIZE was set; this only tunes thresholds
    from oryx_tpu.tools import sanitize

    sanitize.configure(config)
    middlewares = [_metrics_middleware, rsrc.error_middleware, _compression_middleware]
    dl_mw = _deadline_middleware(config)
    if dl_mw is not None:
        # inside metrics (the 504 must be counted + span-stamped), outside
        # the error mapper (the budget covers handler + error rendering)
        middlewares.insert(1, dl_mw)
    auth_mw = _auth_middleware(config)
    if auth_mw is not None:
        middlewares.append(auth_mw)
    app = web.Application(middlewares=middlewares)
    app[rsrc.CONFIG_KEY] = config
    app[rsrc.MANAGER_KEY] = manager
    app[rsrc.INPUT_PRODUCER_KEY] = input_producer

    window_ms = config.get_float("oryx.serving.compute.coalesce-window-ms", 1.0)
    if window_ms > 0:
        from oryx_tpu.serving.batcher import TopNCoalescer

        app[rsrc.COALESCER_KEY] = TopNCoalescer(
            window_ms,
            config.get_int("oryx.serving.compute.coalesce-max-batch", 256),
            config.get_int("oryx.serving.compute.coalesce-inflight", 2),
            config.get_float("oryx.serving.compute.coalesce-deadline-ms", 250.0),
            max_queue_depth=config.get_int(
                "oryx.serving.compute.max-queue-depth", 0
            ),
            # device-call breaker: batched-call failures open it and route
            # requests to uncoalesced per-request scans until a probe heals
            breaker=resilience.CircuitBreaker.from_config(
                "serving.device_call", config
            ),
        )

    modules = list(DEFAULT_RESOURCES)
    configured = config.get("oryx.serving.application-resources", None)
    if configured:
        if isinstance(configured, str):
            configured = [m.strip() for m in configured.split(",") if m.strip()]
        modules.extend(configured)
    for module_name in modules:
        module = importlib.import_module(module_name)
        if not hasattr(module, "register"):
            raise ValueError(f"resource module {module_name} has no register(app)")
        module.register(app)
        log.info("registered resources from %s", module_name)

    context_path = config.get_string("oryx.serving.api.context-path", "/") or "/"
    if context_path not in ("", "/"):
        # the outer shell carries NO middlewares: aiohttp runs the outer
        # app's chain and then the subapp's, so listing them on both made
        # auth and compression run twice per request (and would have
        # double-counted every metric)
        outer = web.Application()
        outer.add_subapp(context_path, app)
        return outer
    return app


_AUTH_REALM = "Oryx"


def _exempt_canonicals(config) -> frozenset:
    """Route templates exempt from API auth — each listed bare plus
    context-path-prefixed (subapp resources report their canonical WITH the
    prefix). Matching on the matched template, not the raw path, means a
    crafted path can never spoof the exemption.

    ``/healthz``/``/readyz`` are ALWAYS exempt (load balancers cannot speak
    digest, and the probes leak nothing beyond up/down); ``/metrics``,
    ``/metrics/history``, ``/trace``, ``/lineage``, ``/debug/profile``, and
    ``/debug/bundle`` share one auth story — exempt unless
    ``oryx.metrics.require-auth``."""
    templates = {"/healthz", "/readyz"}
    if not config.get_bool("oryx.metrics.require-auth", False):
        templates |= {"/metrics", "/metrics/history", "/trace", "/lineage",
                      "/debug/profile", "/debug/bundle"}
    context_path = config.get_string("oryx.serving.api.context-path", "/") or "/"
    prefix = context_path.rstrip("/")
    return frozenset(templates | {prefix + t for t in templates})


def _is_exempt_route(request: web.Request, canonicals: frozenset) -> bool:
    resource = getattr(request.match_info.route, "resource", None)
    return getattr(resource, "canonical", None) in canonicals


def _auth_middleware(config):
    """Optional HTTP auth behind oryx.serving.api.{user-name,password}:
    DIGEST by default for wire parity with the reference's single-user
    InMemoryRealm (ServingLayer.java:293-321); ``auth-scheme = basic`` opts
    into basic-over-TLS. GET /metrics and /trace are exempt unless
    ``oryx.metrics.require-auth`` (Prometheus scrapers rarely speak digest);
    the /healthz & /readyz probes are always exempt."""
    user = config.get_string("oryx.serving.api.user-name", None)
    if not user:
        return None
    exempt = _exempt_canonicals(config)
    password = config.get_string("oryx.serving.api.password", None) or ""
    scheme = config.get_string("oryx.serving.api.auth-scheme", "digest").lower()
    if scheme == "basic":
        return _basic_auth_middleware(user, password, exempt)
    if scheme != "digest":
        raise ValueError(f"unknown oryx.serving.api.auth-scheme: {scheme}")
    return _digest_auth_middleware(user, password, exempt)


def _basic_auth_middleware(user: str, password: str,
                           exempt: frozenset = frozenset()):
    expected = base64.b64encode(f"{user}:{password}".encode()).decode()

    @web.middleware
    async def auth(request, handler):
        if exempt and _is_exempt_route(request, exempt):
            return await handler(request)
        header = request.headers.get("Authorization", "")
        if not hmac.compare_digest(header, f"Basic {expected}"):
            return web.Response(
                status=401,
                headers={"WWW-Authenticate": f'Basic realm="{_AUTH_REALM}"'},
            )
        return await handler(request)

    return auth


_DIGEST_FIELD_RE = re.compile(r'(\w+)=(?:"([^"]*)"|([^\s,]+))')
_NONCE_TTL_SEC = 300


def _digest_auth_middleware(user: str, password: str,
                            exempt: frozenset = frozenset()):
    """RFC 7616/2617 digest challenge-response (MD5 and SHA-256, qop=auth).

    Nonces are self-validating HMAC(timestamp) tokens — no server-side nonce
    table — and expire after 5 minutes with ``stale=true`` so clients reauth
    without re-prompting."""
    server_key = secrets.token_bytes(16)

    def make_nonce() -> str:
        ts = str(int(time.time()))
        sig = hmac.new(server_key, ts.encode(), hashlib.sha256).hexdigest()[:16]
        return f"{ts}.{sig}"

    def nonce_fresh(nonce: str) -> bool:
        ts, _, sig = nonce.partition(".")
        if not ts.isdigit():
            return False
        want = hmac.new(server_key, ts.encode(), hashlib.sha256).hexdigest()[:16]
        return hmac.compare_digest(sig, want) and time.time() - int(ts) < _NONCE_TTL_SEC

    def challenge(stale: bool = False) -> web.Response:
        headers = []
        for alg in ("SHA-256", "MD5"):  # RFC 7616: strongest first
            h = (
                f'Digest realm="{_AUTH_REALM}", qop="auth", algorithm={alg}, '
                f'nonce="{make_nonce()}", charset=UTF-8'
            )
            if stale:
                h += ", stale=true"
            headers.append(("WWW-Authenticate", h))
        resp = web.Response(status=401)
        for k, v in headers:
            resp.headers.add(k, v)
        return resp

    @web.middleware
    async def auth(request, handler):
        if exempt and _is_exempt_route(request, exempt):
            return await handler(request)
        header = request.headers.get("Authorization", "")
        if not header.startswith("Digest "):
            return challenge()
        fields = {
            m.group(1).lower(): m.group(2) if m.group(2) is not None else m.group(3)
            for m in _DIGEST_FIELD_RE.finditer(header[len("Digest "):])
        }
        try:
            username = fields["username"]
            realm = fields["realm"]
            nonce = fields["nonce"]
            uri = fields["uri"]
            response = fields["response"]
        except KeyError:
            return challenge()
        if username != user or realm != _AUTH_REALM:
            return challenge()
        if not nonce_fresh(nonce):
            return challenge(stale=True)
        algorithm = fields.get("algorithm", "MD5").upper()
        if algorithm in ("MD5", "MD5-SESS"):
            digest = lambda s: hashlib.md5(s.encode()).hexdigest()  # noqa: E731,S324
        elif algorithm in ("SHA-256", "SHA-256-SESS"):
            digest = lambda s: hashlib.sha256(s.encode()).hexdigest()  # noqa: E731
        else:
            return challenge()
        ha1 = digest(f"{user}:{realm}:{password}")
        if algorithm.endswith("-SESS"):
            ha1 = digest(f"{ha1}:{nonce}:{fields.get('cnonce', '')}")
        ha2 = digest(f"{request.method}:{uri}")
        qop = fields.get("qop")
        if qop == "auth":
            expected = digest(
                f"{ha1}:{nonce}:{fields.get('nc', '')}:"
                f"{fields.get('cnonce', '')}:auth:{ha2}"
            )
        elif qop is None:
            expected = digest(f"{ha1}:{nonce}:{ha2}")
        else:
            return challenge()  # qop=auth-int unsupported
        if not hmac.compare_digest(response.lower(), expected):
            return challenge()
        return await handler(request)

    return auth


def _ssl_context(config) -> "ssl.SSLContext | None":
    """TLS from config: keystore-file = PEM cert chain, key-alias = key file
    (ServingLayer.makeConnector TLS knobs, :202-255)."""
    cert = config.get_string("oryx.serving.api.keystore-file", None)
    if not cert:
        return None
    key = config.get_string("oryx.serving.api.key-alias", None)
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ctx.load_cert_chain(cert, key or None, config.get_string("oryx.serving.api.keystore-password", None))
    return ctx


class _BatchWarmer(threading.Thread):
    """Pre-compiles the batched top-N programs when a model becomes ready.

    The coalescer pads batches to powers of two for stable jit signatures;
    on a TPU each signature's FIRST occurrence still pays an XLA compile
    (seconds), which otherwise lands inside the first client burst after
    every MODEL handoff. When ``oryx.serving.compute.precompile-batches``
    is on, this thread watches for a new ready model and walks the shared
    pow2 bucket ladder (``batcher.pow2_buckets``, SMALLEST first so the
    replica turns ready incrementally and the warm-fraction readiness gate
    can trip early) through each model's ``warm_bucket`` hook — AOT
    ``lower().compile()`` plus one real execution — populating the very jit
    caches real queries hit. Progress feeds ``compilecache.warmup_state()``
    (readyz gating + the oryx_warmup_* metrics) and each ladder is traced
    as a ``serving.warmup`` span with per-bucket children.

    Generation handoffs double-buffer through the manager's STAGED model:
    the warmer warms a staged generation before the serving one, then
    promotes it atomically, so an update-topic model push never causes a
    request-visible compile storm. Models without a batched top-N (k-means,
    RDF) mark warmup trivially complete. Each bucket warms BOTH signature
    families — exclusion-free and exclusion-carrying (the default
    ``/recommend`` path always sends known-item exclusions, padded to a
    shape-stable floored width precisely so this ladder can cover it);
    only unusual howMany values and oversized exclusion sets still compile
    on first use."""

    # the reference API's default howMany — warms the top-k width the
    # common request hits; larger howMany values still compile on first use
    WARM_HOW_MANY = 10

    def __init__(self, manager, min_fraction: float, max_batch: int,
                 stop_event: threading.Event):
        super().__init__(name="OryxServingBatchWarmer", daemon=True)
        self.manager = manager
        self.min_fraction = min_fraction
        # the shared bucket enumeration: warming a size real flushes never
        # produce would waste the biggest compile, and a flushed size that
        # was never warmed would compile on-path — one list rules both
        from oryx_tpu.serving.batcher import pow2_buckets

        self.buckets = pow2_buckets(max_batch)  # ascending: smallest first
        # NOT named _stop: threading.Thread.join() calls an internal
        # self._stop() when the thread finishes, and an Event attribute of
        # that name shadows it (TypeError on the first join)
        self._stop_event = stop_event
        self.warmed_models: int = 0  # observability + tests
        self.promoted_models: int = 0

    def run(self) -> None:
        import time as _time
        import weakref

        # weakref: a strong reference here would pin a RETIRED model
        # generation (hundreds of MB of factors) for as long as its
        # successor keeps failing to warm
        last_warmed: "weakref.ref | None" = None
        not_before = 0.0  # fraction walks are costly: back off between tries
        failures = 0
        while not self._stop_event.wait(0.25):
            # a staged (incoming) generation warms FIRST: the serving model
            # is warm already, and the staged one blocks a pending swap
            staged = self.manager.get_staged_model()
            model = staged if staged is not None else self.manager.get_model()
            if model is None or (
                last_warmed is not None and last_warmed() is model
            ):
                continue
            if not hasattr(model, "top_n_batch") or not hasattr(model, "features"):
                # nothing batched to warm on this app family — readiness
                # must not wait on a ladder that will never run
                compilecache.warmup_state().mark_trivial()
                last_warmed = weakref.ref(model)
                continue
            now = _time.monotonic()
            if now < not_before:
                continue
            if model.get_fraction_loaded() < self.min_fraction:
                # the fraction test walks the expected-ID sets (see
                # _maybe_trigger_solvers' rate limit) — don't hammer it
                not_before = now + 2.0
                continue
            if self._warm_model(model):
                last_warmed = weakref.ref(model)
                self.warmed_models += 1
                failures = 0
                # adoption timeline: ladder complete for the newest consumed
                # generation (promote below flips it live)
                lineage.tracker().mark_warmed()
                # expected= guards the flip: a newer MODEL push may have
                # replaced the staged generation while this ladder ran, and
                # that replacement is unwarmed — leave it for the next pass
                if staged is not None and self.manager.promote_staged(
                    expected=model
                ):
                    self.promoted_models += 1
                    log.info("promoted prewarmed model generation")
            else:
                # retry the SAME model later: items may simply not have
                # arrived yet, and a silent skip would strand the feature
                failures += 1
                not_before = _time.monotonic() + min(10.0, 2.0 * failures)

    def _warm_model(self, model) -> bool:
        """One bucket ladder, smallest first; progress into the shared
        warmup state so /readyz (warm-fraction gate) tracks it live."""
        import time as _time

        import numpy as np

        state = compilecache.warmup_state()
        state.begin(len(self.buckets))
        t_model = _time.perf_counter()
        with spans.span(
            "serving.warmup", parent=None,
            attributes={"route": "serving.warmup",
                        "buckets": len(self.buckets)},
        ):
            for b in self.buckets:
                if self._stop_event.is_set():
                    return False
                t0 = _time.perf_counter()
                try:
                    with spans.span(
                        "serving.warmup.bucket",
                        attributes={"route": "serving.warmup",
                                    "batch.size": b},
                    ):
                        if hasattr(model, "warm_bucket"):
                            model.warm_bucket(b, self.WARM_HOW_MANY)
                        else:
                            model.top_n_batch(
                                np.zeros((b, model.features), dtype=np.float32),
                                self.WARM_HOW_MANY,
                            )
                except Exception:  # noqa: BLE001 — e.g. no items yet
                    log.debug("batch warm at size %d failed", b, exc_info=True)
                    return False
                compilecache.observe_warmup(
                    "bucket", _time.perf_counter() - t0
                )
                state.bucket_done()
        compilecache.observe_warmup("model", _time.perf_counter() - t_model)
        state.finish()
        return True


class ServingLayer:
    """Lifecycle: model manager + update consumer + HTTP server
    (ServingLayer.start/await/close:121-178, ModelManagerListener:102-145)."""

    def __init__(self, config):
        self.config = config
        # tcp client knobs must be adopted BEFORE the first get_broker()
        # (start() resolves brokers well before make_app re-configures)
        netbroker.configure(config)
        tp.configure(config)
        self.id = config.get_string("oryx.id", None)
        self.update_broker = config.get_string("oryx.update-topic.broker")
        self.update_topic = config.get_string("oryx.update-topic.message.topic")
        self.input_broker = config.get_string("oryx.input-topic.broker")
        self.input_topic = config.get_string("oryx.input-topic.message.topic")
        self.read_only = config.get_bool("oryx.serving.api.read-only", False)
        # "earliest" (reference parity: full replay) or "committed"
        # (offset-keyed resume: commit after processing, restart from the
        # stored position — the multi-host fleet's cheap-restart mode)
        self.update_resume = config.get_string(
            "oryx.serving.update-resume", "earliest"
        )
        if self.update_resume not in ("earliest", "committed"):
            raise ValueError(
                f"oryx.serving.update-resume must be 'earliest' or "
                f"'committed', not {self.update_resume!r}"
            )
        if self.update_resume == "committed" and not self.id:
            raise ValueError(
                "oryx.serving.update-resume='committed' requires oryx.id "
                "(it keys this replica's stored offsets)"
            )
        # TLS listens on secure-port, plaintext on port — the reference's
        # connector split (ServingLayer.makeConnector:202-255); before this
        # the secure-port key was declared but never read (oryx-analyze:
        # config-key-drift)
        self.port = config.get_int("oryx.serving.api.port")
        self.secure_port = config.get_int("oryx.serving.api.secure-port")
        self.manager: ServingModelManager | None = None
        self._update_iterator: ConsumeDataIterator | None = None
        self._metered_updates: "_MeteredUpdates | None" = None
        self.consumer_restarts = 0  # observability + tests
        self._consumer_thread: threading.Thread | None = None
        self._server_thread: threading.Thread | None = None
        self._warmer: _BatchWarmer | None = None
        self._loop: asyncio.AbstractEventLoop | None = None
        self._started = threading.Event()
        self._stopped = threading.Event()
        self._failure: BaseException | None = None

    def start(self) -> None:
        # cache + compile accounting first: the persistent compilation cache
        # must be live before the FIRST model compile of this process
        compilecache.configure(self.config)
        # retry shapes + fault schedules must be live before the update
        # consumer below takes its first message (make_app runs after it)
        resilience.configure(self.config)
        faults.configure(self.config)
        # topics must exist (ModelManagerListener.contextInitialized:107-127)
        if not self.config.get_bool("oryx.serving.no-init-topics", False):
            for burl, bt in ((self.input_broker, self.input_topic),
                             (self.update_broker, self.update_topic)):
                broker = get_broker(burl)
                if not broker.topic_exists(bt):
                    broker.create_topic(bt)
        producer = None
        if not self.read_only:
            producer = TopicProducerImpl(self.input_broker, self.input_topic)
        self.manager = self._load_manager()
        update_broker = get_broker(self.update_broker)
        offset_group = f"serving-{self.id}" if self.id else None
        committed_mode = self.update_resume == "committed"
        last_committed: dict[int, int] = {}

        def _commit_processed():
            # persist only positions that moved since the last commit; the
            # PROCESSED offsets, never the read positions (the prefetch
            # buffer may hold messages the manager has not applied yet).
            # tp.offset_op is the shared commit-path retry contract (site
            # broker.offset, same as the lambda tiers' UpdateOffsetsFn path)
            for p, off in self._update_iterator.processed_offsets.items():
                if last_committed.get(p) != off:
                    tp_offset_op(
                        lambda p=p, off=off: update_broker.set_offset(
                            offset_group, self.update_topic, off, p
                        ),
                        stop=self._stopped,
                    )
                    last_committed[p] = off

        def _new_update_pipeline():
            iterator = ConsumeDataIterator(
                update_broker, self.update_topic,
                "committed" if committed_mode else "earliest",
                offset_group=offset_group,
            )
            metered = _MeteredUpdates(
                iterator, update_broker, self.update_topic,
                commit=_commit_processed if committed_mode else None,
            )
            return iterator, metered

        self._update_iterator, self._metered_updates = _new_update_pipeline()
        restart_cfg = self.config.get_config("oryx.resilience.consumer-restart")
        max_restarts = restart_cfg.get_int("max-restarts", -1)
        base_delay = restart_cfg.get_float("base-delay-ms", 100.0) / 1000.0
        max_delay = restart_cfg.get_float("max-delay-ms", 5000.0) / 1000.0

        def consume():
            # SUPERVISED: before this loop existed, one crash (or one poison
            # update) silently ended the consumer thread — the layer kept
            # serving an ever-staler model until /readyz noticed. Now each
            # crash restarts consumption from "earliest" (full state replay:
            # exactly how a fresh replica builds its model, so correct by
            # construction) after a bounded-exponential delay, while the
            # HTTP side keeps answering from the current in-memory model.
            restarts = 0
            need_rebuild = False
            while not self._stopped.is_set():
                attempt_started = time.monotonic()
                try:
                    if need_rebuild:
                        # the rebuild runs INSIDE the supervised try: the
                        # iterator constructor performs broker RPCs
                        # (num_partitions, stored offsets), and a broker
                        # still down at restart time used to raise out of
                        # the except handler below and kill this thread
                        # permanently — a replica that serves forever but
                        # never consumes again (the fleet SPOF drill's
                        # "never drained" stall)
                        ioutils.close_quietly(self._update_iterator)
                        # committed mode restarts from the stored positions
                        # (offset-keyed resume); earliest replays in full
                        self._update_iterator, self._metered_updates = (
                            _new_update_pipeline()
                        )
                        need_rebuild = False
                        if self._stopped.is_set():
                            # close() raced the rebuild: it closed the OLD
                            # iterator before the assignment above landed,
                            # so this fresh one is ours to close — without
                            # this re-check the consumer would block in
                            # consume() on an iterator nothing ever closes
                            ioutils.close_quietly(self._update_iterator)
                            return
                    self.manager.consume(self._metered_updates)
                    return  # iterator closed: clean shutdown
                except Exception as e:  # noqa: BLE001 — supervised
                    if self._stopped.is_set():
                        return
                    if (
                        time.monotonic() - attempt_started
                        >= _CONSUMER_HEALTHY_RESET_SEC
                    ):
                        restarts = 0  # budget is per unhealthy window
                    restarts += 1
                    self.consumer_restarts += 1  # lifetime-cumulative (tests)
                    _CONSUMER_RESTARTS.inc()
                    blackbox.record_event(
                        "consumer.restart", severity="error",
                        restart=restarts,
                        error=f"{type(e).__name__}: {e}",
                    )
                    if 0 <= max_restarts < restarts:
                        log.exception(
                            "update consumer failed %d times; giving up and "
                            "closing the layer", restarts,
                        )
                        self._failure = e
                        self.close()
                        return
                    delay = min(max_delay, base_delay * (2 ** (restarts - 1)))
                    log.exception(
                        "update consumer crashed (restart %d); restarting "
                        "from %s in %.2fs", restarts, self.update_resume,
                        delay,
                    )
                    if self._stopped.wait(delay):
                        return
                    need_rebuild = True
                    # the loop re-checks _stopped before rebuilding, and the
                    # rebuild re-checks it again after installing the fresh
                    # iterator (closing it when close() raced) — so a
                    # close() at any point cannot strand a consumer blocked
                    # on a just-created iterator; a rebuild that fails
                    # (broker still down) lands back here with the next
                    # backoff step instead of ending the thread

        self._consumer_thread = threading.Thread(
            target=consume, name="OryxServingLayerUpdateConsumerThread", daemon=True
        )
        self._consumer_thread.start()

        # this layer owns the process's serving warmup state: reset leftovers
        # from a previous layer in the same process, then arm when warmup is
        # configured so /readyz holds until the first ladder completes
        warm_state = compilecache.warmup_state()
        warm_state.reset()
        if self.config.get_bool(
            "oryx.serving.compute.precompile-batches", False
        ):
            warm_state.arm()
            self._warmer = _BatchWarmer(
                self.manager,
                self.config.get_float("oryx.serving.min-model-load-fraction"),
                self.config.get_int(
                    "oryx.serving.compute.coalesce-max-batch", 256
                ),
                self._stopped,
            )
            self._warmer.start()

        app = make_app(self.config, self.manager, producer)
        sslctx = _ssl_context(self.config)
        bind_port = self.secure_port if sslctx is not None else self.port

        def serve():
            loop = asyncio.new_event_loop()
            self._loop = loop
            asyncio.set_event_loop(loop)
            # pre-started default executor: the lazily-created one spawns
            # its worker threads on FIRST use, and Thread.start() blocks
            # until the OS schedules the new thread — under CPU contention
            # that is a several-hundred-ms EVENT-LOOP stall on the first
            # coalescer dispatch per worker (caught live by the sanitizer's
            # loop watchdog). Spawning here, off the request path, makes
            # every later run_in_executor hop a queue push.
            executor = concurrent.futures.ThreadPoolExecutor(
                max_workers=min(8, (os.cpu_count() or 4)),
                thread_name_prefix="oryx-serving-exec",
            )
            barrier = threading.Barrier(executor._max_workers + 1)
            for _ in range(executor._max_workers):
                executor.submit(barrier.wait, 10)
            with contextlib.suppress(threading.BrokenBarrierError):
                barrier.wait(10)  # all workers alive before serving starts
            loop.set_default_executor(executor)
            runner = web.AppRunner(app)
            loop.run_until_complete(runner.setup())
            site = web.TCPSite(runner, "0.0.0.0", bind_port, ssl_context=sslctx)
            loop.run_until_complete(site.start())
            log.info("serving layer listening on :%d%s", bind_port,
                     " (TLS)" if sslctx is not None else "")
            self._started.set()
            try:
                loop.run_forever()
            finally:
                loop.run_until_complete(runner.cleanup())
                executor.shutdown(wait=False)
                loop.close()

        self._server_thread = threading.Thread(target=serve, name="OryxServingLayer", daemon=True)
        self._server_thread.start()
        if not self._started.wait(timeout=30):
            raise RuntimeError("serving layer failed to start")

    def _load_manager(self) -> ServingModelManager:
        name = self.config.get_string("oryx.serving.model-manager-class")
        if not name:
            raise ValueError("no class configured at oryx.serving.model-manager-class")
        return classutils.load_instance_of(name, ServingModelManager, self.config)

    def await_termination(self, timeout: float | None = None) -> None:
        self._stopped.wait(timeout)
        if self._failure is not None:
            raise self._failure

    def close(self) -> None:
        self._stopped.set()
        if self._update_iterator is not None:
            self._update_iterator.close()
        if (
            self._warmer is not None
            and self._warmer is not threading.current_thread()
        ):
            # join BEFORE closing the manager: a leaked warmer thread would
            # keep poking get_model()/top_n_batch on a closed manager (and
            # leak across tests); the timeout bounds a warm mid-compile
            self._warmer.join(timeout=10)
            if self._warmer.is_alive():
                log.warning("batch warmer did not stop within 10s")
        if self.manager is not None:
            self.manager.close()
        if self._loop is not None:
            self._loop.call_soon_threadsafe(self._loop.stop)
        if self._server_thread is not None and self._server_thread is not threading.current_thread():
            self._server_thread.join(timeout=10)
        if (
            self._consumer_thread is not None
            and self._consumer_thread is not threading.current_thread()
        ):
            self._consumer_thread.join(timeout=5)
        # this layer armed the process-global warmup state at start; a
        # closed layer must not keep gating /readyz of whatever serves
        # next in this process (an armed-but-dead state read "cold"
        # forever and 503'd later bare make_app() apps)
        compilecache.warmup_state().reset()
