"""Serving runtime: HTTP app factory + layer lifecycle.

Equivalent of the reference's ServingLayer + ModelManagerListener +
OryxApplication (framework/oryx-lambda-serving/.../ServingLayer.java:121-337,
ModelManagerListener.java:81-225, OryxApplication.java:54-96): where the
reference embeds Tomcat and reflection-scans JAX-RS resources, this builds an
aiohttp application, imports the configured ``application-resources`` modules
and calls their ``register(app)`` hooks, wires the model-manager lifecycle
(update-topic consumer thread from ``earliest``, input producer unless
read-only), and serves with optional basic auth, TLS, and a context path.
"""

from __future__ import annotations

import asyncio
import base64
import importlib
import logging
import ssl
import threading

from aiohttp import web

from oryx_tpu.api.serving import ServingModelManager
from oryx_tpu.common import classutils
from oryx_tpu.serving import resource as rsrc
from oryx_tpu.transport.topic import ConsumeDataIterator, TopicProducerImpl, get_broker

log = logging.getLogger(__name__)

DEFAULT_RESOURCES = ["oryx_tpu.serving.resources.common"]


@web.middleware
async def _compression_middleware(request, handler):
    """Negotiated gzip/deflate response bodies (the reference registers
    Jersey EncodingFilter+Gzip/DeflateEncoder, OryxApplication.java:88-93)."""
    response = await handler(request)
    try:
        if response.body is not None and len(response.body) >= 512:
            response.enable_compression()
    except AttributeError:  # streaming/file responses
        pass
    return response


def make_app(config, manager, input_producer=None) -> web.Application:
    """Build the aiohttp application with resources from config
    (OryxApplication.java:54-96)."""
    middlewares = [rsrc.error_middleware, _compression_middleware]
    auth_mw = _basic_auth_middleware(config)
    if auth_mw is not None:
        middlewares.append(auth_mw)
    app = web.Application(middlewares=middlewares)
    app[rsrc.CONFIG_KEY] = config
    app[rsrc.MANAGER_KEY] = manager
    app[rsrc.INPUT_PRODUCER_KEY] = input_producer

    modules = list(DEFAULT_RESOURCES)
    configured = config.get("oryx.serving.application-resources", None)
    if configured:
        if isinstance(configured, str):
            configured = [m.strip() for m in configured.split(",") if m.strip()]
        modules.extend(configured)
    for module_name in modules:
        module = importlib.import_module(module_name)
        if not hasattr(module, "register"):
            raise ValueError(f"resource module {module_name} has no register(app)")
        module.register(app)
        log.info("registered resources from %s", module_name)

    context_path = config.get_string("oryx.serving.api.context-path", "/") or "/"
    if context_path not in ("", "/"):
        outer = web.Application(middlewares=middlewares)
        outer.add_subapp(context_path, app)
        return outer
    return app


def _basic_auth_middleware(config):
    """Optional HTTP basic auth (reference uses a DIGEST realm,
    ServingLayer.java:293-321; basic-over-TLS is the modern equivalent)."""
    user = config.get_string("oryx.serving.api.user-name", None)
    password = config.get_string("oryx.serving.api.password", None)
    if not user:
        return None
    expected = base64.b64encode(f"{user}:{password or ''}".encode()).decode()

    @web.middleware
    async def auth(request, handler):
        header = request.headers.get("Authorization", "")
        if header != f"Basic {expected}":
            return web.Response(
                status=401, headers={"WWW-Authenticate": 'Basic realm="Oryx"'}
            )
        return await handler(request)

    return auth


def _ssl_context(config) -> "ssl.SSLContext | None":
    """TLS from config: keystore-file = PEM cert chain, key-alias = key file
    (ServingLayer.makeConnector TLS knobs, :202-255)."""
    cert = config.get_string("oryx.serving.api.keystore-file", None)
    if not cert:
        return None
    key = config.get_string("oryx.serving.api.key-alias", None)
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ctx.load_cert_chain(cert, key or None, config.get_string("oryx.serving.api.keystore-password", None))
    return ctx


class ServingLayer:
    """Lifecycle: model manager + update consumer + HTTP server
    (ServingLayer.start/await/close:121-178, ModelManagerListener:102-145)."""

    def __init__(self, config):
        self.config = config
        self.id = config.get_string("oryx.id", None)
        self.update_broker = config.get_string("oryx.update-topic.broker")
        self.update_topic = config.get_string("oryx.update-topic.message.topic")
        self.input_broker = config.get_string("oryx.input-topic.broker")
        self.input_topic = config.get_string("oryx.input-topic.message.topic")
        self.read_only = config.get_bool("oryx.serving.api.read-only", False)
        self.port = config.get_int("oryx.serving.api.port")
        self.manager: ServingModelManager | None = None
        self._update_iterator: ConsumeDataIterator | None = None
        self._consumer_thread: threading.Thread | None = None
        self._server_thread: threading.Thread | None = None
        self._loop: asyncio.AbstractEventLoop | None = None
        self._started = threading.Event()
        self._stopped = threading.Event()
        self._failure: BaseException | None = None

    def start(self) -> None:
        # topics must exist (ModelManagerListener.contextInitialized:107-127)
        if not self.config.get_bool("oryx.serving.no-init-topics", False):
            for burl, bt in ((self.input_broker, self.input_topic),
                             (self.update_broker, self.update_topic)):
                broker = get_broker(burl)
                if not broker.topic_exists(bt):
                    broker.create_topic(bt)
        producer = None
        if not self.read_only:
            producer = TopicProducerImpl(self.input_broker, self.input_topic)
        self.manager = self._load_manager()
        self._update_iterator = ConsumeDataIterator(
            get_broker(self.update_broker), self.update_topic, "earliest"
        )

        def consume():
            try:
                self.manager.consume(self._update_iterator)
            except Exception as e:  # noqa: BLE001
                if not self._stopped.is_set():
                    log.exception("fatal error consuming updates; closing layer")
                    self._failure = e
                    self.close()

        self._consumer_thread = threading.Thread(
            target=consume, name="OryxServingLayerUpdateConsumerThread", daemon=True
        )
        self._consumer_thread.start()

        app = make_app(self.config, self.manager, producer)
        sslctx = _ssl_context(self.config)

        def serve():
            loop = asyncio.new_event_loop()
            self._loop = loop
            asyncio.set_event_loop(loop)
            runner = web.AppRunner(app)
            loop.run_until_complete(runner.setup())
            site = web.TCPSite(runner, "0.0.0.0", self.port, ssl_context=sslctx)
            loop.run_until_complete(site.start())
            log.info("serving layer listening on :%d", self.port)
            self._started.set()
            try:
                loop.run_forever()
            finally:
                loop.run_until_complete(runner.cleanup())
                loop.close()

        self._server_thread = threading.Thread(target=serve, name="OryxServingLayer", daemon=True)
        self._server_thread.start()
        if not self._started.wait(timeout=30):
            raise RuntimeError("serving layer failed to start")

    def _load_manager(self) -> ServingModelManager:
        name = self.config.get_string("oryx.serving.model-manager-class")
        if not name:
            raise ValueError("no class configured at oryx.serving.model-manager-class")
        return classutils.load_instance_of(name, ServingModelManager, self.config)

    def await_termination(self, timeout: float | None = None) -> None:
        self._stopped.wait(timeout)
        if self._failure is not None:
            raise self._failure

    def close(self) -> None:
        self._stopped.set()
        if self._update_iterator is not None:
            self._update_iterator.close()
        if self.manager is not None:
            self.manager.close()
        if self._loop is not None:
            self._loop.call_soon_threadsafe(self._loop.stop)
        if self._server_thread is not None and self._server_thread is not threading.current_thread():
            self._server_thread.join(timeout=10)
        if (
            self._consumer_thread is not None
            and self._consumer_thread is not threading.current_thread()
        ):
            self._consumer_thread.join(timeout=5)
