"""Request-coalescing micro-batcher for the top-N serving hot path.

TPU-native replacement for the reference's per-request thread-fanned
partition scans (app/oryx-app-serving/.../als/model/ALSServingModel.java:
261-276 fans one top-N over LSH partitions with an executor PER REQUEST):
on an accelerator the economical unit is one big batched matmul, so
concurrent HTTP requests are gathered for a sub-millisecond window (or
until ``max_batch``) and answered with ONE ``top_n_batch`` device call.
Under the reference LoadBenchmark's concurrency this turns N matmul
launches + N tunnel round-trips into one of each.

Coalescing applies when the request has no score-rewriting rescorer
(``rescore`` hooks change scores, which a shared scan cannot honor);
host-side ``allowed`` filters and per-query known-item exclusions ride
along — ``top_n_batch`` masks exclusions on device and falls back per
query if a filter exhausts its candidates.

Pure asyncio: submissions happen on the event loop; the batched device
call runs in the default executor so the loop never blocks on the chip.
"""

from __future__ import annotations

import asyncio
import logging

import numpy as np

log = logging.getLogger(__name__)


class _Pending:
    __slots__ = ("vec", "want", "how_many", "offset", "allowed", "excluded",
                 "future")

    def __init__(self, vec, how_many, offset, allowed, excluded, future):
        self.vec = vec
        self.want = how_many + offset
        self.how_many = how_many
        self.offset = offset
        self.allowed = allowed
        self.excluded = excluded
        self.future = future


class TopNCoalescer:
    """Gathers concurrent top-N requests into one batched device call.

    One instance per serving app; requests against different model objects
    (a MODEL handoff mid-flight) are grouped by model identity at flush."""

    def __init__(self, window_ms: float = 1.0, max_batch: int = 256):
        self.window_s = window_ms / 1000.0
        self.max_batch = max_batch
        self._pending: list[tuple[object, _Pending]] = []
        self._flusher: asyncio.TimerHandle | None = None

    async def top_n(self, model, query_vec, how_many: int, offset: int = 0,
                    allowed=None, excluded=None) -> list:
        """Coalesced equivalent of ``model.top_n(...)`` (no rescore)."""
        loop = asyncio.get_running_loop()
        fut = loop.create_future()
        self._pending.append((model, _Pending(
            np.asarray(query_vec, dtype=np.float32), how_many, offset,
            allowed, excluded, fut,
        )))
        if len(self._pending) >= self.max_batch:
            self._flush(loop)
        elif self._flusher is None:
            self._flusher = loop.call_later(self.window_s,
                                            lambda: self._flush(loop))
        return await fut

    def _flush(self, loop) -> None:
        if self._flusher is not None:
            self._flusher.cancel()
            self._flusher = None
        batch, self._pending = self._pending, []
        if not batch:
            return
        by_model: dict[int, tuple[object, list[_Pending]]] = {}
        for model, p in batch:
            by_model.setdefault(id(model), (model, []))[1].append(p)
        for model, group in by_model.values():
            loop.run_in_executor(None, self._execute, loop, model, group)

    @staticmethod
    def _execute(loop, model, group: list[_Pending]) -> None:
        """Executor thread: ONE batched device call for the whole group."""
        try:
            qs = np.stack([p.vec for p in group])
            want = max(p.want for p in group)
            alloweds = (
                [p.allowed for p in group]
                if any(p.allowed is not None for p in group)
                else None
            )
            excluded = (
                [p.excluded for p in group]
                if any(p.excluded for p in group)
                else None
            )
            results = model.top_n_batch(qs, want, alloweds, excluded)
            for p, res in zip(group, results):
                out = res[p.offset:p.offset + p.how_many]
                loop.call_soon_threadsafe(_set_result, p.future, out)
        except Exception as e:  # noqa: BLE001 — fail the batch, not the loop
            log.exception("coalesced top-N batch failed")
            for p in group:
                loop.call_soon_threadsafe(_set_exception, p.future, e)


def _set_result(future: asyncio.Future, value) -> None:
    if not future.done():
        future.set_result(value)


def _set_exception(future: asyncio.Future, exc: BaseException) -> None:
    if not future.done():
        future.set_exception(exc)
