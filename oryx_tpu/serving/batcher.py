"""Request-coalescing micro-batcher for the top-N serving hot path.

TPU-native replacement for the reference's per-request thread-fanned
partition scans (app/oryx-app-serving/.../als/model/ALSServingModel.java:
261-276 fans one top-N over LSH partitions with an executor PER REQUEST):
on an accelerator the economical unit is one big batched matmul, so
concurrent HTTP requests are gathered for a sub-millisecond window (or
until ``max_batch``) and answered with ONE ``top_n_batch`` device call.
Under the reference LoadBenchmark's concurrency this turns N matmul
launches + N tunnel round-trips into one of each.

Coalescing applies when the request has no score-rewriting rescorer
(``rescore`` hooks change scores, which a shared scan cannot honor);
host-side ``allowed`` filters and per-query known-item exclusions ride
along — ``top_n_batch`` masks exclusions on device and falls back per
query if a filter exhausts its candidates.

Pure asyncio: submissions happen on the event loop; the batched device
call runs in the default executor so the loop never blocks on the chip.
"""

from __future__ import annotations

import asyncio

import numpy as np

from oryx_tpu.api.serving import OverloadedException
from oryx_tpu.common import blackbox
from oryx_tpu.common import faults
from oryx_tpu.common import metrics as metrics_mod
from oryx_tpu.common import resilience
from oryx_tpu.common import spans

log = spans.get_logger(__name__)

_BATCH_SIZE = metrics_mod.default_registry().histogram(
    "oryx_coalescer_batch_size",
    "Real (pre-padding) request count per coalesced device call",
    buckets=metrics_mod.POW2_BUCKETS,
)
_QUEUE_DEPTH = metrics_mod.default_registry().gauge(
    "oryx_coalescer_queue_depth",
    "Requests waiting for a coalesced flush",
)
_DEADLINE_FLUSHES = metrics_mod.default_registry().counter(
    "oryx_coalescer_deadline_flushes_total",
    "Flushes forced past the inflight cap by the queue-wait deadline",
)
_PAD_WASTE = metrics_mod.default_registry().counter(
    "oryx_coalescer_pad_waste_rows_total",
    "Padding rows added to reach power-of-two batch shapes",
)
_SHED = metrics_mod.default_registry().counter(
    "oryx_shed_requests_total",
    "Requests refused up front (503 + Retry-After) because the coalescer "
    "queue exceeded oryx.serving.compute.max-queue-depth",
)
_DEGRADED = metrics_mod.default_registry().counter(
    "oryx_breaker_degraded_requests_total",
    "Requests served WITHOUT coalescing because the device-call circuit "
    "breaker was open (per-request fallback scans on the current model)",
)
_DEADLINE_DROPS = metrics_mod.default_registry().counter(
    "oryx_coalescer_deadline_dropped_total",
    "Queued requests whose per-request deadline expired before dispatch "
    "(answered 504 without spending a device call on them)",
)


def floor_pow2(n: int) -> int:
    """Largest power of two ≤ max(1, n) — the coalescer's batch-cap floor,
    shared with the batch warmer so both always agree on real flush sizes."""
    return 1 << max(0, max(1, n).bit_length() - 1)


def pow2_buckets(max_batch: int) -> list[int]:
    """Ascending pow2 batch buckets ``[1, 2, ..., floor_pow2(max_batch)]``.

    THE bucket enumeration of the serving hot path: the coalescer pads every
    flush up to one of these sizes (``_execute``), and the warmup subsystem
    precompiles exactly this ladder (smallest first, so a starting replica
    turns ready incrementally) — keeping both ends in one function means a
    cap change can never warm sizes that are not flushed, or flush sizes
    that were not warmed."""
    return [1 << i for i in range(floor_pow2(max_batch).bit_length())]


class _Pending:
    __slots__ = ("vec", "want", "how_many", "offset", "allowed", "excluded",
                 "future", "enq_t", "wait_span", "deadline")

    def __init__(self, vec, how_many, offset, allowed, excluded, future,
                 enq_t: float = 0.0, wait_span=None, deadline=None):
        self.vec = vec
        self.want = how_many + offset
        self.how_many = how_many
        self.offset = offset
        self.allowed = allowed
        self.excluded = excluded
        self.future = future
        self.enq_t = enq_t
        # queue-wait span: opened at enqueue as a child of the request's
        # ingress span (contextvars do NOT cross the executor hop, so the
        # span object itself is the carrier), closed at dispatch
        self.wait_span = wait_span
        # the request's Deadline, captured at enqueue for the same reason:
        # the executor-side dispatch checks it before spending device time
        self.deadline = deadline


class TopNCoalescer:
    """Gathers concurrent top-N requests into one batched device call.

    Batch-while-busy: when no device call is in flight a request flushes
    after at most ``window_ms``; while calls are in flight new arrivals
    simply accumulate and the completion of a call flushes whatever queued
    behind it. Under closed-loop clients (each awaiting its response before
    sending the next request) this makes the batch size converge on
    arrival-rate × device-latency automatically — a fixed window would
    degenerate to one-request batches the moment latency exceeds it, paying
    a full device round-trip per request. ``max_inflight > 1`` keeps the
    pipe full by overlapping one batch's host/transfer time with another's
    compute.

    ``deadline_ms`` bounds the queue wait behind in-flight batches (the p99
    failure mode: with every inflight slot busy, arrivals used to wait an
    unbounded number of device round-trips). When the OLDEST pending request
    has waited past the deadline, a flush dispatches anyway — exceeding
    ``max_inflight`` by AT MOST one call, ever: while that over-cap call is
    out, further expired waiters re-arm and wait for a completion instead of
    stacking device calls. 0 disables.

    One instance per serving app; requests against different model objects
    (a MODEL handoff mid-flight) are grouped by model identity at flush."""

    def __init__(self, window_ms: float = 1.0, max_batch: int = 256,
                 max_inflight: int = 2, deadline_ms: float = 250.0,
                 max_queue_depth: int = 0, breaker=None):
        self.window_s = window_ms / 1000.0
        # floor to a power of two: batches pad up to a pow2 for stable jit
        # signatures, and padding must never exceed the configured cap
        # (the operator tuned it to bound device memory)
        self.max_batch = floor_pow2(max_batch)
        self.max_inflight = max(1, max_inflight)
        self.deadline_s = max(0.0, deadline_ms) / 1000.0
        # load shed past this queue depth (0 = unbounded); the Retry-After
        # hint is roughly one device round-trip — the queue-wait deadline
        self.max_queue_depth = max(0, max_queue_depth)
        # device-call circuit breaker (common/resilience.py); None = always
        # coalesce. Callers consult admit() BEFORE routing a request here.
        self.breaker = breaker
        self._pending: list[tuple[object, _Pending]] = []
        self._flusher: asyncio.TimerHandle | None = None
        self._deadline_timer: asyncio.TimerHandle | None = None
        self._inflight = 0
        self.deadline_flushes = 0  # observability + tests
        self.shed_requests = 0
        self.degraded_requests = 0

    def admit(self) -> bool:
        """Breaker admission for the coalesced path: False while the
        device-call breaker is open (callers degrade to per-request scans
        on the current model instead of erroring); half-open admits the
        breaker's probe quota so a recovered device closes it again."""
        if self.breaker is None or self.breaker.allow():
            return True
        self.degraded_requests += 1
        _DEGRADED.inc()
        return False

    async def top_n(self, model, query_vec, how_many: int, offset: int = 0,
                    allowed=None, excluded=None) -> list:
        """Coalesced equivalent of ``model.top_n(...)`` (no rescore)."""
        loop = asyncio.get_running_loop()
        if self.max_queue_depth and len(self._pending) >= self.max_queue_depth:
            # shed NOW, before queueing: a 503 in microseconds beats a 200
            # after a timeout-sized queue wait, and the client's retry lands
            # on a drained queue (or another replica)
            self.shed_requests += 1
            _SHED.inc()
            # one throttled flight-recorder event per shed burst (the
            # ``suppressed`` count carries the storm's size) — an overload
            # must be reconstructable from a dead replica's bundle without
            # letting the storm itself evict every other event
            blackbox.record_event(
                "shed", severity="warning", throttle_sec=1.0,
                queue_depth=len(self._pending),
                max_queue_depth=self.max_queue_depth,
            )
            raise OverloadedException(
                f"coalescer queue depth {len(self._pending)} >= "
                f"{self.max_queue_depth}",
                retry_after_sec=max(1.0, self.deadline_s),
            )
        fut = loop.create_future()
        wait_span = spans.start_span(
            "coalescer.queue_wait",
            attributes={"route": "coalescer.queue_wait"},
        )
        self._pending.append((model, _Pending(
            np.asarray(query_vec, dtype=np.float32), how_many, offset,
            allowed, excluded, fut, loop.time(), wait_span,
            resilience.current_deadline(),
        )))
        self._maybe_flush(loop)
        return await fut

    def _maybe_flush(self, loop) -> None:
        _QUEUE_DEPTH.set(len(self._pending))
        if not self._pending:
            return
        if self._inflight >= self.max_inflight:
            # an in-flight completion will re-trigger; the deadline timer
            # bounds the wait if the in-flight call is slow or wedged
            self._arm_deadline(loop)
            return
        if len(self._pending) >= self.max_batch:
            self._flush(loop)
        elif self._flusher is None:
            self._flusher = loop.call_later(self.window_s,
                                            lambda: self._flush(loop))

    def _arm_deadline(self, loop) -> None:
        if self.deadline_s <= 0 or self._deadline_timer is not None:
            return
        oldest = self._pending[0][1].enq_t
        # floor the re-arm delay: an ALREADY-expired waiter (over-cap slot
        # spent, device wedged) would otherwise re-arm at 0 and busy-spin
        # the event loop until a device call completes
        delay = max(oldest + self.deadline_s - loop.time(),
                    self.deadline_s / 8.0, 0.001)
        self._deadline_timer = loop.call_later(
            delay, lambda: self._deadline_fire(loop)
        )

    def _deadline_fire(self, loop) -> None:
        self._deadline_timer = None
        if not self._pending:
            return
        # the entry this timer was armed for may have flushed already: only
        # force past the inflight cap for a waiter that actually expired
        oldest = self._pending[0][1].enq_t
        if loop.time() - oldest + 1e-4 < self.deadline_s:
            self._arm_deadline(loop)
            return
        if self._inflight > self.max_inflight:
            # the single over-cap slot is already spent (a previous forced
            # call hasn't completed): never stack further device calls —
            # re-arm and wait for a completion to drain the queue
            self._arm_deadline(loop)
            return
        if self._inflight == self.max_inflight:
            self.deadline_flushes += 1
            _DEADLINE_FLUSHES.inc()
            self._flush(loop, force=True)
        else:
            self._flush(loop)
        if self._pending:
            self._arm_deadline(loop)

    def _flush(self, loop, force: bool = False) -> None:
        if self._flusher is not None:
            self._flusher.cancel()
            self._flusher = None
        if not force and self._inflight >= self.max_inflight:
            return  # raced with a slower flush path; completion re-triggers
        batch = self._pending[:self.max_batch]
        self._pending = self._pending[self.max_batch:]
        if not batch:
            return
        by_model: dict[int, tuple[object, list[_Pending]]] = {}
        for model, p in batch:
            by_model.setdefault(id(model), (model, []))[1].append(p)
        # a flush spanning several model objects (MODEL handoff mid-flight)
        # must still honor max_inflight: dispatch while slots remain (force
        # grants exactly one over-cap slot — the deadline escape hatch) and
        # push the rest back to the queue front for the next completion
        groups = list(by_model.values())
        while groups and (force or self._inflight < self.max_inflight):
            force = False
            model, group = groups.pop(0)
            self._inflight += 1
            _BATCH_SIZE.observe(len(group))
            # queue wait ends at dispatch, and the device-call span OPENS
            # here (not in the executor): the executor-scheduling handoff is
            # part of what the request waits for, so it must be inside a
            # span — otherwise the trace shows an unattributable gap. The
            # call span opens BEFORE the wait spans close so a scheduling
            # pause between the two timestamps reads as span overlap, never
            # as an unattributed hole in the trace.
            now = loop.time()
            waits = [p.wait_span.context for p in group]
            # parent = the first waiter; links = the OTHER waiters (linking
            # the parent too would double-count that request in the fan-in)
            call_span = spans.start_span(
                "coalescer.device_call",
                parent=waits[0],
                links=[c for c in waits[1:] if c is not None],
                attributes={
                    "route": "coalescer.device_call",
                    "batch.size": len(group),
                    "queue_wait_max_ms": round(
                        (now - min(p.enq_t for p in group)) * 1000.0, 3
                    ),
                },
            )
            for p in group:
                p.wait_span.set_attribute(
                    "queue_wait_ms", round((now - p.enq_t) * 1000.0, 3)
                )
                spans.finish_span(p.wait_span)
            try:
                loop.run_in_executor(None, self._execute, loop, model, group,
                                     call_span)
            except Exception as e:  # noqa: BLE001 — executor/loop torn down
                # dispatch itself failed (executor shut down mid-close): the
                # slot was taken but _execute will never run, so _done will
                # never release it — undo the increment HERE and fail the
                # group's futures instead of leaving them (and every later
                # pending request behind the leaked slot) to hang until
                # client timeout
                self._inflight -= 1
                call_span.record_exception(e)
                spans.finish_span(call_span)
                log.exception(
                    "coalesced dispatch failed before execution; failing "
                    "its %d request(s)", len(group),
                )
                for p in group:
                    _set_exception(p.future, e)
        for model, group in reversed(groups):
            self._pending[:0] = [(model, p) for p in group]
        _QUEUE_DEPTH.set(len(self._pending))
        if self._pending:
            self._maybe_flush(loop)

    def _done(self, loop) -> None:
        self._inflight -= 1
        if self._pending:
            # flush NOW — whatever queued behind the finished call has
            # already waited a full device round-trip; re-arming the window
            # timer here would idle the device for window_ms per cycle
            self._flush(loop)

    def _execute(self, loop, model, group: list[_Pending], call_span) -> None:
        """Executor thread: ONE batched device call for the whole group.

        The device call is a FAN-IN: ``call_span`` (opened at dispatch on
        the loop) is parented into the first waiter's trace and *linked* to
        every waiter's queue-wait span, so each participating trace can
        find the shared call — and its batch-size/pad-waste attributes —
        that answered it.

        Resilience (docs/robustness.md): requests whose per-request
        Deadline expired while queued are answered 504 here WITHOUT
        spending device time on them; a failed batch reports to the
        device-call circuit breaker and each of its requests retries as an
        uncoalesced per-request scan (degraded mode) before any client
        sees an error."""
        live: list[_Pending] = []
        for p in group:
            if p.deadline is not None and p.deadline.expired():
                _DEADLINE_DROPS.inc()
                loop.call_soon_threadsafe(
                    _set_exception, p.future,
                    resilience.DeadlineExceeded(
                        "deadline expired in the coalescer queue"
                    ),
                )
            else:
                live.append(p)
        if len(live) < len(group):
            call_span.set_attribute("deadline.dropped", len(group) - len(live))
        group = live
        if not group:
            spans.finish_span(call_span)
            loop.call_soon_threadsafe(self._done, loop)
            return
        span_finished = False
        try:
            with spans.activate(call_span):
                faults.maybe_fail("serving.device_call")
                qs = np.stack([p.vec for p in group])
                want = max(p.want for p in group)
                alloweds = (
                    [p.allowed for p in group]
                    if any(p.allowed is not None for p in group)
                    else None
                )
                excluded = (
                    [p.excluded for p in group]
                    if any(p.excluded for p in group)
                    else None
                )
                # pad the batch to a power of two: coalesced batch sizes vary
                # per flush, and every distinct size would otherwise be a fresh
                # XLA trace/compile of the batched top-N program — on a
                # tunneled backend that is seconds of compile on the hot path
                n_real = len(group)
                n_pad = 1 << max(0, n_real - 1).bit_length()
                call_span.set_attribute("batch.padded", n_pad)
                call_span.set_attribute("pad.waste_rows", n_pad - n_real)
                if n_pad > n_real:
                    _PAD_WASTE.inc(n_pad - n_real)
                    qs = np.concatenate(
                        [qs, np.repeat(qs[:1], n_pad - n_real, axis=0)]
                    )
                    if alloweds is not None:
                        alloweds = alloweds + [None] * (n_pad - n_real)
                    if excluded is not None:
                        excluded = list(excluded) + [None] * (n_pad - n_real)
                results = model.top_n_batch(qs, want, alloweds, excluded)
            if self.breaker is not None:
                self.breaker.record_success()
            # trace completeness: the call span must land in the ring
            # BEFORE any waiter's future resolves — a client that has its
            # response may immediately fetch GET /trace?trace_id=, and a
            # trace missing its device call there is a torn read (the
            # sanitized suite widened this executor-side race enough to
            # observe it)
            span_finished = True
            spans.finish_span(call_span)
            for p, res in zip(group, results):
                out = res[p.offset:p.offset + p.how_many]
                loop.call_soon_threadsafe(_set_result, p.future, out)
        except Exception as e:  # noqa: BLE001 — fail the batch, not the loop
            if self.breaker is not None:
                self.breaker.record_failure()
            call_span.record_exception(e)
            if not span_finished:
                span_finished = True
                spans.finish_span(call_span)  # same ordering on the error path
            log.exception(
                "coalesced top-N batch failed; retrying its %d request(s) "
                "individually", len(group),
            )
            self._fallback_individually(loop, model, group, e)
        finally:
            if not span_finished:
                spans.finish_span(call_span)
            loop.call_soon_threadsafe(self._done, loop)

    def _fallback_individually(self, loop, model, group: list[_Pending],
                               batch_exc: BaseException) -> None:
        """Degraded completion of a failed batch: each request re-runs as an
        uncoalesced per-request scan on the same model (the path an open
        breaker routes NEW requests to), so one bad batched program — or an
        injected device fault — costs latency, not errors. A request whose
        fallback also fails gets the ORIGINAL batch exception: that is the
        failure that actually broke it."""
        direct = getattr(model, "top_n", None)
        for p in group:
            if p.deadline is not None and p.deadline.expired():
                loop.call_soon_threadsafe(
                    _set_exception, p.future,
                    resilience.DeadlineExceeded(
                        "deadline expired during degraded retry"
                    ),
                )
                continue
            if direct is None:
                loop.call_soon_threadsafe(_set_exception, p.future, batch_exc)
                continue
            try:
                res = direct(p.vec, p.how_many, p.offset, p.allowed, None,
                             excluded=p.excluded)
            except Exception:  # noqa: BLE001 — the batch exception is the story
                log.exception("degraded per-request fallback also failed")
                loop.call_soon_threadsafe(_set_exception, p.future, batch_exc)
            else:
                _DEGRADED.inc()
                loop.call_soon_threadsafe(_set_result, p.future, res)


def _set_result(future: asyncio.Future, value) -> None:
    if not future.done():
        future.set_result(value)


def _set_exception(future: asyncio.Future, exc: BaseException) -> None:
    if not future.done():
        future.set_exception(exc)
