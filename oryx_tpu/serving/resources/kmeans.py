"""Clustering REST endpoints: /assign, /distanceToNearest, /add.

Equivalent of the reference's clustering resources
(app/oryx-app-serving/.../clustering/Assign.java:51-55,
kmeans/DistanceToNearest.java:39, clustering/Add.java:42-53): a datum is a
delimited line like ``1,-4,3.0``; /assign returns the nearest cluster ID (one
per input line on POST), /distanceToNearest the distance to the closest
center, /add appends data points to the input topic. Scalar responses are
text/plain like the reference.
"""

from __future__ import annotations

from aiohttp import web

from oryx_tpu.common import textutils
from oryx_tpu.models import pmml_common
from oryx_tpu.serving import resource as rsrc
from oryx_tpu.serving.resource import check

# the clustering family reuses a single concrete model: k-means


def _nearest(request: web.Request, datum: str) -> tuple[int, float]:
    check(bool(datum), "Data is needed to cluster")
    model = rsrc.get_serving_model(request)
    tokens = textutils.parse_delimited(datum)
    try:
        vec = pmml_common.features_from_tokens(tokens, model.input_schema)
    except (ValueError, IndexError) as e:
        raise rsrc.OryxServingException(400, f"bad datum: {datum}") from e
    return model.nearest_cluster(vec)


async def assign_get(request: web.Request) -> web.Response:
    cluster_id, _ = _nearest(request, request.match_info["datum"])
    return web.Response(text=str(cluster_id), content_type="text/plain")


async def assign_post(request: web.Request) -> web.Response:
    lines = await rsrc.read_body_lines(request)
    check(bool(lines), "Data is needed to cluster")
    ids = [str(_nearest(request, line)[0]) for line in lines]
    return web.Response(text="\n".join(ids) + "\n", content_type="text/plain")


async def distance_to_nearest(request: web.Request) -> web.Response:
    _, dist = _nearest(request, request.match_info["datum"])
    return web.Response(text=str(dist), content_type="text/plain")


async def add_datum(request: web.Request) -> web.Response:
    await rsrc.send_input_async(request, request.match_info["datum"])
    return web.Response(status=204)


async def add_body(request: web.Request) -> web.Response:
    lines = await rsrc.read_body_lines(request)
    check(bool(lines), "Data is needed")
    await rsrc.send_input_many(request, lines)
    return web.Response(status=204)


def register(app: web.Application) -> None:
    app.router.add_route("GET", "/assign/{datum}", assign_get)
    app.router.add_route("POST", "/assign", assign_post)
    app.router.add_route("GET", "/distanceToNearest/{datum}", distance_to_nearest)
    app.router.add_route("POST", "/add/{datum}", add_datum)
    app.router.add_route("POST", "/add", add_body)

    from oryx_tpu.serving.console import register_console

    register_console(app, "Oryx clustering serving layer", [
        ("GET", "/assign/{datum}", "nearest cluster ID for a datum"),
        ("POST", "/assign", "nearest cluster IDs, one per body line"),
        ("GET", "/distanceToNearest/{datum}", "distance to the closest center"),
        ("POST", "/add/{datum}", "append a data point"),
        ("POST", "/add", "append data points from the body"),
        ("GET", "/metrics", "Prometheus metrics exposition"),
        ("GET", "/trace", "recent + slowest-per-route request traces"),
        ("GET", "/healthz", "liveness probe"),
        ("GET", "/readyz", "readiness probe (model loaded + update lag)"),
    ])
