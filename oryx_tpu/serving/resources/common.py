"""Framework-level endpoints: /ready and /error.

Equivalent of the reference's Ready (app/oryx-app-serving/.../Ready.java:33)
and ErrorResource (framework/oryx-lambda-serving/.../ErrorResource.java:35).
"""

from __future__ import annotations

from aiohttp import web

from oryx_tpu.api.serving import OryxServingException
from oryx_tpu.serving import resource as rsrc


async def ready(request: web.Request) -> web.Response:
    """200 when the model is loaded enough, 503 otherwise (HEAD or GET)."""
    try:
        rsrc.get_serving_model(request)
        return web.Response(status=200)
    except OryxServingException as e:
        return web.Response(status=e.status)


async def error(request: web.Request) -> web.Response:
    """Error page aggregating status/message (ErrorResource)."""
    status = request.query.get("status", "500")
    message = request.query.get("message", "error")
    return web.json_response({"status": int(status), "error": message}, status=int(status))


def register(app: web.Application) -> None:
    app.router.add_route("GET", "/ready", ready)
    app.router.add_route("HEAD", "/ready", ready)
    app.router.add_route("GET", "/error", error)
