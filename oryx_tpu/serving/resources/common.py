"""Framework-level endpoints: /ready, /error, and /metrics.

Equivalent of the reference's Ready (app/oryx-app-serving/.../Ready.java:33)
and ErrorResource (framework/oryx-lambda-serving/.../ErrorResource.java:35);
/metrics is the Prometheus exposition of the process-wide registry
(docs/observability.md) — the stand-in for the reference's Spark-UI/JMX
visibility (SURVEY §5.1).
"""

from __future__ import annotations

from aiohttp import web

from oryx_tpu.api.serving import OryxServingException
from oryx_tpu.common import metrics as metrics_mod
from oryx_tpu.serving import resource as rsrc


async def ready(request: web.Request) -> web.Response:
    """200 when the model is loaded enough, 503 otherwise (HEAD or GET)."""
    try:
        rsrc.get_serving_model(request)
        return web.Response(status=200)
    except OryxServingException as e:
        return web.Response(status=e.status)


async def error(request: web.Request) -> web.Response:
    """Error page aggregating status/message (ErrorResource)."""
    status = request.query.get("status", "500")
    message = request.query.get("message", "error")
    return web.json_response({"status": int(status), "error": message}, status=int(status))


async def metrics(request: web.Request) -> web.Response:
    """Prometheus text exposition of the process-wide metrics registry.
    Exempt from API auth unless ``oryx.metrics.require-auth``."""
    body = metrics_mod.default_registry().render().encode("utf-8")
    return web.Response(body=body,
                        headers={"Content-Type": metrics_mod.CONTENT_TYPE})


def register(app: web.Application) -> None:
    app.router.add_route("GET", "/ready", ready)
    app.router.add_route("HEAD", "/ready", ready)
    app.router.add_route("GET", "/error", error)
    app.router.add_route("GET", "/metrics", metrics)
