"""Framework-level endpoints: /ready, /error, /metrics, /trace, probes,
and the on-demand profiler.

Equivalent of the reference's Ready (app/oryx-app-serving/.../Ready.java:33)
and ErrorResource (framework/oryx-lambda-serving/.../ErrorResource.java:35);
/metrics is the Prometheus exposition of the process-wide registry
(docs/observability.md) — the stand-in for the reference's Spark-UI/JMX
visibility (SURVEY §5.1); /metrics/history serves the in-process
time-series rings behind it (common/tsdb.py). /trace renders the span ring
buffer
(common/spans.py): recent spans, the kept-slowest per route, or one whole
trace by id. /healthz (liveness) and /readyz (readiness: model loaded +
update-consumer lag under ``oryx.serving.ready-max-lag-sec``) are the
load-balancer probe pair — always auth-exempt. POST /debug/profile captures
a timed ``jax.profiler`` trace of the LIVE process through the shared
one-at-a-time session (common/profiling.py) — 409 while another capture is
in flight, auth story identical to /metrics.
"""

from __future__ import annotations

import asyncio

from aiohttp import web

from oryx_tpu.api.serving import OryxServingException
from oryx_tpu.common import blackbox
from oryx_tpu.common import compilecache
from oryx_tpu.common import lineage
from oryx_tpu.common import metrics as metrics_mod
from oryx_tpu.common import profiling
from oryx_tpu.common import slo as slo_mod
from oryx_tpu.common import spans
from oryx_tpu.common import tsdb
from oryx_tpu.serving import resource as rsrc


async def ready(request: web.Request) -> web.Response:
    """200 when the model is loaded enough, 503 otherwise (HEAD or GET)."""
    try:
        rsrc.get_serving_model(request)
        return web.Response(status=200)
    except OryxServingException as e:
        return web.Response(status=e.status)


async def healthz(request: web.Request) -> web.Response:
    """Liveness: the process is up and the event loop is serving requests.
    Deliberately model-agnostic — a layer mid-model-load is alive (restart
    nothing), it is just not READY (send no traffic: that is /readyz)."""
    return web.json_response({"status": "ok"})


def _gauge_value(name: str) -> float:
    gauge = metrics_mod.default_registry().get(name)
    value = float(gauge.value) if gauge is not None else 0.0
    return 0.0 if value != value else value  # NaN (dead callback) -> unknown


async def readyz(request: web.Request) -> web.Response:
    """Readiness for load balancers: 200 only when (a) the model has passed
    ``min-model-load-fraction`` (the PR-2 load-fraction gate) and (b) the
    update consumer is not stale. Stale means BOTH gauges agree: messages
    are waiting behind the broker head (``…update_lag_messages``, probed
    live at read time) AND the consumer has made no progress for more than
    ``oryx.serving.ready-max-lag-sec`` (0 disables the lag check) — a
    quiet topic with nothing to consume is healthy however long it stays
    quiet, while a wedged consumer with a backlog keeps serving the OLD
    model silently, and this gate lets the balancer rotate that replica
    out before users notice. Both gauges are scrape-time callbacks, so the
    probe works even with ``oryx.metrics.enabled = false``.

    With batch-bucket warmup configured (``precompile-batches``), a third
    condition gates readiness: at least ``oryx.compile.ready-warm-fraction``
    of the pow2 bucket ladder must be compiled (default 1.0), so load
    balancers never route into a replica that would answer its first burst
    with XLA compiles. The ``warmup`` detail reports {done, total} buckets;
    once one ladder fully completes, warm-readiness is sticky — a staged
    generation re-warming off-path must not drop the replica out."""
    detail: dict = {}
    ok = True
    try:
        rsrc.get_serving_model(request)
        detail["model"] = "loaded"
    except OryxServingException:
        detail["model"] = "not loaded"
        ok = False
    config = request.app[rsrc.CONFIG_KEY]
    warm = compilecache.warmup_state()
    detail["warmup"] = warm.snapshot()
    warm_fraction = config.get_float("oryx.compile.ready-warm-fraction", 1.0)
    if not warm.ready(warm_fraction):
        detail["warmup_status"] = "cold"
        ok = False
    max_lag = config.get_float("oryx.serving.ready-max-lag-sec", 600.0)
    detail["ready_max_lag_sec"] = max_lag
    if max_lag > 0:
        lag_sec = _gauge_value("oryx_serving_update_lag_seconds")
        lag_msgs = _gauge_value("oryx_serving_update_lag_messages")
        detail["update_lag_sec"] = round(lag_sec, 3)
        detail["update_lag_messages"] = int(lag_msgs)
        if lag_msgs > 0 and lag_sec > max_lag:
            detail["update_consumer"] = "stale"
            ok = False
    # active SLO burn-rate alerts ride the probe body (docs/slo.md) so
    # anything watching /readyz sees budget exhaustion — INFORMATIONAL
    # only: a replica burning budget is exactly the replica that must NOT
    # be rotated out of the balancer (less capacity burns faster). The
    # evaluation takes the engine lock + registry family locks, so it
    # hops to a worker thread like every other blocking probe read.
    detail["slo_alerts"] = await asyncio.to_thread(slo_mod.active_alerts)
    # trend alerts (common/tsdb.py) ride the same way and are equally
    # INFORMATIONAL: a replica whose queue depth is ramping toward its cap
    # needs traffic shifted TO its peers, not a readiness failure
    detail["trend_alerts"] = tsdb.trend_alerts()
    detail["status"] = "ready" if ok else "unavailable"
    return web.json_response(detail, status=200 if ok else 503)


async def error(request: web.Request) -> web.Response:
    """Error page aggregating status/message (ErrorResource)."""
    status = request.query.get("status", "500")
    message = request.query.get("message", "error")
    return web.json_response({"status": int(status), "error": message}, status=int(status))


async def metrics(request: web.Request) -> web.Response:
    """Prometheus text exposition of the process-wide metrics registry.
    Exempt from API auth unless ``oryx.metrics.require-auth``. An Accept
    header asking for OpenMetrics gets that format WITH trace-id exemplars
    on the latency histograms (the 0.0.4 text parser would reject them)."""
    openmetrics = "application/openmetrics-text" in request.headers.get(
        "Accept", ""
    )
    body = metrics_mod.default_registry().render(
        exemplars=openmetrics
    ).encode("utf-8")
    content_type = (
        metrics_mod.OPENMETRICS_CONTENT_TYPE if openmetrics
        else metrics_mod.CONTENT_TYPE
    )
    return web.Response(body=body, headers={"Content-Type": content_type})


async def metrics_history(request: web.Request) -> web.Response:
    """JSON time series from the in-process tsdb rings (common/tsdb.py,
    docs/observability.md "Time series & trends"): per-signal
    ``{unit, points: [[ts, value], ...]}`` plus active trend alerts.
    ``?signal=a,b`` keeps only the named signals; ``?since=<unix-ts>``
    keeps only points strictly newer (pollers — fleet-status --watch —
    pass the last ts they saw). Walking the rings takes their locks, so
    the read hops to a worker thread like every other blocking probe.
    Auth story = /metrics (exempt unless ``oryx.metrics.require-auth``)."""
    signal = request.query.get("signal")
    signals = None
    if signal:
        signals = {s for s in signal.replace(",", " ").split() if s}
    since = None
    raw_since = request.query.get("since")
    if raw_since:
        try:
            since = float(raw_since)
        except ValueError as e:
            raise OryxServingException(400, "bad since") from e
    payload = await asyncio.to_thread(tsdb.history_payload, signals, since)
    return web.json_response(payload)


async def trace(request: web.Request) -> web.Response:
    """JSON view of the span ring buffer (auth story identical to /metrics).

    ``?trace_id=<32hex>`` returns every buffered span of one trace (what
    ``tools/trace_summary.py --trace-id`` renders as a tree); otherwise the
    most recent ``?limit=`` spans (default 100) plus the kept-slowest spans
    per route — the p99 outliers survive ring wrap by design."""
    recorder = spans.default_recorder()
    trace_id = request.query.get("trace_id")
    if trace_id:
        hits = recorder.spans(trace_id=trace_id)
        return web.json_response({
            "trace_id": trace_id,
            "spans": [s.to_dict() for s in hits],
        })
    try:
        limit = max(1, int(request.query.get("limit", "100")))
    except ValueError as e:
        raise OryxServingException(400, "bad limit") from e
    return web.json_response({
        "enabled": spans.enabled(),
        "stats": recorder.stats(),
        "recent": [s.to_dict() for s in recorder.spans(limit=limit)],
        "slowest_by_route": {
            route: [s.to_dict() for s in slow]
            for route, slow in sorted(recorder.slowest().items())
        },
    })


async def lineage_view(request: web.Request) -> web.Response:
    """Model lineage console (docs/observability.md "Model lineage &
    freshness"): the provenance chain of the live and staged generations —
    generation id, checkpoint fingerprint, resume/scratch origin, the
    per-partition input offsets each generation trained through, its
    publish→consume→warm→live→first-query adoption timeline — plus the
    speed-tier delta watermark and the derived freshness numbers. This is
    the attributability loop closer: take ``x-oryx-model-generation`` off
    any response, look its offsets up here, and you know exactly which
    input data produced that answer. Auth story = /metrics (exempt unless
    ``oryx.metrics.require-auth``)."""
    snapshot = await asyncio.to_thread(lineage.tracker().snapshot)
    snapshot["enabled"] = lineage.enabled()
    return web.json_response(snapshot)


async def debug_profile(request: web.Request) -> web.Response:
    """On-demand device profiling of the live process:
    ``POST /debug/profile?seconds=N`` captures a ``jax.profiler`` trace for
    N seconds (clamped to ``oryx.profiling.max-capture-sec``) and answers
    with the trace directory — readable by TensorBoard/XProf or
    ``python -m oryx_tpu.tools.trace_summary <dir>``. Exactly ONE capture
    may be in flight per process (jax's own constraint): a concurrent
    request answers 409 naming the current owner. The capture runs in a
    worker thread (``asyncio.to_thread``) so the event loop keeps serving
    — profiling a replica must not stall its traffic. Auth story = /metrics
    (exempt unless ``oryx.metrics.require-auth``)."""
    config = request.app[rsrc.CONFIG_KEY]
    try:
        seconds = float(request.query.get("seconds", "3"))
    except ValueError as e:
        raise OryxServingException(400, "bad seconds") from e
    max_seconds = config.get_float("oryx.profiling.max-capture-sec", 60.0)
    rsrc.check(seconds > 0, "seconds must be positive")
    rsrc.check(seconds <= max_seconds,
               f"seconds capped at {max_seconds:g} "
               "(oryx.profiling.max-capture-sec)")
    session = profiling.profile_session()
    if session.busy():
        # fast-path refusal; the start() inside capture() still guards the
        # race where two requests pass this check together
        raise OryxServingException(
            409, f"profiler capture already in flight "
                 f"(owner={session.owner()!r})"
        )
    try:
        # dir creation + capture are ONE worker-thread hop: both block, and
        # neither may stall the loop of the replica being profiled
        trace_dir = await asyncio.to_thread(
            profiling.timed_capture,
            config.get_string("oryx.profiling.profile-dir", None),
            seconds, "debug-endpoint",
        )
    except profiling.ProfileBusyError as e:
        raise OryxServingException(409, str(e)) from e
    return web.json_response({
        "trace_dir": trace_dir,
        "seconds": seconds,
        "hint": f"python -m oryx_tpu.tools.trace_summary {trace_dir}",
    })


async def debug_bundle(request: web.Request) -> web.Response:
    """The black-box flight recorder's one-call postmortem artifact
    (common/blackbox.py): event ring + metrics snapshot + slowest traces
    + SLO status + redacted config + device/host memory + versions, as a
    single JSON document. Assembly walks the registry and the span
    reservoir, so it runs in a worker thread like /debug/profile — a
    postmortem pull must not stall the replica being diagnosed. Auth
    story = /metrics (exempt unless ``oryx.metrics.require-auth``).
    The same bundle auto-dumps to ``oryx.blackbox.dump-dir`` on SIGTERM,
    breaker-open/quarantine edges, and the periodic flight-recorder tick
    — this endpoint is the live view of what a dead replica would have
    left on disk."""
    payload = await asyncio.to_thread(blackbox.bundle, "endpoint")
    return web.json_response(payload)


def register(app: web.Application) -> None:
    app.router.add_route("GET", "/ready", ready)
    app.router.add_route("HEAD", "/ready", ready)
    app.router.add_route("GET", "/healthz", healthz)
    app.router.add_route("HEAD", "/healthz", healthz)
    app.router.add_route("GET", "/readyz", readyz)
    app.router.add_route("HEAD", "/readyz", readyz)
    app.router.add_route("GET", "/error", error)
    app.router.add_route("GET", "/metrics", metrics)
    app.router.add_route("GET", "/metrics/history", metrics_history)
    app.router.add_route("GET", "/trace", trace)
    app.router.add_route("GET", "/lineage", lineage_view)
    app.router.add_route("POST", "/debug/profile", debug_profile)
    app.router.add_route("GET", "/debug/bundle", debug_bundle)
