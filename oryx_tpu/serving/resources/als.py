"""ALS REST endpoints — the full recommender API surface.

Equivalent of the reference's app/oryx-app-serving ALS resources (SURVEY §2.11
endpoint inventory; per-class citations inline). Handlers are async; device
calls (top-N matmuls) run in the default executor so the event loop never
blocks on the accelerator.

All endpoints produce JSON (default) or CSV (Accept: text/csv).
"""

from __future__ import annotations

import asyncio
import time

import numpy as np
from aiohttp import web

from oryx_tpu.api.serving import OryxServingException
from oryx_tpu.common import textutils
from oryx_tpu.ops import vectormath as vm
from oryx_tpu.serving import resource as rsrc
from oryx_tpu.serving.resource import (
    check,
    check_exists,
    get_how_many_offset,
    get_rescorer_params,
    id_count,
    id_value,
    parse_id_value_pairs,
    render,
    split_path_list,
)


def _als_model(request: web.Request):
    return rsrc.get_serving_model(request)


def _rescorer_provider(request: web.Request):
    return getattr(rsrc.get_manager(request), "rescorer_provider", None)


async def _run(request, fn, *args):
    # to_thread (not run_in_executor) carries contextvars: device work in
    # the worker keeps the request's ingress span current, so spans opened
    # inside (and any histogram exemplars) land in the right trace
    return await asyncio.to_thread(fn, *args)


async def _top_n(request, model, vec, how_many, offset, allowed, rescore,
                 excluded):
    """Recommend-family top-N: coalesced into one batched device call with
    concurrent requests when no score-rewriting rescorer applies (a shared
    scan cannot honor per-request rescore hooks).

    Degraded mode: while the device-call circuit breaker is OPEN
    (``coalescer.admit()`` false), requests bypass the coalescer and run
    per-request scans on the current model — slower, but answering — until
    a half-open probe through the coalesced path closes the breaker."""
    coalescer = request.app.get(rsrc.COALESCER_KEY)
    if coalescer is not None and rescore is None and coalescer.admit():
        return await coalescer.top_n(model, vec, how_many, offset, allowed,
                                     excluded)
    return await _run(
        request,
        lambda: model.top_n(vec, how_many, offset, allowed, rescore,
                            excluded=excluded),
    )


def _combine_allowed_rescore(allowed, rescorer):
    if rescorer is None:
        return allowed, None
    base_allowed = allowed

    def allowed2(id_):
        if base_allowed is not None and not base_allowed(id_):
            return False
        return not rescorer.is_filtered(id_)

    return allowed2, rescorer.rescore


# ---------------------------------------------------------------------------
# Recommendation endpoints
# ---------------------------------------------------------------------------


async def recommend(request: web.Request) -> web.Response:
    """GET /recommend/{userID} (als/Recommend.java:68-114)."""
    model = _als_model(request)
    user = request.match_info["userID"]
    how_many, offset = get_how_many_offset(request)
    consider_known = request.query.get("considerKnownItems", "false") == "true"
    uv = check_exists(model.get_user_vector(user), user)
    # known-item filtering rides the scan as a device-side mask (the sharded
    # path needs no host fallback); rescorer hooks stay host-side callables
    known = set() if consider_known else model.get_known_items(user)
    provider = _rescorer_provider(request)
    rescorer = (
        provider.get_recommend_rescorer([user], get_rescorer_params(request))
        if provider
        else None
    )
    allowed, rescore = _combine_allowed_rescore(None, rescorer)
    results = await _top_n(
        request, model, uv, how_many, offset, allowed, rescore, known
    )
    return render(request, [id_value(i, s) for i, s in results])


async def recommend_to_many(request: web.Request) -> web.Response:
    """GET /recommendToMany/{userID...} — mean of user vectors
    (als/RecommendToMany.java:56)."""
    model = _als_model(request)
    users = split_path_list(request.match_info["userIDs"])
    how_many, offset = get_how_many_offset(request)
    consider_known = request.query.get("considerKnownItems", "false") == "true"
    vectors = [v for u in users if (v := model.get_user_vector(u)) is not None]
    check(bool(vectors), "no known users", 404)
    mean_vec = np.mean(vectors, axis=0)
    known: set[str] = set()
    if not consider_known:
        for u in users:
            known |= model.get_known_items(u)
    provider = _rescorer_provider(request)
    rescorer = (
        provider.get_recommend_rescorer(users, get_rescorer_params(request))
        if provider
        else None
    )
    allowed, rescore = _combine_allowed_rescore(None, rescorer)
    results = await _top_n(
        request, model, mean_vec, how_many, offset, allowed, rescore, known
    )
    return render(request, [id_value(i, s) for i, s in results])


async def recommend_to_anonymous(request: web.Request) -> web.Response:
    """GET /recommendToAnonymous/{itemID=value...} — fold-in synthesized user
    (als/RecommendToAnonymous.java:58)."""
    model = _als_model(request)
    pairs = parse_id_value_pairs(split_path_list(request.match_info["items"]))
    how_many, offset = get_how_many_offset(request)
    vec = await _run(request, lambda: model.build_temporary_user_vector(pairs))
    check(vec is not None, "no solver available for model yet", 503)
    context_items = {i for i, _ in pairs}
    provider = _rescorer_provider(request)
    rescorer = (
        provider.get_recommend_to_anonymous_rescorer(
            [i for i, _ in pairs], get_rescorer_params(request)
        )
        if provider
        else None
    )
    allowed, rescore = _combine_allowed_rescore(None, rescorer)
    results = await _top_n(
        request, model, vec, how_many, offset, allowed, rescore, context_items
    )
    return render(request, [id_value(i, s) for i, s in results])


async def recommend_with_context(request: web.Request) -> web.Response:
    """GET /recommendWithContext/{userID}/{itemID...}
    (als/RecommendWithContext.java:58)."""
    model = _als_model(request)
    user = request.match_info["userID"]
    pairs = parse_id_value_pairs(split_path_list(request.match_info["items"]))
    how_many, offset = get_how_many_offset(request)
    consider_known = request.query.get("considerKnownItems", "false") == "true"
    uv = check_exists(model.get_user_vector(user), user)
    vec = await _run(request, lambda: model.build_temporary_user_vector(pairs, uv))
    check(vec is not None, "no solver available for model yet", 503)
    known = {i for i, _ in pairs}
    if not consider_known:
        known |= model.get_known_items(user)
    provider = _rescorer_provider(request)
    rescorer = (
        provider.get_recommend_rescorer([user], get_rescorer_params(request))
        if provider
        else None
    )
    allowed, rescore = _combine_allowed_rescore(None, rescorer)
    results = await _top_n(
        request, model, vec, how_many, offset, allowed, rescore, known
    )
    return render(request, [id_value(i, s) for i, s in results])


# ---------------------------------------------------------------------------
# Similarity / estimation
# ---------------------------------------------------------------------------


async def similarity(request: web.Request) -> web.Response:
    """GET /similarity/{itemID...} — mean cosine top-N (als/Similarity.java:59)."""
    model = _als_model(request)
    items = split_path_list(request.match_info["items"])
    how_many, offset = get_how_many_offset(request)
    vectors = [v for i in items if (v := model.get_item_vector(i)) is not None]
    check(bool(vectors), "no known items", 404)
    exclude = set(items)
    results = await _run(
        request,
        lambda: model.top_n_cosine(
            np.stack(vectors), how_many, offset, lambda i: i not in exclude
        ),
    )
    return render(request, [id_value(i, s) for i, s in results])


async def similarity_to_item(request: web.Request) -> web.Response:
    """GET /similarityToItem/{toItemID}/{itemID...} — pairwise cosines
    (als/SimilarityToItem.java:43)."""
    model = _als_model(request)
    to_item = request.match_info["toItemID"]
    items = split_path_list(request.match_info["items"])
    to_vec = check_exists(model.get_item_vector(to_item), to_item)
    norm_to = float(np.linalg.norm(to_vec))
    vecs = []
    for i in items:
        v = model.get_item_vector(i)
        check_exists(v, i)
        vecs.append(v)
    # the jnp dispatch (and its first-call XLA compile, ~600 ms) must not
    # run on the event loop — the sanitizer's loop-stall watchdog caught
    # exactly that here; one executor hop covers the whole pair list, and
    # the cosines are batched into ONE device call + one transfer (the
    # per-pair float() loop was one blocking sync per item)
    sims = await _run(
        request,
        lambda: vm.cosine_similarities(np.stack(vecs), to_vec, norm_to).tolist(),
    )
    return render(request, [id_value(i, s) for i, s in zip(items, sims)])


async def estimate(request: web.Request) -> web.Response:
    """GET /estimate/{userID}/{itemID...} — dot products (als/Estimate.java:50)."""
    model = _als_model(request)
    user = request.match_info["userID"]
    items = split_path_list(request.match_info["items"])
    uv = check_exists(model.get_user_vector(user), user)
    dots = model.dot_with_items(uv, items)
    return render(request, [id_value(i, d) for i, d in zip(items, dots)])


async def estimate_for_anonymous(request: web.Request) -> web.Response:
    """GET /estimateForAnonymous/{toItemID}/{itemID=value...}
    (als/EstimateForAnonymous.java:47)."""
    model = _als_model(request)
    to_item = request.match_info["toItemID"]
    pairs = parse_id_value_pairs(split_path_list(request.match_info["items"]))
    to_vec = check_exists(model.get_item_vector(to_item), to_item)
    vec = await _run(request, lambda: model.build_temporary_user_vector(pairs))
    check(vec is not None, "no solver available for model yet", 503)
    return render(request, float(np.dot(vec, to_vec)))


async def because(request: web.Request) -> web.Response:
    """GET /because/{userID}/{itemID} — known items most similar to the item
    (als/Because.java:51)."""
    model = _als_model(request)
    user = request.match_info["userID"]
    item = request.match_info["itemID"]
    how_many, offset = get_how_many_offset(request)
    item_vec = check_exists(model.get_item_vector(item), item)
    known_vecs = model.get_known_item_vectors_for_user(user)
    if not known_vecs:
        return render(request, [])
    norm = float(np.linalg.norm(item_vec))
    # same loop-stall hazard as similarity_to_item: per-pair jnp dispatch
    # off the event loop in one hop, cosines batched into one device call
    sim_vals = await _run(
        request,
        lambda: vm.cosine_similarities(
            np.stack([v for _, v in known_vecs]), item_vec, norm
        ).tolist(),
    )
    sims = list(zip((i for i, _ in known_vecs), sim_vals))
    sims.sort(key=lambda t: -t[1])
    return render(request, [id_value(i, s) for i, s in sims[offset:offset + how_many]])


async def most_surprising(request: web.Request) -> web.Response:
    """GET /mostSurprising/{userID} — known items with lowest estimate
    (als/MostSurprising.java:53)."""
    model = _als_model(request)
    user = request.match_info["userID"]
    how_many, offset = get_how_many_offset(request)
    uv = check_exists(model.get_user_vector(user), user)
    known_vecs = model.get_known_item_vectors_for_user(user)
    if not known_vecs:
        return render(request, [])
    dots = [(i, float(np.dot(uv, v))) for i, v in known_vecs]
    dots.sort(key=lambda t: t[1])  # ascending: most surprising first
    return render(request, [id_value(i, s) for i, s in dots[offset:offset + how_many]])


# ---------------------------------------------------------------------------
# Popularity / inventory
# ---------------------------------------------------------------------------


async def popular_representative_items(request: web.Request) -> web.Response:
    """GET /popularRepresentativeItems — top item per feature dimension
    (als/PopularRepresentativeItems.java:42)."""
    model = _als_model(request)

    def compute():
        items = []
        for f in range(model.features):
            unit = np.zeros(model.features, dtype=np.float32)
            unit[f] = 1.0
            top = model.top_n(unit, 1)
            items.append(top[0][0] if top else None)
        return items

    return render(request, await _run(request, compute))


def _top_counts(counts, how_many, offset, rescorer):
    pairs = list(counts.items())
    if rescorer is not None:
        pairs = [(i, c) for i, c in pairs if not rescorer.is_filtered(i)]
    pairs.sort(key=lambda t: -t[1])
    return [id_count(i, c) for i, c in pairs[offset:offset + how_many]]


async def most_popular_items(request: web.Request) -> web.Response:
    """GET /mostPopularItems (als/MostPopularItems.java:51)."""
    model = _als_model(request)
    how_many, offset = get_how_many_offset(request)
    provider = _rescorer_provider(request)
    rescorer = (
        provider.get_most_popular_items_rescorer(get_rescorer_params(request))
        if provider
        else None
    )
    return render(request, _top_counts(model.item_counts(), how_many, offset, rescorer))


async def most_active_users(request: web.Request) -> web.Response:
    """GET /mostActiveUsers (als/MostActiveUsers.java:46)."""
    model = _als_model(request)
    how_many, offset = get_how_many_offset(request)
    provider = _rescorer_provider(request)
    rescorer = (
        provider.get_most_active_users_rescorer(get_rescorer_params(request))
        if provider
        else None
    )
    return render(request, _top_counts(model.user_counts(), how_many, offset, rescorer))


async def known_items(request: web.Request) -> web.Response:
    """GET /knownItems/{userID} (als/KnownItems.java:34)."""
    model = _als_model(request)
    user = request.match_info["userID"]
    return render(request, sorted(model.get_known_items(user)))


async def all_user_ids(request: web.Request) -> web.Response:
    """GET /user/allIDs (als/AllUserIDs.java:33)."""
    return render(request, _als_model(request).all_user_ids())


async def all_item_ids(request: web.Request) -> web.Response:
    """GET /item/allIDs (als/AllItemIDs.java:33)."""
    return render(request, _als_model(request).all_item_ids())


# ---------------------------------------------------------------------------
# Writes
# ---------------------------------------------------------------------------


async def set_preference(request: web.Request) -> web.Response:
    """POST /pref/{userID}/{itemID} with strength body (als/Preference.java:41)."""
    user = request.match_info["userID"]
    item = request.match_info["itemID"]
    body = (await request.text()).strip()
    if body:
        try:
            float(body)
        except ValueError as e:
            raise OryxServingException(400, f"bad strength: {body}") from e
    strength = body if body else "1"
    line = textutils.join_delimited([user, item, strength, int(time.time() * 1000)])
    await rsrc.send_input_async(request, line)
    return web.Response(status=200)


async def delete_preference(request: web.Request) -> web.Response:
    """DELETE /pref/{userID}/{itemID} — empty strength = delete
    (als/Preference.java:69)."""
    user = request.match_info["userID"]
    item = request.match_info["itemID"]
    line = textutils.join_delimited([user, item, "", int(time.time() * 1000)])
    await rsrc.send_input_async(request, line)
    return web.Response(status=200)


async def ingest(request: web.Request) -> web.Response:
    """POST /ingest — bulk CSV, gzip/zip/multipart (als/Ingest.java:60-100)."""
    lines = await rsrc.read_body_lines(request)
    for line in lines:
        tokens = textutils.parse_csv(line)
        check(2 <= len(tokens) <= 4, f"bad line: {line}")
    await rsrc.send_input_many(request, lines)
    return web.Response(status=200)


def register(app: web.Application) -> None:
    r = app.router
    r.add_get("/recommend/{userID}", recommend)
    r.add_get("/recommendToMany/{userIDs:.+}", recommend_to_many)
    r.add_get("/recommendToAnonymous/{items:.+}", recommend_to_anonymous)
    r.add_get("/recommendWithContext/{userID}/{items:.+}", recommend_with_context)
    r.add_get("/similarity/{items:.+}", similarity)
    r.add_get("/similarityToItem/{toItemID}/{items:.+}", similarity_to_item)
    r.add_get("/knownItems/{userID}", known_items)
    r.add_get("/estimate/{userID}/{items:.+}", estimate)
    r.add_get("/estimateForAnonymous/{toItemID}/{items:.+}", estimate_for_anonymous)
    r.add_get("/because/{userID}/{itemID}", because)
    r.add_get("/mostSurprising/{userID}", most_surprising)
    r.add_get("/popularRepresentativeItems", popular_representative_items)
    r.add_get("/mostActiveUsers", most_active_users)
    r.add_get("/mostPopularItems", most_popular_items)
    r.add_get("/user/allIDs", all_user_ids)
    r.add_get("/item/allIDs", all_item_ids)
    r.add_post("/pref/{userID}/{itemID}", set_preference)
    r.add_delete("/pref/{userID}/{itemID}", delete_preference)
    r.add_post("/ingest", ingest)

    from oryx_tpu.serving.console import register_console

    register_console(app, "Oryx ALS serving layer", [
        ("GET", "/recommend/{userID}", "top-N recommendations for a user"),
        ("GET", "/recommendToMany/{userID}/...", "recommendations for several users"),
        ("GET", "/recommendToAnonymous/{itemID=value}/...", "recs from item interactions"),
        ("GET", "/recommendWithContext/{userID}/{itemID}/...", "user recs blended with context items"),
        ("GET", "/similarity/{itemID}/...", "items similar to items"),
        ("GET", "/similarityToItem/{toItemID}/{itemID}/...", "pairwise similarities"),
        ("GET", "/knownItems/{userID}", "items the user interacted with"),
        ("GET", "/estimate/{userID}/{itemID}/...", "estimated strengths"),
        ("GET", "/estimateForAnonymous/{toItemID}/{itemID=value}/...", "fold-in estimate"),
        ("GET", "/because/{userID}/{itemID}", "known items explaining a rec"),
        ("GET", "/mostSurprising/{userID}", "known items with lowest estimate"),
        ("GET", "/popularRepresentativeItems", "one item per hash partition"),
        ("GET", "/mostActiveUsers", "users with most known items"),
        ("GET", "/mostPopularItems", "items known to most users"),
        ("GET", "/user/allIDs", "all user IDs"),
        ("GET", "/item/allIDs", "all item IDs"),
        ("POST", "/pref/{userID}/{itemID}", "write a preference"),
        ("DELETE", "/pref/{userID}/{itemID}", "delete a preference"),
        ("POST", "/ingest", "bulk CSV ingest"),
        ("GET", "/metrics", "Prometheus metrics exposition"),
        ("GET", "/trace", "recent + slowest-per-route request traces"),
        ("GET", "/healthz", "liveness probe"),
        ("GET", "/readyz", "readiness probe (model loaded + update lag)"),
    ])
