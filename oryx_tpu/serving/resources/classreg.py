"""Classification/regression + RDF REST endpoints.

Equivalent of the reference's classreg and rdf resources
(app/oryx-app-serving/.../classreg/Predict.java:51-99, Train.java:41-52,
rdf/ClassificationDistribution.java:52-77, rdf/FeatureImportance.java:45-69):
/predict returns the forest vote per datum line (category value or numeric
score); /train appends training data to the input topic;
/classificationDistribution returns per-class probabilities as IDValues;
/feature/importance returns forest importances.
"""

from __future__ import annotations

from aiohttp import web

from oryx_tpu.common import textutils
from oryx_tpu.serving import resource as rsrc
from oryx_tpu.serving.resource import check


def _predict_one(request: web.Request, datum: str) -> str:
    check(bool(datum), "Missing input data")
    model = rsrc.get_serving_model(request)
    tokens = textutils.parse_delimited(datum)
    try:
        return model.predict(tokens)
    except (ValueError, KeyError, IndexError) as e:
        raise rsrc.OryxServingException(400, f"bad datum: {datum}") from e


async def predict_get(request: web.Request) -> web.Response:
    return web.Response(
        text=_predict_one(request, request.match_info["datum"]),
        content_type="text/plain",
    )


async def predict_post(request: web.Request) -> web.Response:
    lines = await rsrc.read_body_lines(request)
    check(bool(lines), "Missing input data")
    predictions = [_predict_one(request, line) for line in lines]
    return rsrc.render(request, predictions)


async def train_datum(request: web.Request) -> web.Response:
    await rsrc.send_input_async(request, request.match_info["datum"])
    return web.Response(status=204)


async def train_body(request: web.Request) -> web.Response:
    lines = await rsrc.read_body_lines(request)
    check(bool(lines), "Missing input data")
    await rsrc.send_input_many(request, lines)
    return web.Response(status=204)


async def classification_distribution(request: web.Request) -> web.Response:
    datum = request.match_info["datum"]
    check(bool(datum), "Missing input data")
    model = rsrc.get_serving_model(request)
    schema = model.input_schema
    check(schema.is_classification(), "Only applicable for classification")
    try:
        prediction = model.make_prediction(textutils.parse_delimited(datum))
    except (ValueError, KeyError, IndexError) as e:
        raise rsrc.OryxServingException(400, f"bad datum: {datum}") from e
    probabilities = prediction.category_probabilities
    e2v = model.encodings.get_encoding_value_map(schema.target_feature_index)
    return rsrc.render(
        request,
        [rsrc.id_value(e2v[i], float(p)) for i, p in enumerate(probabilities)],
    )


async def feature_importance(request: web.Request) -> web.Response:
    model = rsrc.get_serving_model(request)
    importances = [float(v) for v in model.forest.feature_importances]
    return rsrc.render(request, importances)


async def feature_importance_one(request: web.Request) -> web.Response:
    model = rsrc.get_serving_model(request)
    importances = model.forest.feature_importances
    try:
        n = int(request.match_info["featureNumber"])
    except ValueError as e:
        raise rsrc.OryxServingException(400, "Bad feature number") from e
    check(0 <= n < len(importances), "Bad feature number")
    return web.Response(text=str(float(importances[n])), content_type="text/plain")


def register(app: web.Application) -> None:
    app.router.add_route("GET", "/predict/{datum}", predict_get)
    app.router.add_route("POST", "/predict", predict_post)
    app.router.add_route("POST", "/train/{datum}", train_datum)
    app.router.add_route("POST", "/train", train_body)
    app.router.add_route(
        "GET", "/classificationDistribution/{datum}", classification_distribution
    )
    app.router.add_route("GET", "/feature/importance", feature_importance)
    app.router.add_route(
        "GET", "/feature/importance/{featureNumber}", feature_importance_one
    )

    from oryx_tpu.serving.console import register_console

    register_console(app, "Oryx classification/regression serving layer", [
        ("GET", "/predict/{datum}", "forest vote for one datum"),
        ("POST", "/predict", "forest votes, one per body line"),
        ("POST", "/train/{datum}", "append one training example"),
        ("POST", "/train", "append training examples from the body"),
        ("GET", "/classificationDistribution/{datum}", "per-class probabilities"),
        ("GET", "/feature/importance", "all feature importances"),
        ("GET", "/feature/importance/{n}", "one feature's importance"),
        ("GET", "/metrics", "Prometheus metrics exposition"),
        ("GET", "/trace", "recent + slowest-per-route request traces"),
        ("GET", "/healthz", "liveness probe"),
        ("GET", "/readyz", "readiness probe (model loaded + update lag)"),
    ])
