"""Speed-tier SPI.

Equivalent of the reference's SpeedModelManager / SpeedModel
(framework/oryx-api/.../speed/SpeedModelManager.java:50-98, SpeedModel.java)
plus the key/message-dispatch convenience base AbstractSpeedModelManager.
"""

from __future__ import annotations

import abc
from typing import Iterable, Iterator, Sequence

from oryx_tpu.api.keymessage import KeyMessage


class SpeedModel(abc.ABC):
    @abc.abstractmethod
    def get_fraction_loaded(self) -> float:
        """Readiness gate in [0,1] (SpeedModel.java)."""


class SpeedModelManager(abc.ABC):
    """Consumes the update topic to maintain an in-memory reference model, and
    turns each input microbatch into incremental model updates."""

    @abc.abstractmethod
    def consume(self, updates: Iterator[KeyMessage]) -> None:
        """Blocking loop over update-topic messages (MODEL/MODEL-REF/UP)."""

    @abc.abstractmethod
    def build_updates(self, new_data: Sequence[KeyMessage]) -> Iterable[str]:
        """Incremental updates for one microbatch, published with key "UP"."""

    def close(self) -> None:
        pass


class AbstractSpeedModelManager(SpeedModelManager):
    """Dispatches each consumed message to consume_key_message
    (AbstractSpeedModelManager.java:48-67)."""

    def consume(self, updates: Iterator[KeyMessage]) -> None:
        for km in updates:
            self.consume_key_message(km.key, km.message)

    @abc.abstractmethod
    def consume_key_message(self, key: str, message: str) -> None:
        ...
