"""Batch-tier SPI.

Equivalent of the reference's BatchLayerUpdate
(framework/oryx-api/.../batch/BatchLayerUpdate.java:38-59), with jax-friendly
types: new/past data arrive as lists of KeyMessage (host side; implementations
move them onto the mesh), the Spark context becomes a ComputeContext.
"""

from __future__ import annotations

import abc
from typing import Sequence

from oryx_tpu.api.keymessage import KeyMessage


class BatchLayerUpdate(abc.ABC):
    """Implementations define one batch generation: read new+past data, build
    and publish a model."""

    @abc.abstractmethod
    def run_update(
        self,
        context,  # ComputeContext
        timestamp_ms: int,
        new_data: Sequence[KeyMessage],
        past_data: Sequence[KeyMessage],
        model_dir: str,
        model_update_topic,  # TopicProducerImpl | None
    ) -> None:
        ...
