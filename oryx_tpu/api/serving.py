"""Serving-tier SPI.

Equivalent of the reference's ServingModelManager / ServingModel /
OryxServingException (framework/oryx-api/.../serving/ServingModelManager.java:48-66,
ServingModel.java, OryxServingException.java) plus the dispatch base
AbstractServingModelManager.
"""

from __future__ import annotations

import abc
from typing import Iterator

from oryx_tpu.api.keymessage import KeyMessage
from oryx_tpu.common import metrics as metrics_mod

_MODEL_GENERATIONS = metrics_mod.default_registry().counter(
    "oryx_serving_model_generation_total",
    "MODEL/MODEL-REF handoffs consumed by the serving model manager",
)


class ServingModel(abc.ABC):
    @abc.abstractmethod
    def get_fraction_loaded(self) -> float:
        """Readiness gate in [0,1]; requests 503 until this passes the
        configured min-model-load-fraction."""


class OryxServingException(Exception):
    """Status + message carrier mapped to HTTP error responses."""

    def __init__(self, status: int, message: str = ""):
        super().__init__(message or str(status))
        self.status = status
        self.message = message or str(status)


class OverloadedException(OryxServingException):
    """Load shed: the serving tier refused the request up front (503 with a
    Retry-After hint) because its coalescer queue is past the configured
    depth — fail fast and cheap instead of queueing into timeout."""

    def __init__(self, message: str = "overloaded; retry later",
                 retry_after_sec: float = 1.0):
        super().__init__(503, message)
        self.retry_after_sec = retry_after_sec


class ServingModelManager(abc.ABC):
    """Maintains the in-memory serving model from the update topic."""

    def __init__(self, config=None):
        self._config = config

    @abc.abstractmethod
    def consume(self, updates: Iterator[KeyMessage]) -> None:
        ...

    def get_config(self):
        return self._config

    @abc.abstractmethod
    def get_model(self) -> ServingModel | None:
        ...

    def get_staged_model(self) -> ServingModel | None:
        """The incoming model generation being double-buffered for a
        prewarmed swap, if any. Managers that swap in place return None;
        the serving batch warmer warms whatever this returns FIRST, then
        calls :meth:`promote_staged` to flip it into service."""
        return None

    def promote_staged(self, expected=None) -> bool:
        """Atomically promote the staged generation into service after its
        off-path warmup completed. ``expected`` (when given) must still BE
        the staged model — a later push may have replaced it mid-warm, and
        flipping an unwarmed replacement would defeat the prewarm. Returns
        True when a flip happened."""
        return False

    def is_read_only(self) -> bool:
        cfg = self.get_config()
        return bool(cfg and cfg.get_bool("oryx.serving.api.read-only", False))

    def close(self) -> None:
        pass


class AbstractServingModelManager(ServingModelManager):
    """Dispatches each consumed message to consume_key_message
    (AbstractServingModelManager.java:88)."""

    def consume(self, updates: Iterator[KeyMessage]) -> None:
        from oryx_tpu.common import blackbox, lineage

        for km in updates:
            is_model = km.key in ("MODEL", "MODEL-REF")
            if is_model:
                # counted before dispatch so every app family (ALS, k-means,
                # RDF, examples) reports generations uniformly
                _MODEL_GENERATIONS.inc()
                # flight-recorder edge: a postmortem's first question about
                # a misbehaving replica is "when did its model last change"
                blackbox.record_event(
                    "model.generation", key=km.key,
                    message_bytes=len(km.message)
                    if isinstance(km.message, (str, bytes)) else None,
                )
                # adoption timeline opens at consume (headers carry the
                # batch tier's provenance stamp when lineage is on)
                lineage.tracker().model_consumed(km.key, km.headers)
            elif km.headers:
                # speed-tier fold-in deltas advance the freshness watermark
                lineage.tracker().delta_consumed(km.headers)
            self.consume_key_message(km.key, km.message)
            if is_model:
                # in-place managers serve the new generation as soon as the
                # dispatch returns; double-buffering managers hold it staged
                # until the warmer (or the swap deadline) promotes it
                try:
                    staged = self.get_staged_model()
                except Exception:  # noqa: BLE001 — tracker must never kill consume
                    staged = None
                if staged is None:
                    lineage.tracker().mark_live()
                else:
                    lineage.tracker().mark_staged()

    @abc.abstractmethod
    def consume_key_message(self, key: str, message: str) -> None:
        ...
