"""Key/message pair flowing through topics.

Equivalent of the reference's KeyMessage/KeyMessageImpl
(framework/oryx-api/.../KeyMessage.java:34-40, KeyMessageImpl.java).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Generic, TypeVar

K = TypeVar("K")
M = TypeVar("M")


@dataclass(frozen=True)
class KeyMessage(Generic[K, M]):
    key: K
    message: M

    def get_key(self) -> K:
        return self.key

    def get_message(self) -> M:
        return self.message
