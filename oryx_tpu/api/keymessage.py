"""Key/message pair flowing through topics.

Equivalent of the reference's KeyMessage/KeyMessageImpl
(framework/oryx-api/.../KeyMessage.java:34-40, KeyMessageImpl.java), plus
transport-level ``headers`` (Kafka record headers equivalent) carrying
cross-tier metadata — today the W3C ``traceparent`` injected by
TopicProducerImpl so a trace minted at HTTP ingress survives the topic hop
into the speed/batch tiers (common/spans.py).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Generic, TypeVar

K = TypeVar("K")
M = TypeVar("M")


@dataclass(frozen=True)
class KeyMessage(Generic[K, M]):
    key: K
    message: M
    #: Transport metadata (e.g. {"traceparent": ...}); excluded from
    #: equality so payload comparison semantics predate headers.
    headers: "dict | None" = field(default=None, compare=False)

    def get_key(self) -> K:
        return self.key

    def get_message(self) -> M:
        return self.message
