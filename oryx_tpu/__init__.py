"""oryx_tpu — a TPU-native lambda-architecture realtime ML framework.

A from-scratch JAX/XLA re-design with the capability surface of Oryx 2
(batch/speed/serving tiers over topics and a data store; ALS, k-means and
random-decision-forest verticals; HOCON-style config; PMML model artifacts;
REST serving API), built TPU-first: models are sharded device arrays on a
jax mesh, batch jobs are pjit'd programs, and incremental updates are jit'd
microbatch kernels.
"""

__version__ = "0.1.0"
