"""oryx_tpu — a TPU-native lambda-architecture realtime ML framework.

A from-scratch JAX/XLA re-design with the capability surface of Oryx 2
(batch/speed/serving tiers over topics and a data store; ALS, k-means and
random-decision-forest verticals; HOCON-style config; PMML model artifacts;
REST serving API), built TPU-first: models are sharded device arrays on a
jax mesh, batch jobs are pjit'd programs, and incremental updates are jit'd
microbatch kernels.
"""

__version__ = "0.1.0"

# Runtime concurrency sanitizer opt-in (ORYX_SANITIZE=locks,loop): install
# at package import, BEFORE any oryx module allocates its locks or spins up
# an event loop — subprocess layers (fleet replicas, the cli broker)
# inherit the env var and self-install the same way. Stdlib-only import;
# a no-op when the variable is unset (see docs/sanitizer.md).
import os as _os

if _os.environ.get("ORYX_SANITIZE"):
    from oryx_tpu.tools import sanitize as _sanitize

    _sanitize.install_from_env()
