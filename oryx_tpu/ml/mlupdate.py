"""MLUpdate: the train/tune/eval/publish harness behind every model family.

Equivalent of the reference's MLUpdate (framework/oryx-ml/.../MLUpdate.java:163-378):
one batch generation = choose hyperparameter combos → build+evaluate candidates
in parallel → promote the best into a timestamped model dir → publish MODEL
(inline PMML when ≤ update-topic max-size) or MODEL-REF (path) → optional
additional model data (e.g. ALS streams every factor row).

TPU notes: candidate builds run through a host thread pool
(``oryx.ml.eval.parallelism``, ExecUtils.collectInParallel:255 equivalent);
each build is itself a pjit'd program on the mesh, so host threads only
overlap orchestration and host↔device transfers of different candidates.
"""

from __future__ import annotations

import logging
import math
import shutil
import tempfile
import time
from pathlib import Path
from typing import Sequence

from oryx_tpu.api.batch import BatchLayerUpdate
from oryx_tpu.api.keymessage import KeyMessage
from oryx_tpu.common import executils, lineage, rand
from oryx_tpu.ml import param as hp
from oryx_tpu.pmml import pmmlutils
from oryx_tpu.store.datastore import ModelStore
from oryx_tpu.transport.topic import TopicException

log = logging.getLogger(__name__)

MODEL_FILE_NAME = "model.pmml"  # MLUpdate.java MODEL_FILE_NAME


class MLUpdate(BatchLayerUpdate):
    """Subclasses implement build_model / evaluate (+ optional hooks)."""

    def __init__(self, config):
        self.config = config
        self.test_fraction = config.get_float("oryx.ml.eval.test-fraction")
        candidates = config.get_int("oryx.ml.eval.candidates")
        self.eval_parallelism = config.get_int("oryx.ml.eval.parallelism")
        self.threshold = config.get("oryx.ml.eval.threshold", None)
        self.hyperparam_search = config.get_string("oryx.ml.eval.hyperparam-search")
        self.max_message_size = config.get_int("oryx.update-topic.message.max-size")
        if self.test_fraction == 0.0 and candidates > 1:
            log.info("test-fraction = 0 so candidates is overridden to 1")
            candidates = 1
        self.candidates = candidates
        # speculative backup execution for straggling candidate builds
        # (reference spark.speculation, reference.conf:86)
        self.speculation = config.get_bool("oryx.ml.eval.speculation.enabled", True)
        self.speculation_multiplier = config.get_float(
            "oryx.ml.eval.speculation.multiplier", 1.5
        )
        self.speculation_min_runtime = config.get_float(
            "oryx.ml.eval.speculation.min-runtime-sec", 10.0
        )
        self.speculation_timeout = config.get(
            "oryx.ml.eval.speculation.timeout-sec", None
        )

    # -- abstract surface (MLUpdate.java:113-157) ---------------------------
    def get_hyper_parameter_values(self) -> list[hp.HyperParamValues]:
        return []

    def build_model(
        self,
        context,
        train_data: Sequence[KeyMessage],
        hyper_parameters: list,
        candidate_path: Path,
    ):
        """Train and return a PMML Element for one candidate."""
        raise NotImplementedError

    def evaluate(
        self,
        context,
        model,  # PMML Element
        model_parent_path: Path,
        test_data: Sequence[KeyMessage],
        train_data: Sequence[KeyMessage],
    ) -> float:
        """Higher is better (MLUpdate.java:157)."""
        raise NotImplementedError

    def publish_additional_model_data(
        self, context, pmml, new_data, past_data, model_path: Path, producer
    ) -> None:
        """Hook (MLUpdate.java:139-146); default no-op."""

    def make_checkpointer(self, fp: str, meta: "dict | None" = None):
        """``oryx.batch.checkpoint.*`` → a ``TrainerCheckpointer`` keyed by
        the candidate's data fingerprint, or None when checkpointing is
        disabled. The candidate-loop resume contract every model family
        shares: a killed batch layer re-runs ``run_update`` with the same
        input slice (offsets were never committed), each candidate's
        ``build_model`` recomputes the same fingerprint, and the trainer
        resumes from the newest valid checkpoint instead of redoing the
        generation — a kill -9 costs at most one checkpoint interval."""
        from oryx_tpu.common import checkpoint as ckpt_mod

        return ckpt_mod.from_config(self.config, fp, meta=meta)

    # -- BatchLayerUpdate (runUpdate:163-248) --------------------------------
    def run_update(self, context, timestamp_ms, new_data, past_data, model_dir, producer):
        train_start_ms = int(time.time() * 1000)
        new_data = list(new_data)
        past_data = list(past_data)
        if not new_data and not past_data:
            log.info("no data to train on")
            return
        combos = hp.choose_hyper_parameter_combos(
            self.get_hyper_parameter_values(), self.candidates, self.hyperparam_search
        )
        # test data is held out of NEW data only; past data always trains
        # (MLUpdate.java:306,342-376)
        train_new, test = self.split_new_data_to_train_test(new_data)
        train = list(train_new) + past_data
        scratch = Path(tempfile.mkdtemp(prefix="oryx-candidates-"))
        try:
            best_path, best_eval = self._find_best_candidate_path(
                context, train, test, combos, scratch
            )
            if best_path is None:
                log.info("unable to build any model")
                return
            if self.threshold is not None and (
                best_eval is None
                or math.isnan(best_eval)
                or best_eval < float(self.threshold)
            ):
                log.info(
                    "best model eval %s does not exceed threshold %s; not publishing",
                    best_eval,
                    self.threshold,
                )
                return
            # promote best candidate into the model store (MLUpdate.java:201-207)
            store = ModelStore(model_dir)
            final_path = store.promote(best_path, timestamp_ms)
        finally:
            # drop the whole candidates scratch (fs.delete(candidatesPath))
            shutil.rmtree(scratch, ignore_errors=True)
        model_file = final_path / MODEL_FILE_NAME
        pmml = pmmlutils.read(model_file)
        pmml_string = pmmlutils.to_string(pmml)
        if producer is not None:
            # provenance stamp on the publish: generation id (stable from
            # the checkpoint fingerprint when there is one), the input
            # offsets/watermark the batch layer recorded on the context,
            # train timing, origin, row counts — every send below carries it
            if self.config.get_bool("oryx.lineage.enabled", True):
                stamp = lineage.make_stamp(
                    context, timestamp_ms,
                    train_start_ms=train_start_ms,
                    train_end_ms=int(time.time() * 1000),
                    new_rows=len(new_data), past_rows=len(past_data),
                )
                producer = lineage.StampedProducer(producer, stamp)
            # inline if small enough, else by reference (MLUpdate.java:219-233)
            if len(pmml_string) <= self.max_message_size:
                producer.send("MODEL", pmml_string)
            else:
                producer.send("MODEL-REF", str(model_file))
            self.publish_additional_model_data(
                context, pmml, new_data, past_data, final_path, producer
            )

    # -- candidate search (findBestCandidatePath:250-292) --------------------
    def _find_best_candidate_path(self, context, train, test, combos, scratch: Path):
        # candidate-model parallelism (SURVEY §2.14 EP-like fan-out): with
        # several devices and several candidates, round-robin each candidate's
        # default device so parallel builds land on different chips
        devices = None
        if self.eval_parallelism > 1 and len(combos) > 1:
            import jax

            local = jax.local_devices()
            if len(local) > 1:
                devices = local

        def build_and_eval(i: int, attempt: int = 0):
            # a backup attempt writes to its own path and prefers a DIFFERENT
            # device than the original, mirroring Spark's speculative copies
            candidate_path = scratch / (f"{i}" if attempt == 0 else f"{i}.{attempt}")
            candidate_path.mkdir(parents=True, exist_ok=True)
            try:
                if devices is not None:
                    import jax

                    with jax.default_device(devices[(i + attempt) % len(devices)]):
                        pmml = self.build_model(context, train, combos[i], candidate_path)
                else:
                    pmml = self.build_model(context, train, combos[i], candidate_path)
            except Exception:  # noqa: BLE001 - a failed candidate is skipped
                log.exception("candidate %d failed to build", i)
                return None
            if pmml is None:
                return None
            pmmlutils.write(pmml, candidate_path / MODEL_FILE_NAME)
            if self.test_fraction == 0.0 or not test:
                eval_result = None
            else:
                eval_result = self.evaluate(context, pmml, candidate_path, test, train)
            log.info("candidate %d (%s) eval = %s", i, combos[i], eval_result)
            return candidate_path, eval_result

        if self.speculation:
            results = executils.collect_speculative(
                len(combos), build_and_eval, self.eval_parallelism,
                multiplier=self.speculation_multiplier,
                min_runtime_sec=self.speculation_min_runtime,
                abandon_sec=(
                    float(self.speculation_timeout)
                    if self.speculation_timeout is not None
                    else None
                ),
            )
        else:
            results = executils.collect_in_parallel(
                len(combos), build_and_eval, self.eval_parallelism
            )
        best = None
        for r in results:
            if r is None:
                continue
            if best is None or _better(r[1], best[1]):
                best = r
        return best if best is not None else (None, None)

    # -- train/test split (splitTrainTest:342-376) ---------------------------
    def split_new_data_to_train_test(self, new_data):
        """Default random split of the NEW data by test-fraction; subclasses
        may override with e.g. time-ordered splits (ALSUpdate.java:326-343)."""
        if self.test_fraction <= 0:
            return new_data, []
        rng = rand.get_random()
        mask = rng.random(len(new_data)) < self.test_fraction
        train = [d for d, m in zip(new_data, mask) if not m]
        test = [d for d, m in zip(new_data, mask) if m]
        return train, test


def _better(a, b) -> bool:
    """Candidate-score comparison where None and NaN are worse than any real
    score: 'real > nan' is False in IEEE terms, so a NaN-scored candidate
    evaluated first would otherwise survive every later comparison and be
    published as "best"."""
    a_bad = a is None or a != a  # self-inequality: NaN of ANY float-like
    b_bad = b is None or b != b  # (np.float32 NaN is not a python float)
    if a_bad:
        return False
    if b_bad:
        return True
    return a > b


def read_pmml_from_update_key_message(key: str, message: str):
    """Decode MODEL / MODEL-REF update messages into a PMML Element
    (AppPMMLUtils.readPMMLFromUpdateKeyMessage:234-259)."""
    if key == "MODEL":
        return pmmlutils.from_string(message)
    if key == "MODEL-REF":
        path = Path(message)
        if not path.exists():
            raise TopicException(f"MODEL-REF path does not exist: {message}")
        return pmmlutils.read(path)
    raise ValueError(f"not a model message: {key}")
