"""Hyperparameter DSL + grid/random search.

Equivalent of the reference's ml.param package (framework/oryx-ml/.../param/):
HyperParamValues impls ContinuousRange, DiscreteRange, ContinuousAround,
DiscreteAround, Unordered; config sniffing HyperParams.fromConfig:67-103
(scalar → fixed, 2-element list → range typed by int/float, longer list →
unordered); GridSearch.chooseHyperParameterCombos:42 (cartesian product with
per-param value count sized to reach the candidate budget, random subset +
shuffle) and RandomSearch:35 (independent random draws).
"""

from __future__ import annotations

import abc
import itertools
from typing import Any, Sequence

from oryx_tpu.common import rand

MAX_COMBOS = 65536


class HyperParamValues(abc.ABC):
    @abc.abstractmethod
    def get_trial_values(self, num: int) -> list:
        ...

    @abc.abstractmethod
    def get_random_value(self, rng) -> Any:
        ...

    @abc.abstractmethod
    def get_num_distinct_values(self) -> int:
        ...


class ContinuousRange(HyperParamValues):
    """Uniform real range [min, max] (param/ContinuousRange.java)."""

    def __init__(self, lo: float, hi: float):
        assert lo <= hi
        self.lo, self.hi = float(lo), float(hi)

    def get_trial_values(self, num: int) -> list:
        if self.hi == self.lo:
            return [self.lo]
        if num == 1:
            return [(self.hi + self.lo) / 2.0]
        if num == 2:
            return [self.lo, self.hi]
        step = (self.hi - self.lo) / (num - 1)
        return [self.lo + i * step for i in range(num)]

    def get_random_value(self, rng) -> float:
        if self.hi == self.lo:
            return self.lo
        return float(rng.uniform(self.lo, self.hi))

    def get_num_distinct_values(self) -> int:
        return 2**63 - 1

    def __repr__(self):  # pragma: no cover
        return f"ContinuousRange[{self.lo},{self.hi}]"


class DiscreteRange(HyperParamValues):
    """Integer range [min, max] inclusive (param/DiscreteRange.java)."""

    def __init__(self, lo: int, hi: int):
        assert lo <= hi
        self.lo, self.hi = int(lo), int(hi)

    def get_trial_values(self, num: int) -> list:
        count = self.hi - self.lo + 1
        if count <= num:
            return list(range(self.lo, self.hi + 1))
        if num == 1:
            return [round((self.lo + self.hi) / 2)]
        step = (self.hi - self.lo) / (num - 1)
        vals = sorted({round(self.lo + i * step) for i in range(num)})
        return vals

    def get_random_value(self, rng) -> int:
        return int(rng.integers(self.lo, self.hi + 1))

    def get_num_distinct_values(self) -> int:
        return self.hi - self.lo + 1

    def __repr__(self):  # pragma: no cover
        return f"DiscreteRange[{self.lo},{self.hi}]"


class ContinuousAround(HyperParamValues):
    """Values spread around a center with a given step (param/ContinuousAround.java)."""

    def __init__(self, around: float, step: float):
        self.around, self.step = float(around), float(step)

    def get_trial_values(self, num: int) -> list:
        start = self.around - self.step * (num - 1) / 2.0
        return [start + i * self.step for i in range(num)]

    def get_random_value(self, rng) -> float:
        return float(rng.uniform(self.around - self.step, self.around + self.step))

    def get_num_distinct_values(self) -> int:
        return 2**63 - 1


class DiscreteAround(HyperParamValues):
    """Integer values around a center (param/DiscreteAround.java)."""

    def __init__(self, around: int, step: int):
        self.around, self.step = int(around), int(step)

    def get_trial_values(self, num: int) -> list:
        start = self.around - (self.step * (num - 1)) // 2
        return [start + i * self.step for i in range(num)]

    def get_random_value(self, rng) -> int:
        return int(rng.integers(self.around - self.step, self.around + self.step + 1))

    def get_num_distinct_values(self) -> int:
        return 2**63 - 1


class Unordered(HyperParamValues):
    """Categorical values (param/Unordered.java)."""

    def __init__(self, values: Sequence):
        assert len(values) > 0
        self.values = list(values)

    def get_trial_values(self, num: int) -> list:
        return self.values[: max(1, num)]

    def get_random_value(self, rng) -> Any:
        return self.values[int(rng.integers(0, len(self.values)))]

    def get_num_distinct_values(self) -> int:
        return len(self.values)


def fixed(value) -> HyperParamValues:
    """A single fixed value as a degenerate range."""
    if isinstance(value, bool) or isinstance(value, str):
        return Unordered([value])
    if isinstance(value, int):
        return DiscreteRange(value, value)
    return ContinuousRange(float(value), float(value))


def from_config(config, key: str) -> HyperParamValues:
    """Sniff a hyperparam spec from config (HyperParams.fromConfig:67-103):
    scalar → fixed; [lo, hi] → typed range; longer list → unordered."""
    v = config.get(key)
    if isinstance(v, list):
        if len(v) == 2 and all(isinstance(x, (int, float)) and not isinstance(x, bool) for x in v):
            if all(isinstance(x, int) for x in v):
                return DiscreteRange(v[0], v[1])
            return ContinuousRange(float(v[0]), float(v[1]))
        return Unordered(v)
    return fixed(v)


# ---------------------------------------------------------------------------
# Search strategies
# ---------------------------------------------------------------------------


def choose_hyper_parameter_combos(
    ranges: Sequence[HyperParamValues], how_many: int, search: str = "random"
) -> list[list]:
    """Dispatch by oryx.ml.eval.hyperparam-search (HyperParams:105-116)."""
    if search == "grid":
        return _grid(ranges, how_many)
    if search == "random":
        return _random(ranges, how_many)
    raise ValueError(f"unknown hyperparam search: {search}")


def _values_per_hyper_param(ranges: Sequence[HyperParamValues], candidates: int) -> int:
    """Smallest per-param count whose combination total reaches the budget
    (GridSearch.chooseValuesPerHyperParam)."""
    if not ranges:
        return 0
    per, last_total, total = 0, -1, 0
    while total < candidates and total > last_total or per == 0:
        per += 1
        last_total = total
        total = 1
        for r in ranges:
            total *= min(per, r.get_num_distinct_values())
        if total >= candidates or total <= last_total:
            break
    return per


def _grid(ranges: Sequence[HyperParamValues], how_many: int) -> list[list]:
    assert 0 < how_many <= MAX_COMBOS
    if not ranges:
        return [[]]
    per = _values_per_hyper_param(ranges, how_many)
    value_lists = [r.get_trial_values(per) for r in ranges]
    combos = [list(c) for c in itertools.product(*value_lists)]
    rng = rand.get_random()
    if how_many >= len(combos):
        rng.shuffle(combos)
        return combos
    idx = rng.permutation(len(combos))[:how_many]
    picked = [combos[i] for i in idx]
    rng.shuffle(picked)
    return picked


def _random(ranges: Sequence[HyperParamValues], how_many: int) -> list[list]:
    assert how_many > 0
    if not ranges:
        return [[]]
    rng = rand.get_random()
    return [[r.get_random_value(rng) for r in ranges] for _ in range(how_many)]
