"""Data/model store: timestamped segments on a filesystem.

TPU-native replacement for the reference's HDFS layout
(lambda/batch/SaveToHDFSFunction.java, BatchUpdateFunction.java:103-130,
lambda/DeleteOldDataFn.java, ml/MLUpdate.java:191-207):

  * each batch interval's new data is persisted as a timestamped segment dir
    ``oryx-<millis>.data/part-00000.jsonl`` (skipping empty intervals to avoid
    small files);
  * past data is re-read by globbing ``*/part-*`` across all segments;
  * models live in timestamped dirs ``<model-dir>/<millis>/model.pmml`` plus
    side data (ALS X/ Y/ factor part-files);
  * TTL GC deletes segments/models older than max-age-hours.

Local paths work single-host; pointing data-dir/model-dir at a shared/network
filesystem gives the multi-host layout the reference gets from HDFS.
"""

from __future__ import annotations

import json
import re
import time
from pathlib import Path
from typing import Iterator

from oryx_tpu.api.keymessage import KeyMessage
from oryx_tpu.common import ioutils

_DATA_SEGMENT_RE = re.compile(r"oryx-(\d+)\.data")
_MODEL_DIR_RE = re.compile(r"(\d+)")


def _delete_older_than(
    dirs, timestamp_of, max_age_hours: int, now_ms: "int | None"
) -> list[Path]:
    """Shared TTL-GC policy (DeleteOldDataFn.java); max_age_hours < 0 disables."""
    if max_age_hours < 0:
        return []
    now_ms = now_ms if now_ms is not None else int(time.time() * 1000)
    cutoff = now_ms - max_age_hours * 3600 * 1000
    deleted = []
    for d in dirs:
        ts = timestamp_of(d)
        if ts is not None and ts < cutoff:
            ioutils.delete_recursively(d)
            deleted.append(d)
    return deleted


class DataStore:
    """Append/read/GC of timestamped data segments under one data-dir."""

    def __init__(self, data_dir: str):
        self._dir = Path(_strip_scheme(data_dir))

    @property
    def path(self) -> Path:
        return self._dir

    def write_segment(self, timestamp_ms: int, data: "list[KeyMessage]") -> Path | None:
        """Persist one interval's data; returns the segment dir or None if empty
        (SaveToHDFSFunction skips empty RDDs)."""
        if not data:
            return None
        seg = self._dir / f"oryx-{timestamp_ms}.data"
        ioutils.mkdirs(seg)
        part = seg / "part-00000.jsonl"
        with open(part, "w", encoding="utf-8") as f:
            for km in data:
                f.write(json.dumps({"k": km.key, "m": km.message}, separators=(",", ":")) + "\n")
        return seg

    def read_all(self) -> Iterator[KeyMessage]:
        """Glob `*/part-*` over all segments — the pastData read
        (BatchUpdateFunction.java:103-130)."""
        if not self._dir.exists():
            return
        for seg in sorted(self._dir.glob("oryx-*.data")):
            for part in sorted(seg.glob("part-*")):
                with open(part, "r", encoding="utf-8") as f:
                    for line in f:
                        if line.strip():
                            d = json.loads(line)
                            yield KeyMessage(d["k"], d["m"])

    def segments(self) -> list[Path]:
        return sorted(self._dir.glob("oryx-*.data")) if self._dir.exists() else []

    def delete_older_than(self, max_age_hours: int, now_ms: int | None = None) -> list[Path]:
        def ts_of(seg: Path):
            m = _DATA_SEGMENT_RE.fullmatch(seg.name)
            return int(m.group(1)) if m else None

        return _delete_older_than(self.segments(), ts_of, max_age_hours, now_ms)


class ModelStore:
    """Timestamped model dirs under one model-dir (MLUpdate.java:191-207)."""

    def __init__(self, model_dir: str):
        self._dir = Path(_strip_scheme(model_dir))

    @property
    def path(self) -> Path:
        return self._dir

    def new_model_dir(self, timestamp_ms: int) -> Path:
        d = self._dir / str(timestamp_ms)
        ioutils.mkdirs(d)
        return d

    def promote(self, candidate_dir: Path, timestamp_ms: int) -> Path:
        """Move the winning candidate into place (MLUpdate.java:201-207).
        shutil.move handles candidates on a different filesystem than the
        model dir (tmpfs scratch → shared storage)."""
        import shutil

        dest = self._dir / str(timestamp_ms)
        ioutils.mkdirs(dest.parent)
        shutil.move(str(candidate_dir), str(dest))
        return dest

    def model_dirs(self) -> list[Path]:
        if not self._dir.exists():
            return []
        return sorted(
            (d for d in self._dir.iterdir() if d.is_dir() and _MODEL_DIR_RE.fullmatch(d.name)),
            key=lambda d: int(d.name),
        )

    def latest(self) -> Path | None:
        dirs = self.model_dirs()
        return dirs[-1] if dirs else None

    def delete_older_than(self, max_age_hours: int, now_ms: int | None = None) -> list[Path]:
        return _delete_older_than(
            self.model_dirs(), lambda d: int(d.name), max_age_hours, now_ms
        )


def _strip_scheme(path: str) -> str:
    if path.startswith("file:"):
        return path[len("file:"):]
    return path
