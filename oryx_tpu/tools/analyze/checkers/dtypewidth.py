"""dtype-widening: narrow device dtypes silently promoted to f32 in jit.

The framework keeps deliberately-narrow device copies — bf16 scoring
matrices (half the HBM per scan) and int8 quantized factor slabs (a
quarter) — precisely to stay under the bandwidth roofline. A bf16/int8
value that silently contracts or mixes at float32 inside a jitted program
pays f32 traffic anyway while keeping the narrow dtype's rounding error:
the worst of both. This generalizes ``float64-promotion`` onto real
dataflow (the dtype lattice ``int8 ≤ bf16 ≤ f32 ≤ f64``) instead of
literal spotting.

Flagged inside jit scopes: a binary arithmetic op mixing a LOW-dtype value
(``int8``/``bfloat16`` by ``.astype``/constructor evidence) with a float32
one, and an einsum/matmul/dot over mixed LOW+f32 operands with NO
``preferred_element_type``. Sanctioned and silent:

  * ``preferred_element_type=...`` contractions — f32 ACCUMULATION over
    narrow inputs is the standard TPU matmul recipe, not a widening;
  * an explicit ``.astype(float32)`` — visible intent, not silent;
  * scopes whose qualname contains ``rescore`` or ``solve`` — the exact-f32
    rescore of quantized candidates and the f32 Cholesky/Gauss-Jordan
    solves widen by design.
"""

from __future__ import annotations

import ast

from oryx_tpu.tools.analyze.core import scope_nodes
from oryx_tpu.tools.analyze.dataflow import (
    DTYPE_RANK,
    LOW_DTYPES,
    LineStateEnv,
    dtype_of_node,
)

ID = "dtype-widening"

_SANCTIONED_NAME_PARTS = ("rescore", "solve")
_CONTRACTION_NAMES = {
    "jax.numpy.einsum", "jax.numpy.matmul", "jax.numpy.dot",
    "jax.numpy.tensordot",
}
_ARITH_OPS = (ast.Add, ast.Sub, ast.Mult, ast.Div, ast.MatMult, ast.Pow)
#: jnp constructors whose default dtype is float32.
_F32_DEFAULT_CTORS = {"zeros", "ones", "full", "empty", "zeros_like",
                      "ones_like", "linspace"}


class _DtypeEnv:
    """Flow-sensitive (per-line) name -> lattice dtype inference for one
    jit scope, the same discipline as ``dataflow.DeviceFlow``: a name
    resolves to its dtype just BEFORE the queried line, so the idiomatic
    compute-wide-then-store-narrow pattern (``acc = acc + w`` ... ``acc =
    acc.astype(bf16)`` at the end) never retro-flags the earlier pure-f32
    arithmetic."""

    def __init__(self, fctx, fn_node):
        self.fctx = fctx
        self._env = LineStateEnv()
        stmts = sorted(
            (n for n in scope_nodes(fctx, fn_node)
             if isinstance(n, (ast.Assign, ast.AnnAssign))),
            key=lambda n: n.lineno,
        )
        for stmt in stmts:
            if stmt.value is None:
                continue
            dt = self.dtype_of(stmt.value, stmt.lineno)
            targets = stmt.targets if isinstance(stmt, ast.Assign) else [stmt.target]
            for t in targets:
                if isinstance(t, ast.Name):
                    self._env.record(t.id, stmt.lineno, dt)

    def dtype_of(self, node, line: int) -> "str | None":
        if isinstance(node, ast.Name):
            return self._env.state_before(node.id, line)
        if isinstance(node, ast.Attribute):
            if node.attr == "T":
                return self.dtype_of(node.value, line)
            return None
        if isinstance(node, ast.Subscript):
            return self.dtype_of(node.value, line)
        if isinstance(node, ast.Call):
            func = node.func
            if isinstance(func, ast.Attribute) and func.attr == "astype":
                if node.args:
                    return dtype_of_node(self.fctx, node.args[0])
                return None
            for kw in node.keywords:
                if kw.arg == "dtype":
                    return dtype_of_node(self.fctx, kw.value)
            resolved = self.fctx.resolve(func)
            if resolved:
                mod, _, name = resolved.rpartition(".")
                if mod == "jax.numpy" and name in _F32_DEFAULT_CTORS:
                    return "float32"
            return None
        if isinstance(node, ast.BinOp):
            lo = self.dtype_of(node.left, line)
            hi = self.dtype_of(node.right, line)
            if lo is None or hi is None:
                return lo or hi
            return lo if DTYPE_RANK[lo] >= DTYPE_RANK[hi] else hi
        return None


def _mixes_low_and_f32(env: _DtypeEnv, operands, line: int) -> "tuple | None":
    """(low_expr, low_dtype) when the operand dtypes (as of ``line``) mix a
    LOW dtype with float32/float64 — the silent-widening signature."""
    dts = [(op, env.dtype_of(op, line)) for op in operands]
    low = next(((op, dt) for op, dt in dts if dt in LOW_DTYPES), None)
    wide = any(dt in ("float32", "float64") for _, dt in dts)
    return low if (low and wide) else None


class DtypeWideningChecker:
    id = ID
    version = 1

    def check(self, project) -> list:
        out = []
        for fctx in project.files:
            for scope in fctx.jit_scopes.values():
                low_name = scope.qualname.lower()
                if any(p in low_name for p in _SANCTIONED_NAME_PARTS):
                    continue
                env = _DtypeEnv(fctx, scope.node)
                for node in scope_nodes(fctx, scope.node):
                    hit = None
                    how = None
                    if isinstance(node, ast.BinOp) and isinstance(
                        node.op, _ARITH_OPS
                    ):
                        hit = _mixes_low_and_f32(
                            env, [node.left, node.right], node.lineno
                        )
                        how = "arithmetic mixing"
                    elif isinstance(node, ast.Call):
                        resolved = fctx.resolve(node.func)
                        if resolved in _CONTRACTION_NAMES and not any(
                            kw.arg == "preferred_element_type"
                            for kw in node.keywords
                        ):
                            hit = _mixes_low_and_f32(
                                env, list(node.args), node.lineno
                            )
                            how = "a contraction over"
                    if hit is None:
                        continue
                    expr, dt = hit
                    out.append(fctx.finding(
                        ID, node,
                        f"{how} {dt} `{ast.unparse(expr)[:40]}` and float32 "
                        f"inside jitted `{scope.qualname}` silently widens "
                        f"to f32 — the narrow copy pays full HBM traffic "
                        "anyway; widen explicitly (.astype) at a sanctioned "
                        "rescore/solve site, or keep the op narrow with "
                        "preferred_element_type accumulation",
                        symbol=f"{scope.qualname}:{dt}",
                    ))
        return out
