"""config-key-drift: the oryx.* key surface must match reference_conf.

Two directions (both are real bugs in a convention-typed config tree):

  * **unknown key** — code reads an ``oryx.*`` key that does not exist in
    ``common/reference_conf.py``. With a default argument the typo silently
    disables the knob forever; without one it is a runtime ConfigError on a
    path nobody tested.
  * **unread key** — a key declared in reference_conf that no code reads:
    a dead knob an operator can set with no effect (or the fossil of a
    rename that left the old spelling behind).

Read detection is AST-based: literal first arguments of
``get/get_string/get_int/get_float/get_bool/get_list/get_config/has`` calls,
f-string keys (``f"oryx.{tier}.streaming..."`` becomes a one-segment
wildcard), relative reads through a tracked ``get_config("oryx.x")``
variable, loose ``oryx.*`` string literals anywhere in code (constants such
as routing keys), and ``${oryx.*}`` substitutions inside the reference text
itself.
"""

from __future__ import annotations

import ast
import re

from oryx_tpu.tools.analyze.core import Finding

ID = "config-key-drift"

_GETTERS = {
    "get", "get_string", "get_int", "get_float", "get_bool", "get_list",
    "get_config", "has",
}

_SUBST_RE = re.compile(r"\$\{\??\s*(oryx\.[^}]+?)\s*\}")

# best-effort line numbers for keys inside the reference HOCON text
_KEY_LINE_RE = re.compile(r"^(\s*)([A-Za-z0-9_\-]+)\s*(=|\{|:)")
_INLINE_OBJ_RE = re.compile(r"([A-Za-z0-9_\-]+)\s*=")


def _fstring_pattern(node: ast.JoinedStr) -> "str | None":
    """f"oryx.{tk}.broker" -> regex ``oryx\\.[^.]+\\.broker`` (each hole spans
    one dotted segment); None when the literal head is not oryx."""
    parts = []
    for v in node.values:
        if isinstance(v, ast.Constant) and isinstance(v.value, str):
            parts.append(re.escape(v.value))
        elif isinstance(v, ast.FormattedValue):
            parts.append(r"[^.]+")
        else:
            return None
    pattern = "".join(parts)
    return pattern if pattern.startswith("oryx\\.") else None


def _flatten_conf(text: str) -> dict:
    """key -> best-effort line number in the reference text."""
    from oryx_tpu.common.config import Config

    flat = dict(Config.parse_string(text).flatten())
    lines_of: dict[str, int] = {}
    stack: list[str] = []
    for lineno, raw in enumerate(text.splitlines(), start=1):
        stripped = raw.split("#", 1)[0].strip()
        if not stripped:
            continue
        m = _KEY_LINE_RE.match(raw)
        if m:
            key = m.group(2)
            path = ".".join([*stack, key])
            if "{" in stripped and "}" not in stripped:
                stack.append(key)
            elif "{" in stripped and "}" in stripped:
                # inline object: `lock = { master = "memory:" }`
                inner = stripped[stripped.index("{") + 1:]
                for im in _INLINE_OBJ_RE.finditer(inner):
                    lines_of.setdefault(f"{path}.{im.group(1)}", lineno)
            else:
                lines_of.setdefault(path, lineno)
        # net close braces pop enclosing objects (same-line open+close nets 0)
        for _ in range(max(0, stripped.count("}") - stripped.count("{"))):
            if stack:
                stack.pop()
    return {k: lines_of.get(k, 1) for k in flat}


class ConfigKeyDriftChecker:
    id = ID

    def check(self, project) -> list:
        conf_text = project.reference_conf_text()
        key_lines = _flatten_conf(conf_text)
        flat_keys = set(key_lines)

        strict: list = []  # (key_or_None, pattern_or_None, fctx, line)
        loose_literals: set = set()
        loose_patterns: set = set()
        for m in _SUBST_RE.finditer(conf_text):
            loose_literals.add(m.group(1))

        for fctx in project.files:
            self._collect_file(fctx, strict, loose_literals, loose_patterns)

        out = []
        # -- unknown keys ----------------------------------------------------
        for key, pattern, fctx, line in strict:
            if key is not None:
                ok = key in flat_keys or any(
                    k.startswith(key + ".") for k in flat_keys
                )
                if not ok:
                    out.append(fctx.finding(
                        ID, line,
                        f"config key {key!r} is read here but does not exist "
                        "in common/reference_conf.py — typo'd or dropped knob",
                        symbol=key,
                    ))
            elif pattern is not None:
                ok = any(
                    re.fullmatch(pattern, k) or re.match(pattern + r"\.", k)
                    for k in flat_keys
                )
                if not ok:
                    out.append(fctx.finding(
                        ID, line,
                        f"config key pattern `{pattern}` matches no key in "
                        "common/reference_conf.py",
                        symbol=pattern,
                    ))

        # -- unread keys -----------------------------------------------------
        read_exact = {k for k, _, _, _ in strict if k is not None} | loose_literals
        read_patterns = [p for _, p, _, _ in strict if p is not None]
        read_patterns.extend(loose_patterns)
        conf_relpath = self._conf_relpath(project)
        # map conf-text line numbers onto the .py file holding the string
        conf_fctx = project.by_relpath.get(conf_relpath)
        line_offset = 0
        if conf_fctx is not None:
            for i, raw in enumerate(conf_fctx.lines, start=1):
                if "REFERENCE_CONF" in raw and '"""' in raw:
                    line_offset = i - 1
                    break
        for key in sorted(flat_keys):
            if key in read_exact:
                continue
            if any(key.startswith(p + ".") for p in read_exact):
                continue
            if any(
                re.fullmatch(p, key) or re.match(p + r"\.", key)
                for p in read_patterns
            ):
                continue
            out.append(Finding(
                ID, conf_relpath, key_lines[key] + line_offset,
                f"config key {key!r} is declared in reference_conf but never "
                "read anywhere — dead knob (wire it or remove it)",
                symbol=key,
            ))
        return out

    @staticmethod
    def _conf_relpath(project) -> str:
        for rel in project.by_relpath:
            if rel.endswith("common/reference_conf.py"):
                return rel
        return "oryx_tpu/common/reference_conf.py"

    def _collect_file(self, fctx, strict, loose_literals, loose_patterns) -> None:
        # One walk gathers everything; getter calls are replayed after so
        # prefix tracking still sees assignments that follow a use site.
        # (ast.walk is breadth-first, so a scope node is always seen
        # before its docstring Constant.)
        docstrings = set()
        prefixes: dict[str, str] = {}
        getter_calls: list = []
        for node in ast.walk(fctx.tree):
            if isinstance(node, ast.Constant):
                if (
                    isinstance(node.value, str)
                    and node.value.startswith("oryx.")
                    and node not in docstrings
                ):
                    val = node.value.rstrip(".")
                    if "." in val:  # bare "oryx" would prefix-mask every key
                        loose_literals.add(val)
            elif isinstance(node, ast.Call):
                if (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr in _GETTERS
                    and node.args
                ):
                    getter_calls.append(node)
            elif isinstance(node, ast.JoinedStr):
                p = _fstring_pattern(node)
                if p:
                    loose_patterns.add(p)
            elif isinstance(node, ast.Assign):
                call = node.value
                if (
                    isinstance(call, ast.Call)
                    and isinstance(call.func, ast.Attribute)
                    and call.func.attr == "get_config"
                    and call.args
                    and isinstance(call.args[0], ast.Constant)
                    and isinstance(call.args[0].value, str)
                    and call.args[0].value.startswith("oryx.")
                ):
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            prefixes[t.id] = call.args[0].value
            elif isinstance(
                node,
                (ast.Module, ast.ClassDef, ast.FunctionDef, ast.AsyncFunctionDef),
            ):
                body = getattr(node, "body", [])
                if body and isinstance(body[0], ast.Expr) and isinstance(
                    body[0].value, ast.Constant
                ):
                    docstrings.add(body[0].value)

        for node in getter_calls:
            arg = node.args[0]
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                key = arg.value
                if key.startswith("oryx."):
                    strict.append((key, None, fctx, node.lineno))
                elif (
                    isinstance(node.func.value, ast.Name)
                    and node.func.value.id in prefixes
                ):
                    strict.append((
                        f"{prefixes[node.func.value.id]}.{key}", None, fctx,
                        node.lineno,
                    ))
            elif isinstance(arg, ast.JoinedStr):
                p = _fstring_pattern(arg)
                if p:
                    strict.append((None, p, fctx, node.lineno))
