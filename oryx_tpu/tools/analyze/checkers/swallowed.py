"""swallowed-exception: broad catches in hot paths must re-raise or log
(docs/robustness.md: failures degrade loudly, never silently).

A ``except Exception:`` (or bare ``except:`` / ``except BaseException:``)
whose body neither re-raises, logs, nor records the exception erases a
failure from every observability surface at once: no log line, no span
status, no metric — the bug ships as silence. In the serving/transport/
lambda_rt hot paths (where this framework's whole robustness story is
"degrade loudly, never silently"), that pattern is treated as a defect.

A handler is compliant when its body (nested scopes included) contains any
of: a ``raise``, a call to a logging method (``debug``/``info``/``warning``/
``error``/``exception``/``critical``/``log``), or a
``span.record_exception(...)`` call. NARROW catches (``except ValueError:``,
``except FileNotFoundError:``) are deliberate control flow and stay out of
scope — the checker targets the catch-everything-say-nothing shape.

Intentional broad swallows (e.g. advisory scrape-time probes where a log
per scrape would flood) carry the standard inline suppression comment
(``analyze: ignore`` with this checker's id and a justification).
"""

from __future__ import annotations

import ast

ID = "swallowed-exception"

#: Repo-relative path prefixes where silent failure is unacceptable (the
#: same hot-path scope as the log-discipline checker).
HOT_PATH_PREFIXES = (
    "oryx_tpu/serving/",
    "oryx_tpu/transport/",
    "oryx_tpu/lambda_rt/",
)

_BROAD = {"Exception", "BaseException"}
_LOG_METHODS = {
    "debug", "info", "warning", "error", "exception", "critical", "log",
    "record_exception",
}


def _is_broad(handler: ast.ExceptHandler, fctx) -> bool:
    """Bare except, Exception/BaseException, or a tuple containing one."""
    t = handler.type
    if t is None:
        return True
    types = t.elts if isinstance(t, ast.Tuple) else [t]
    for node in types:
        if isinstance(node, ast.Name) and node.id in _BROAD:
            return True
        resolved = fctx.resolve(node)
        if resolved in ("builtins.Exception", "builtins.BaseException"):
            return True
    return False


def _is_handled(handler: ast.ExceptHandler) -> bool:
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in _LOG_METHODS
        ):
            return True
    return False


class SwallowedExceptionChecker:
    id = ID

    def check(self, project) -> list:
        out = []
        for fctx in project.files:
            if not fctx.relpath.startswith(HOT_PATH_PREFIXES):
                continue
            for node in ast.walk(fctx.tree):
                if not isinstance(node, ast.Try):
                    continue
                for handler in node.handlers:
                    if not _is_broad(handler, fctx):
                        continue
                    if _is_handled(handler):
                        continue
                    out.append(fctx.finding(
                        ID, handler,
                        "broad except swallows the exception silently in a "
                        "hot path — no log, no re-raise, no span status; "
                        "failures here must degrade LOUDLY (log through "
                        "spans.get_logger, record_exception on the span, or "
                        "re-raise)",
                        symbol=f"swallow:{handler.lineno}",
                    ))
        return out
