"""log-discipline: hot-path modules must log through the trace-aware adapter.

The serving/transport/lambda tiers process traced requests (common/spans.py
carries a current span per task/thread). A log line emitted there through a
bare ``logging.getLogger(__name__)`` logger loses the trace/span ids that
would let an operator jump from the line to ``GET /trace?trace_id=...`` —
and a stray ``print(...)`` bypasses logging entirely (no level, no handler,
interleaved stdout under concurrency). Both are flagged in library hot
paths in favor of ``oryx_tpu.common.spans.get_logger``, whose adapter
appends ``[trace=... span=...]`` to every message under an active span.

Scope is deliberately the HOT paths only (``serving/``, ``transport/``,
``lambda_rt/``): CLI tools and benches print by design, and offline
trainers have no request context to correlate.
"""

from __future__ import annotations

import ast

ID = "log-discipline"

#: Repo-relative path prefixes where request context is live.
HOT_PATH_PREFIXES = (
    "oryx_tpu/serving/",
    "oryx_tpu/transport/",
    "oryx_tpu/lambda_rt/",
)


class LogDisciplineChecker:
    id = ID

    def check(self, project) -> list:
        out = []
        for fctx in project.files:
            if not fctx.relpath.startswith(HOT_PATH_PREFIXES):
                continue
            for node in ast.walk(fctx.tree):
                if not isinstance(node, ast.Call):
                    continue
                if (
                    isinstance(node.func, ast.Name)
                    and node.func.id == "print"
                    and "print" not in fctx.import_map
                ):
                    out.append(fctx.finding(
                        ID, node,
                        "print() in a library hot path — stdout has no "
                        "level, no handler, and no trace correlation; use "
                        "oryx_tpu.common.spans.get_logger(__name__)",
                        symbol=f"print:{node.lineno}",
                    ))
                    continue
                resolved = fctx.resolve(node.func)
                if resolved == "logging.getLogger":
                    out.append(fctx.finding(
                        ID, node,
                        "bare logging.getLogger() in a library hot path — "
                        "its lines drop the trace/span ids; use "
                        "oryx_tpu.common.spans.get_logger(__name__) so log "
                        "lines correlate with GET /trace",
                        symbol=f"getLogger:{node.lineno}",
                    ))
        return out
