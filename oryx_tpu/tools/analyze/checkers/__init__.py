"""Checker registry: one instance per checker id, in report order."""

from oryx_tpu.tools.analyze.checkers.recompile import JitRecompileChecker
from oryx_tpu.tools.analyze.checkers.tracer import TracerLeakChecker
from oryx_tpu.tools.analyze.checkers.blocking import BlockingAsyncChecker
from oryx_tpu.tools.analyze.checkers.hotcompile import HotPathCompileChecker
from oryx_tpu.tools.analyze.checkers.locks import LockDisciplineChecker
from oryx_tpu.tools.analyze.checkers.concurrency import (
    BlockingUnderLockChecker,
    LockOrderCycleChecker,
    SharedStateEscapeChecker,
)
from oryx_tpu.tools.analyze.checkers.confkeys import ConfigKeyDriftChecker
from oryx_tpu.tools.analyze.checkers.float64 import Float64PromotionChecker
from oryx_tpu.tools.analyze.checkers.logstyle import LogDisciplineChecker
from oryx_tpu.tools.analyze.checkers.swallowed import SwallowedExceptionChecker
from oryx_tpu.tools.analyze.checkers.perrowstore import PerRowNdarrayStoreChecker
from oryx_tpu.tools.analyze.checkers.replicated import ReplicatedCollectiveChecker
from oryx_tpu.tools.analyze.checkers.hosttransfer import HostDeviceTransferChecker
from oryx_tpu.tools.analyze.checkers.dtypewidth import DtypeWideningChecker
from oryx_tpu.tools.analyze.checkers.pallas import (
    KernelAliasDisciplineChecker,
    KernelIndexBoundsChecker,
    KernelInterpretDefaultChecker,
    KernelTileAlignmentChecker,
    KernelVmemBudgetChecker,
)
from oryx_tpu.tools.analyze.checkers.protocolmodel import ProtocolModelDriftChecker

ALL_CHECKERS = (
    JitRecompileChecker(),
    TracerLeakChecker(),
    BlockingAsyncChecker(),
    HotPathCompileChecker(),
    LockDisciplineChecker(),
    LockOrderCycleChecker(),
    BlockingUnderLockChecker(),
    SharedStateEscapeChecker(),
    ConfigKeyDriftChecker(),
    Float64PromotionChecker(),
    LogDisciplineChecker(),
    SwallowedExceptionChecker(),
    PerRowNdarrayStoreChecker(),
    ReplicatedCollectiveChecker(),
    HostDeviceTransferChecker(),
    DtypeWideningChecker(),
    KernelVmemBudgetChecker(),
    KernelTileAlignmentChecker(),
    KernelIndexBoundsChecker(),
    KernelAliasDisciplineChecker(),
    KernelInterpretDefaultChecker(),
    ProtocolModelDriftChecker(),
)

#: checker id -> precision version, recorded per baseline entry so a
#: checker upgrade invalidates stale justifications loudly (core.py).
CHECKER_VERSIONS = {c.id: getattr(c, "version", 1) for c in ALL_CHECKERS}
