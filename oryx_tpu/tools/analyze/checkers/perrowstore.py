"""per-row-ndarray-store: dict-of-small-ndarray accumulation in hot paths.

The round-9 factor-arena migration exists because the serving/speed host
stores were ``dict[str, np.ndarray]`` maps: one Python ndarray object
(~200 B of header) plus a dict slot and key string per row. At reference
scale (millions of rows) that multiplies host RSS 2-3× over the raw factor
bytes (measured: 2.24× dict vs 1.27× arena at 1M × 50f) and turns every
device materialization into a million-element ``np.stack``. The sanctioned
pattern is an arena: ids → row indices into one contiguous slab
(models/als/vectors.py).

This checker flags the accumulation shape so it cannot quietly grow back:
inside ``oryx_tpu/models/`` and ``oryx_tpu/serving/``, a subscript store of
an ndarray-valued expression into an instance attribute that the class
initializes as a dict::

    self._vectors[id_] = np.asarray(vec, dtype=np.float32)   # flagged

Stores of scalars/indices into dicts (``self._rows[id_] = 7``) and writes
into array rows (``self._slab[row] = vec``) are the arena idiom and stay
silent. One-hop local inference follows names assigned from an
ndarray-producing expression earlier in the same function.
"""

from __future__ import annotations

import ast

from oryx_tpu.tools.analyze.core import walk_scope

ID = "per-row-ndarray-store"

#: Module-path prefixes whose per-id stores sit on model/serving hot paths.
_HOT_PREFIXES = ("oryx_tpu/models/", "oryx_tpu/serving/")

#: Calls whose result is a (fresh) ndarray — the per-row allocation the
#: arena exists to eliminate.
_NDARRAY_CALLS = {
    "numpy.asarray", "numpy.array", "numpy.ascontiguousarray",
    "numpy.copy", "numpy.zeros", "numpy.ones", "numpy.full", "numpy.empty",
    "numpy.stack", "numpy.concatenate", "numpy.frombuffer", "numpy.fromiter",
    "jax.numpy.asarray", "jax.numpy.array", "jax.numpy.zeros",
    "jax.numpy.ones",
}

#: Method calls that (near-)always yield a fresh ndarray. ``.copy()`` is
#: deliberately NOT here unconditionally — sets/dicts/lists copy too, and
#: a ``known.copy()`` into a bookkeeping dict must stay silent; it only
#: counts when its receiver is itself array-like (see _is_ndarray_expr).
_NDARRAY_METHODS = {"astype"}


def _is_dict_init(value: ast.AST) -> bool:
    return isinstance(value, ast.Dict) or (
        isinstance(value, ast.Call)
        and isinstance(value.func, ast.Name)
        and value.func.id == "dict"
    )


def _dict_annotation(node: ast.AST) -> bool:
    """True for ``dict[...]``/``Dict[...]`` annotations."""
    if isinstance(node, ast.Subscript):
        node = node.value
    name = getattr(node, "id", None) or getattr(node, "attr", None)
    return name in ("dict", "Dict")


class PerRowNdarrayStoreChecker:
    id = ID

    def check(self, project) -> list:
        out = []
        for fctx in project.files:
            if not fctx.relpath.startswith(_HOT_PREFIXES):
                continue
            out.extend(self._check_file(fctx))
        return out

    # -- helpers ------------------------------------------------------------
    def _dict_attrs(self, cnode: ast.ClassDef) -> set:
        """Attribute names this class initializes (or annotates) as dicts."""
        attrs: set = set()
        for node in ast.walk(cnode):
            if isinstance(node, ast.Assign) and _is_dict_init(node.value):
                for target in node.targets:
                    if (isinstance(target, ast.Attribute)
                            and isinstance(target.value, ast.Name)
                            and target.value.id == "self"):
                        attrs.add(target.attr)
            elif isinstance(node, ast.AnnAssign):
                target = node.target
                if (isinstance(target, ast.Attribute)
                        and isinstance(target.value, ast.Name)
                        and target.value.id == "self"
                        and _dict_annotation(node.annotation)):
                    attrs.add(target.attr)
        return attrs

    def _is_ndarray_expr(self, fctx, node: ast.AST, local_arrays: set) -> bool:
        if isinstance(node, ast.Name):
            return node.id in local_arrays
        if isinstance(node, ast.Call):
            func = node.func
            if isinstance(func, ast.Attribute):
                if func.attr in _NDARRAY_METHODS:
                    return True
                if func.attr == "copy":
                    # only when the receiver is itself array-like: a bare
                    # `known.copy()` (set/dict) must not fire
                    return self._is_ndarray_expr(fctx, func.value, local_arrays)
            resolved = fctx.resolve(func)
            return resolved in _NDARRAY_CALLS
        return False

    def _check_file(self, fctx) -> list:
        out = []
        for cqual, cnode in fctx.classes:
            dict_attrs = self._dict_attrs(cnode)
            if not dict_attrs:
                continue
            for child in cnode.body:
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    out.extend(self._check_method(
                        fctx, cqual, child, dict_attrs
                    ))
        return out

    def _check_method(self, fctx, cqual: str, fn, dict_attrs: set) -> list:
        out = []
        # one-hop local inference: names bound from ndarray-producing
        # expressions anywhere in this function body
        local_arrays: set = set()
        for node in walk_scope(fn):
            if isinstance(node, ast.Assign) and self._is_ndarray_expr(
                    fctx, node.value, set()):
                for target in node.targets:
                    if isinstance(target, ast.Name):
                        local_arrays.add(target.id)
        for node in walk_scope(fn):
            if not isinstance(node, ast.Assign):
                continue
            for target in node.targets:
                if not (isinstance(target, ast.Subscript)
                        and isinstance(target.value, ast.Attribute)
                        and isinstance(target.value.value, ast.Name)
                        and target.value.value.id == "self"
                        and target.value.attr in dict_attrs):
                    continue
                if self._is_ndarray_expr(fctx, node.value, local_arrays):
                    attr = target.value.attr
                    out.append(fctx.finding(
                        ID, node,
                        f"per-row ndarray accumulation: `self.{attr}[...]` "
                        f"stores an ndarray per key in `{cqual}.{fn.name}` — "
                        "at model scale the per-key Python/numpy object "
                        "overhead multiplies host RSS 2-3x over raw factor "
                        "bytes; intern rows into a contiguous arena slab "
                        "(models/als/vectors.py FeatureVectorStore)",
                        symbol=f"{cqual}.{fn.name}:{attr}",
                    ))
        return out
