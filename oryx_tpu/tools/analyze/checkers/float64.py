"""float64-promotion: float64 constants flowing into jitted numerics.

The framework's device numerics are float32/bfloat16 by design (factors,
scores, Gramians); the tests enable x64, so an ``np.float64`` constant or a
dtype-less host-numpy array creation inside a jit scope silently promotes the
whole expression to f64 there — 2x HBM, no MXU — while staying f32 in
production. Host-side float64 (the SVD solver, PMML codecs) is deliberate
and out of scope: only jitted scopes are checked.

Flagged inside jit: references to ``np/jnp.float64``, ``dtype="float64"`` or
``dtype=float`` (builtin float == f64), ``.astype(float64)``, and host
``np.array/zeros/ones/full/empty`` creations with no dtype argument (numpy
defaults them to f64). ``tracer-leak`` owns numpy-on-traced-values; this
checker skips those to avoid double reports.
"""

from __future__ import annotations

import ast

from oryx_tpu.tools.analyze.core import walk_scope

ID = "float64-promotion"

_NP_CREATORS = {"array", "zeros", "ones", "full", "empty", "asarray", "arange"}


class Float64PromotionChecker:
    id = ID

    def check(self, project) -> list:
        out = []
        for fctx in project.files:
            for scope in fctx.jit_scopes.values():
                out.extend(self._check_scope(fctx, scope))
        return out

    @staticmethod
    def _is_f64_ref(fctx, node) -> bool:
        if isinstance(node, ast.Constant):
            return node.value == "float64"
        if isinstance(node, ast.Name) and node.id == "float":
            return True
        resolved = fctx.resolve(node)
        return resolved in ("numpy.float64", "jax.numpy.float64")

    def _check_scope(self, fctx, scope) -> list:
        out = []
        traced = fctx.traced_names(scope)
        for node in walk_scope(scope.node):
            if isinstance(node, ast.keyword) and node.arg == "dtype":
                if self._is_f64_ref(fctx, node.value):
                    out.append(fctx.finding(
                        ID, node.value,
                        f"dtype=float64 inside jitted `{scope.qualname}` — "
                        "promotes the computation off the f32/bf16 path",
                        symbol=f"{scope.qualname}:dtype",
                    ))
                continue
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if isinstance(func, ast.Attribute) and func.attr == "astype":
                if any(self._is_f64_ref(fctx, a) for a in node.args):
                    out.append(fctx.finding(
                        ID, node,
                        f".astype(float64) inside jitted `{scope.qualname}` — "
                        "doubles HBM traffic and leaves the MXU",
                        symbol=f"{scope.qualname}:astype",
                    ))
                continue
            resolved = fctx.resolve(func)
            if resolved in ("numpy.float64", "jax.numpy.float64"):
                out.append(fctx.finding(
                    ID, node,
                    f"np.float64(...) constant inside jitted `{scope.qualname}`"
                    " — promotes downstream arithmetic to f64",
                    symbol=f"{scope.qualname}:float64",
                ))
                continue
            if (
                resolved
                and resolved.split(".")[0] == "numpy"
                and resolved.rpartition(".")[2] in _NP_CREATORS
                and not any(kw.arg == "dtype" for kw in node.keywords)
                and not any(fctx.is_traced(a, traced) for a in node.args)
            ):
                out.append(fctx.finding(
                    ID, node,
                    f"host `{ast.unparse(func)}` creation without dtype inside "
                    f"jitted `{scope.qualname}` — numpy defaults to float64 "
                    "(pass dtype=np.float32 or use jnp)",
                    symbol=f"{scope.qualname}:np-default-dtype",
                ))
        return out
