"""blocking-async: event-loop stalls reachable from ``async def`` handlers.

The serving tier is one asyncio loop; any synchronous sleep, file write,
device fetch, or lock acquisition inside a handler stalls EVERY in-flight
request — the p99-inflating bug class behind VERDICT r5 weak #5. Flagged
when reachable from an ``async def``:

  * ``time.sleep``, ``subprocess.*``, builtin ``open()``, blocking ``os.*``
    file calls
  * ``jax.device_get`` / ``.block_until_ready()`` (synchronous device I/O)
  * lock acquisition: ``with <anything named *lock*>``, ``.acquire()``,
    ``AutoLock``/``AutoReadWriteLock`` handles
  * ``<*producer*>.send(...)`` — the topic producer's send does file I/O
    under the broker lock on ``file:`` brokers
  * raw socket I/O: ``socket.create_connection`` and
    ``<*sock*>.{connect,recv,sendall}`` — the tcp broker hazard class: the
    netbroker server/``cli broker`` event loop must reach sockets only
    through asyncio streams (or the sync client, which runs on threads)

Reachability is a project-wide call graph over resolvable calls (module
functions, ``from``-imports, ``module.fn``, ``self.method``), so a handler
calling a sync helper that blocks is flagged at the handler's call site.
Callables handed to ``run_in_executor`` (the sanctioned escape hatch) are
references, not calls, and naturally stay clean; nested defs/lambdas are
likewise only charged where they are actually invoked.
"""

from __future__ import annotations

import ast

from oryx_tpu.tools.analyze.core import scope_nodes

ID = "blocking-async"

_BLOCKING_RESOLVED = {
    "time.sleep": "time.sleep() sleeps the whole event loop (use asyncio.sleep)",
    "subprocess.run": "subprocess.run blocks the event loop",
    "subprocess.call": "subprocess.call blocks the event loop",
    "subprocess.check_call": "subprocess.check_call blocks the event loop",
    "subprocess.check_output": "subprocess.check_output blocks the event loop",
    "jax.device_get": "jax.device_get is a synchronous device fetch",
    "socket.create_connection": "socket.create_connection blocks the event "
                                "loop (use asyncio.open_connection)",
}

#: Methods that block on a raw socket when the receiver is named like one.
_BLOCKING_SOCKET_METHODS = {"connect", "recv", "sendall"}

_BLOCKING_OS = {
    "open", "remove", "rename", "replace", "fsync", "makedirs", "listdir",
    "unlink", "scandir", "stat",
}

_LOCK_CTORS = {
    "oryx_tpu.common.lockutils.AutoLock",
    "oryx_tpu.common.lockutils.AutoReadWriteLock",
    "threading.Lock",
    "threading.RLock",
    "threading.Condition",
}


def _identifiers(node: ast.AST) -> list:
    """All identifier parts of a name/attribute/call chain, outermost last."""
    out = []
    while True:
        if isinstance(node, ast.Call):
            node = node.func
        elif isinstance(node, ast.Attribute):
            out.append(node.attr)
            node = node.value
        elif isinstance(node, ast.Name):
            out.append(node.id)
            return out
        else:
            return out


class BlockingAsyncChecker:
    id = ID

    def check(self, project) -> list:
        # -- pass 1: per-function direct blocking facts over the SHARED
        # project call graph (built once per run, core.CallGraph) ----------
        graph = project.call_graph()
        edges = graph.edges
        async_keys = graph.async_keys

        facts = {}  # (relpath, qualname) -> (line, cause) | None
        for key, (fctx, fn) in graph.functions.items():
            facts[key] = self._direct_fact(fctx, fn)

        # -- pass 2: propagate blocking through the call graph --------------
        blocking = graph.propagate(
            {k: v for k, v in facts.items() if v is not None}
        )

        # -- report: async functions only -----------------------------------
        out = []
        for fctx in project.files:
            for qual, fn in fctx.functions:
                key = (fctx.relpath, qual)
                if key not in async_keys:
                    continue
                direct = facts.get(key)
                if direct is not None:
                    line, cause = direct
                    out.append(fctx.finding(
                        ID, line,
                        f"async `{qual}` blocks the event loop: {cause} "
                        "(await an async equivalent or run_in_executor)",
                        symbol=qual,
                    ))
                    continue
                for line, callee, label in edges[key]:
                    if callee in blocking and callee not in async_keys:
                        _, cause = blocking[callee]
                        out.append(fctx.finding(
                            ID, line,
                            f"async `{qual}` calls {label} which blocks the "
                            f"event loop ({cause}) — run it in an executor",
                            symbol=f"{qual}->{callee[1]}",
                        ))
                        break  # one finding per handler keeps the report readable
        return out

    # -- fact/edge extraction ------------------------------------------------
    def _direct_fact(self, fctx, fn):
        for node in scope_nodes(fctx, fn):
            if isinstance(node, ast.With):
                for item in node.items:
                    ids = [s.lower() for s in _identifiers(item.context_expr)]
                    ctor = (
                        fctx.resolve(item.context_expr.func)
                        if isinstance(item.context_expr, ast.Call)
                        else None
                    )
                    if ctor in _LOCK_CTORS or any("lock" in s for s in ids):
                        src = ast.unparse(item.context_expr)
                        return (node.lineno, f"`with {src}` acquires a thread lock")
            if not isinstance(node, ast.Call):
                continue
            resolved = fctx.resolve(node.func)
            if resolved in _BLOCKING_RESOLVED:
                return (node.lineno, _BLOCKING_RESOLVED[resolved])
            if resolved and resolved.startswith("os.") and resolved[3:] in _BLOCKING_OS:
                return (node.lineno, f"{resolved} does synchronous file I/O")
            if (
                isinstance(node.func, ast.Name)
                and node.func.id == "open"
                and "open" not in fctx.import_map
            ):
                return (node.lineno, "builtin open() does synchronous file I/O")
            if isinstance(node.func, ast.Attribute):
                attr = node.func.attr
                recv = _identifiers(node.func.value)
                recv_l = [s.lower() for s in recv]
                if attr == "acquire" and any("lock" in s for s in recv_l):
                    return (node.lineno, f"`{ast.unparse(node.func)}()` acquires a thread lock")
                if attr == "block_until_ready":
                    return (node.lineno, "`.block_until_ready()` waits on the device")
                if attr in _BLOCKING_SOCKET_METHODS and any(
                    "sock" in s for s in recv_l
                ):
                    return (
                        node.lineno,
                        f"`{ast.unparse(node.func)}()` does synchronous "
                        "socket I/O (use asyncio streams on the event loop)",
                    )
                if attr == "send" and any("producer" in s for s in recv_l):
                    return (
                        node.lineno,
                        f"`{ast.unparse(node.func)}()` — topic producer send does "
                        "file I/O under the broker lock on file: brokers",
                    )
        return None
