"""host-device-transfer: silent device→host syncs on hot paths.

Every ``np.asarray(device_val)``, ``float()``, ``.item()``, ``.tolist()``,
or implicit numpy-op on a device array is a blocking round trip through the
transfer engine. Three contexts make it a bug rather than a design choice:

  * **(A) event-loop reachability** — a sync in any function an ``async
    def`` actually calls (project call graph) stalls every in-flight
    request: the static cousin of the PR-10 loop-stall watchdog's catch.
    Callables hopped through ``to_thread``/``run_in_executor`` are
    references, not calls, so the sanctioned executor escape stays clean.
  * **(B) inner training loops** — a transfer inside a ``for``/``while``
    body in a trainer module (``models/**/train.py``, ``lambda_rt/``)
    serializes the device against the host once per iteration.
  * **(C) per-element scalar syncs** — ``float(...)``/``.item()`` applied
    per element in a loop/comprehension over device-returning calls inside
    ``models/``/``serving/``: the death-by-a-thousand-syncs shape (one
    dispatch + one transfer per item instead of one batched call). Lambda
    bodies count here — the shape is the hazard wherever it finally runs.

``jax.device_get`` is deliberately exempt: it is the explicit, batched
transfer idiom fixes should reach for (and ``blocking-async`` already owns
its event-loop reachability). Jit scopes are skipped — ``tracer-leak`` owns
numpy-on-traced-values inside traced code.
"""

from __future__ import annotations

import ast

from oryx_tpu.tools.analyze.dataflow import (
    DeviceFlow,
    SCALAR_TRANSFERS,
    SCALAR_TRANSFER_METHODS,
    async_reachable,
    transfer_of_call,
)

ID = "host-device-transfer"

_TRAIN_TIER_MARKERS = ("/train.py", "lambda_rt/")
_HOT_TIER_PREFIXES = ("oryx_tpu/models/", "oryx_tpu/serving/")


def _is_train_tier(relpath: str) -> bool:
    return any(m in relpath for m in _TRAIN_TIER_MARKERS)


def _is_hot_tier(relpath: str) -> bool:
    return relpath.startswith(_HOT_TIER_PREFIXES)


def _may_touch_device(fctx) -> bool:
    """Cheap file gate: a file can only hold device values if it imports
    jax itself or a project module (which may re-export device-returning
    helpers, the ``vm.cosine_similarity`` shape)."""
    return any(origin.split(".")[0] in ("jax", "oryx_tpu")
               for origin in fctx.import_map.values())


def _transfer_operands(call: ast.Call) -> list:
    """The expressions a transfer call would fetch: the single operand for
    scalar casts and methods, every positional arg for numpy-op mixing."""
    func = call.func
    if isinstance(func, ast.Name) and func.id in SCALAR_TRANSFERS:
        return list(call.args) if len(call.args) == 1 else []
    if isinstance(func, ast.Attribute) and func.attr in SCALAR_TRANSFER_METHODS:
        return [func.value]
    return list(call.args)


def _is_scalar_kind(kind: str) -> bool:
    return kind in ("float()", "int()", "bool()", ".item()", ".tolist()")


class _SiteWalker:
    """Collect transfer-shaped calls with their loop/lambda context and the
    comprehension-target bindings in scope at each site. Loop context
    covers ``for``/``while`` bodies, ``while`` tests, and comprehension
    element/condition expressions — but NOT a ``for`` statement's iterable,
    which evaluates once, and NOT loop ``else:`` arms, which run at most
    once. Comprehension targets are their own scope: ``v`` in ``[float(v)
    for v in hostvals]`` binds one element of ``hostvals``, shadowing any
    earlier (possibly device) ``v`` — the bindings map lets the checker
    resolve such names to their iterable instead of the outer flow state."""

    def __init__(self):
        self.sites: list = []  # (call, in_loop, in_lambda, bindings)

    def visit(self, node, in_loop: bool, in_lambda: bool,
              bindings: "dict | None" = None) -> None:
        bindings = bindings or {}
        if isinstance(node, ast.Call):
            self.sites.append((node, in_loop, in_lambda, bindings))
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return
        if isinstance(node, ast.Lambda):
            self.visit(node.body, in_loop, True, bindings)
            return
        if isinstance(node, (ast.For, ast.AsyncFor)):
            self.visit(node.iter, in_loop, in_lambda, bindings)
            for stmt in node.body:
                self.visit(stmt, True, in_lambda, bindings)
            for stmt in node.orelse:  # else: runs at most ONCE per loop
                self.visit(stmt, in_loop, in_lambda, bindings)
            return
        if isinstance(node, ast.While):
            self.visit(node.test, True, in_lambda, bindings)
            for stmt in node.body:
                self.visit(stmt, True, in_lambda, bindings)
            for stmt in node.orelse:
                self.visit(stmt, in_loop, in_lambda, bindings)
            return
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.GeneratorExp,
                             ast.DictComp)):
            inner = dict(bindings)
            for gen in node.generators:
                self.visit(gen.iter, in_loop, in_lambda, bindings)
                for n in ast.walk(gen.target):
                    if isinstance(n, ast.Name):
                        inner[n.id] = gen.iter
                for cond in gen.ifs:
                    self.visit(cond, True, in_lambda, inner)
            if isinstance(node, ast.DictComp):
                self.visit(node.key, True, in_lambda, inner)
                self.visit(node.value, True, in_lambda, inner)
            else:
                self.visit(node.elt, True, in_lambda, inner)
            return
        for child in ast.iter_child_nodes(node):
            self.visit(child, in_loop, in_lambda, bindings)


class HostDeviceTransferChecker:
    id = ID
    version = 1

    def check(self, project) -> list:
        reach = async_reachable(project)  # memoizes the shared call graph
        out = []
        for fctx in project.files:
            if not _may_touch_device(fctx):
                continue  # no jax/project imports: no device values to fetch
            jit_nodes = set(fctx.jit_scopes)
            train_tier = _is_train_tier(fctx.relpath)
            hot_tier = _is_hot_tier(fctx.relpath)
            for qual, fn in fctx.functions:
                if fn in jit_nodes:
                    continue  # tracer-leak owns traced scopes
                key = (fctx.relpath, qual)
                on_loop = key in reach
                if not (on_loop or train_tier or hot_tier):
                    continue
                flow = None
                walker = _SiteWalker()
                for stmt in fn.body:
                    walker.visit(stmt, False, False)
                for call, in_loop, in_lambda, bindings in walker.sites:
                    kind = transfer_of_call(fctx, call)
                    if kind is None:
                        continue
                    if flow is None:
                        flow = DeviceFlow(fctx, fn, project)

                    def _op_is_device(o) -> bool:
                        # a comprehension-bound name is one ELEMENT of its
                        # iterable: device iff the iterable is
                        if isinstance(o, ast.Name) and o.id in bindings:
                            return flow.expr_is_device(
                                bindings[o.id], call.lineno
                            )
                        return flow.expr_is_device(o, call.lineno)

                    operand = next(
                        (o for o in _transfer_operands(call)
                         if _op_is_device(o)),
                        None,
                    )
                    if operand is None:
                        continue
                    context = None
                    if on_loop and not in_lambda:
                        context = ("reachable from an async handler — it "
                                   "blocks the event loop for every "
                                   "in-flight request (batch with "
                                   "jax.device_get in an executor hop)")
                    elif in_loop and not in_lambda and train_tier:
                        context = ("inside an inner training-tier loop — "
                                   "one blocking device round-trip per "
                                   "iteration (hoist it, or batch the "
                                   "fetch with one jax.device_get)")
                    elif in_loop and hot_tier and _is_scalar_kind(kind):
                        context = ("a per-element device sync in a "
                                   "models/serving loop — one dispatch + "
                                   "one transfer PER ITEM; batch the "
                                   "computation into a single device call")
                    if context is None:
                        continue
                    out.append(fctx.finding(
                        ID, call,
                        f"`{kind.rstrip('()')}({ast.unparse(operand)[:40]})` "
                        f"fetches a device value host-side in `{qual}`, "
                        f"{context}",
                        symbol=f"{qual}:{kind}:{ast.unparse(operand)[:30]}",
                    ))
        return out
