"""lock-discipline: attributes written under a lock but accessed without it.

The framework's shared state (feature-vector stores, brokers, model
managers) is guarded by convention: ``with self._lock…`` around every access.
Convention decays — the race detector here is structural: within a class that
owns a lock (``threading.Lock``/``RLock``/``Condition``, ``AutoLock``,
``AutoReadWriteLock``, or any ``*lock*``-named attribute), an attribute that
is WRITTEN under a lock context in one method and READ OR WRITTEN outside any
lock context in another method is a finding. ``__init__`` (single-threaded
construction) and the guarded accesses themselves are exempt, so a class
whose every post-init access is guarded stays silent.
"""

from __future__ import annotations

import ast

from oryx_tpu.tools.analyze.core import walk_scope

ID = "lock-discipline"

_LOCK_CTORS = {
    "threading.Lock",
    "threading.RLock",
    "threading.Condition",
    "threading.Semaphore",
    "oryx_tpu.common.lockutils.AutoLock",
    "oryx_tpu.common.lockutils.AutoReadWriteLock",
}

_EXEMPT_METHODS = {"__init__", "__repr__", "__str__", "__post_init__"}


class LockDisciplineChecker:
    id = ID

    def check(self, project) -> list:
        out = []
        for fctx in project.files:
            for cqual, cnode in fctx.classes:
                out.extend(self._check_class(fctx, cqual, cnode))
        return out

    # -- class facts ---------------------------------------------------------
    @staticmethod
    def _methods(cnode):
        for child in cnode.body:
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield child

    def _lock_attrs(self, fctx, cnode) -> set:
        locks = set()
        for method in self._methods(cnode):
            for node in walk_scope(method):
                if not (isinstance(node, ast.Assign) and isinstance(node.value, ast.Call)):
                    continue
                ctor = fctx.resolve(node.value.func)
                for t in node.targets:
                    if (
                        isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"
                    ):
                        if ctor in _LOCK_CTORS or "lock" in t.attr.lower():
                            locks.add(t.attr)
        return locks

    @staticmethod
    def _with_guards(node: ast.With, locks: set) -> bool:
        """True when any with-item acquires one of the class's locks
        (``self._lock``, ``self._lock.read()``, ``self.rw.write()``…)."""
        for item in node.items:
            expr = item.context_expr
            while isinstance(expr, ast.Call):
                expr = expr.func
            parts = []
            while isinstance(expr, ast.Attribute):
                parts.append(expr.attr)
                expr = expr.value
            if isinstance(expr, ast.Name) and expr.id == "self" and (
                set(parts) & locks
            ):
                return True
        return False

    def _check_class(self, fctx, cqual, cnode) -> list:
        locks = self._lock_attrs(fctx, cnode)
        if not locks:
            return []
        method_names = {m.name for m in self._methods(cnode)}
        # attr -> {"guarded_writes": {(method, line)}, "unguarded": {(method, line, is_write)}}
        acc: dict[str, dict] = {}

        def visit(node, method, guarded):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                    continue
                child_guarded = guarded or (
                    isinstance(child, ast.With) and self._with_guards(child, locks)
                )
                attr_node, is_write = None, False
                if (
                    isinstance(child, ast.Attribute)
                    and isinstance(child.value, ast.Name)
                    and child.value.id == "self"
                ):
                    attr_node = child
                    is_write = isinstance(child.ctx, (ast.Store, ast.Del))
                elif (
                    # container mutation: self.x[i] = v / self.x[i] += v
                    isinstance(child, ast.Subscript)
                    and isinstance(child.ctx, (ast.Store, ast.Del))
                    and isinstance(child.value, ast.Attribute)
                    and isinstance(child.value.value, ast.Name)
                    and child.value.value.id == "self"
                ):
                    attr_node = child.value
                    is_write = True
                if (
                    attr_node is not None
                    and attr_node.attr not in locks
                    and attr_node.attr not in method_names
                ):
                    rec = acc.setdefault(
                        attr_node.attr, {"guarded_writes": set(), "unguarded": set()}
                    )
                    if guarded:
                        if is_write:
                            rec["guarded_writes"].add((method, attr_node.lineno))
                    else:
                        rec["unguarded"].add((method, attr_node.lineno, is_write))
                visit(child, method, child_guarded)

        for method in self._methods(cnode):
            if method.name in _EXEMPT_METHODS:
                continue
            visit(method, method.name, False)

        out = []
        for attr in sorted(acc):
            rec = acc[attr]
            if not rec["guarded_writes"]:
                continue
            write_methods = {m for m, _ in rec["guarded_writes"]}
            reported = set()
            for method, line, is_write in sorted(rec["unguarded"], key=lambda t: t[1]):
                if method in reported:
                    continue
                if method in write_methods and not is_write:
                    # a read in the same method that also writes under the
                    # lock is usually the pre-check of a double-checked
                    # pattern; still racy, still reported
                    pass
                reported.add(method)
                w_method, w_line = sorted(rec["guarded_writes"], key=lambda t: t[1])[0]
                kind = "written" if is_write else "read"
                out.append(fctx.finding(
                    ID, line,
                    f"`self.{attr}` is written under a lock in "
                    f"`{cqual}.{w_method}` (line {w_line}) but {kind} without "
                    f"one in `{cqual}.{method}` — racy against concurrent "
                    "writers",
                    symbol=f"{cqual}.{attr}:{method}",
                ))
        return out
