"""jit-recompile: compile-churn hazards inside jitted scopes.

The 229 qps HTTP regression (VERDICT r5 weak #1) was exactly this bug class:
every distinct trace signature pays a fresh XLA compile on the hot path.
Statically detectable shapes of it:

  * Python ``if``/``while``/``for`` whose condition/iterable depends on a
    traced value — jax retraces per branch (or throws TracerBoolConversion);
    branching on ``.shape``/``.dtype``/``is None``/static args is fine and
    not flagged.
  * an f-string (or ``str()``/``repr()``/``format()``) over a traced value —
    bakes a concretized value into the trace.
  * constructing a fresh ``jax.jit`` wrapper inside a loop — its compile
    cache dies with the wrapper, so every iteration recompiles. Creation
    inside an ``lru_cache``'d builder is the sanctioned pattern and exempt.
  * ``static_argnames`` naming a parameter the function does not have — the
    argument silently stays traced (typo'd static is a recompile or a
    tracer error at call time).
"""

from __future__ import annotations

import ast

from oryx_tpu.tools.analyze.core import walk_scope

ID = "jit-recompile"

_CACHE_DECORATORS = {
    "functools.lru_cache",
    "functools.cache",
    "lru_cache",
    "cache",
}


class JitRecompileChecker:
    id = ID

    def check(self, project) -> list:
        out = []
        for fctx in project.files:
            out.extend(self._check_file(fctx))
        return out

    # -- helpers ------------------------------------------------------------
    @staticmethod
    def _is_cached_fn(fctx, fn) -> bool:
        for dec in fn.decorator_list:
            target = dec.func if isinstance(dec, ast.Call) else dec
            if fctx.resolve(target) in _CACHE_DECORATORS:
                return True
        return False

    def _check_file(self, fctx) -> list:
        out = []
        for scope in fctx.jit_scopes.values():
            out.extend(self._check_scope(fctx, scope))
            out.extend(self._check_static_names(fctx, scope))
        out.extend(self._check_jit_in_loop(fctx))
        return out

    def _check_scope(self, fctx, scope) -> list:
        out = []
        traced = fctx.traced_names(scope)
        for node in walk_scope(scope.node):
            if isinstance(node, (ast.If, ast.While)) and fctx.is_traced(node.test, traced):
                out.append(fctx.finding(
                    ID, node,
                    f"Python `{'if' if isinstance(node, ast.If) else 'while'}` on a "
                    f"traced value inside jitted `{scope.qualname}` — each branch "
                    "is a retrace/recompile (use jnp.where / lax.cond)",
                    symbol=f"{scope.qualname}:branch",
                ))
            elif isinstance(node, ast.For) and fctx.is_traced(node.iter, traced):
                out.append(fctx.finding(
                    ID, node,
                    f"Python `for` over a traced value inside jitted "
                    f"`{scope.qualname}` — unrolls per trace (use lax.scan/map)",
                    symbol=f"{scope.qualname}:for",
                ))
            elif isinstance(node, ast.JoinedStr):
                if any(
                    isinstance(v, ast.FormattedValue) and fctx.is_traced(v.value, traced)
                    for v in node.values
                ):
                    out.append(fctx.finding(
                        ID, node,
                        f"f-string formats a traced value inside jitted "
                        f"`{scope.qualname}` — concretizes at trace time and bakes "
                        "the value into the compiled program",
                        symbol=f"{scope.qualname}:fstring",
                    ))
            elif isinstance(node, ast.Call):
                fname = ast.unparse(node.func) if hasattr(ast, "unparse") else ""
                if fname in ("str", "repr", "format") and any(
                    fctx.is_traced(a, traced) for a in node.args
                ):
                    out.append(fctx.finding(
                        ID, node,
                        f"`{fname}()` of a traced value inside jitted "
                        f"`{scope.qualname}` — concretizes at trace time",
                        symbol=f"{scope.qualname}:{fname}",
                    ))
        return out

    def _check_static_names(self, fctx, scope) -> list:
        if scope.how == "nested":
            return []
        args = scope.node.args
        params = {a.arg for a in args.posonlyargs + args.args + args.kwonlyargs}
        out = []
        for name in sorted(scope.static_names - params):
            out.append(fctx.finding(
                ID, scope.node,
                f"static_argnames entry {name!r} matches no parameter of "
                f"`{scope.qualname}` — the intended argument stays traced",
                symbol=f"{scope.qualname}:static:{name}",
            ))
        return out

    def _check_jit_in_loop(self, fctx) -> list:
        """jax.jit(...) constructed inside a for/while body (fresh compile
        cache per iteration) unless the enclosing function is lru_cached."""
        if "jit(" not in fctx.source:  # textual gate: skip the full walk
            return []
        out = []

        def scan(node, in_loop: bool, cached: bool):
            for child in ast.iter_child_nodes(node):
                child_cached = cached
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    child_cached = cached or self._is_cached_fn(fctx, child)
                    scan(child, False, child_cached)
                    continue
                child_in_loop = in_loop or isinstance(child, (ast.For, ast.While))
                if (
                    in_loop
                    and not cached
                    and isinstance(child, ast.Call)
                    and fctx.resolve(child.func) in ("jax.jit", "jax.pjit")
                ):
                    out.append(fctx.finding(
                        ID, child,
                        "fresh jax.jit wrapper constructed inside a loop — its "
                        "compile cache is discarded every iteration; hoist it or "
                        "memoize the builder (functools.lru_cache)",
                        symbol="jit-in-loop",
                    ))
                scan(child, child_in_loop, child_cached)

        scan(fctx.tree, False, False)
        return out
