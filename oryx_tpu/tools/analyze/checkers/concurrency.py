"""Whole-program concurrency analysis: the generational upgrade of
``lock-discipline`` from per-class heuristics to project-wide flow.

Three checkers share one per-function lock-region analysis plus the
project call graph (``core.CallGraph``, built once per run):

  * ``lock-order-cycle`` — an interprocedural lock-acquisition-order graph:
    every ``with self._lock`` / ``.acquire()`` region contributes
    held-lock → acquired-lock edges, held-lock sets propagate through
    resolvable calls, and a cycle in the resulting graph is a potential
    deadlock (two threads can interleave the two acquisition paths). The
    finding carries BOTH paths.
  * ``blocking-under-lock`` — any ``await``, ``asyncio.to_thread``,
    ``run_in_executor``, raw-socket I/O, ``subprocess.*`` or ``time.sleep``
    reachable while a ``threading`` lock is held, plus the PR-9 spin shape:
    a ``while True`` loop with no ``break``/``return``/``raise`` under a
    lock (the tombstone-probe bug — an infinite spin that wedges every
    other thread on the lock). Locks serialize; anything slow or unbounded
    inside one is a convoy (and, on the event loop, a p99 regression).
  * ``shared-state-escape`` — instance attributes written from BOTH a
    thread-context method (a ``threading.Thread`` subclass's ``run``, or a
    method handed to ``Thread(target=...)`` / ``to_thread`` /
    ``run_in_executor``, plus methods those call) and an event-loop-context
    method (``async def``, plus sync methods they call), with no common
    guarding lock across the writes — the cross-context race
    ``lock-discipline``'s single-class view cannot see.

Lock identity is structural: ``self.<attr>`` attributes assigned a
``threading``/``lockutils`` lock constructor (or named ``*lock*``, unless
assigned an ``asyncio`` primitive — holding an asyncio lock across an
``await`` is the POINT of asyncio locks) own per-class nodes; module-level
``_x_lock = threading.Lock()`` globals own per-module nodes. Self-edges
(RLock re-entry, two instances from one allocation site) are never
reported. The runtime counterpart of the order graph is the lock sanitizer
(``oryx_tpu/tools/sanitize``, ``ORYX_SANITIZE=locks``), which observes the
REAL acquisition orders the static pass can only approximate.
"""

from __future__ import annotations

import ast

from oryx_tpu.tools.analyze.core import scope_nodes
# the sanitizer's cycle-path BFS is the same algorithm this checker needs
# (both packages are stdlib-only; one implementation, two callers)
from oryx_tpu.tools.sanitize.locks import bfs_path

ORDER_ID = "lock-order-cycle"
BLOCKING_ID = "blocking-under-lock"
ESCAPE_ID = "shared-state-escape"

_LOCK_CTORS = {
    "threading.Lock",
    "threading.RLock",
    "threading.Condition",
    "threading.Semaphore",
    "threading.BoundedSemaphore",
    "oryx_tpu.common.lockutils.AutoLock",
    "oryx_tpu.common.lockutils.AutoReadWriteLock",
}

#: asyncio primitives are NOT thread locks: holding one across an await is
#: their design, and they never block a thread — a ``*lock*``-named attr
#: assigned one of these must not create a lock node.
_ASYNC_CTORS = {
    "asyncio.Lock",
    "asyncio.Condition",
    "asyncio.Semaphore",
    "asyncio.Event",
}

#: Calls that block (or hop to) another thread of control — forbidden while
#: a threading lock is held. File I/O is deliberately absent: serializing
#: file access IS what broker locks are for.
_BLOCKING_RESOLVED = {
    "time.sleep": "`time.sleep` sleeps with the lock held",
    "asyncio.to_thread": "`asyncio.to_thread` hops to an executor with the "
                         "lock held",
    "socket.create_connection": "`socket.create_connection` does network "
                                "I/O with the lock held",
    "subprocess.run": "`subprocess.run` blocks with the lock held",
    "subprocess.call": "`subprocess.call` blocks with the lock held",
    "subprocess.check_call": "`subprocess.check_call` blocks with the lock "
                             "held",
    "subprocess.check_output": "`subprocess.check_output` blocks with the "
                               "lock held",
}

#: Attribute calls that block regardless of how the receiver is spelled.
_BLOCKING_ATTRS = {
    "run_in_executor": "`run_in_executor` schedules executor work with the "
                       "lock held (the hop's completion needs another "
                       "thread; awaiting it parks the loop with the lock)",
}

#: Socket methods that block when the receiver is named like a socket.
_SOCKET_METHODS = {"connect", "recv", "sendall"}


def _recv_parts(node: ast.AST) -> list:
    """Identifier parts of an attribute/name chain, innermost-first."""
    out = []
    while isinstance(node, ast.Attribute):
        out.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        out.append(node.id)
    return out


def _fmt_lock(node: tuple) -> str:
    """Human name of a lock node: ``Store._lock`` / ``netbroker._defaults_lock``."""
    return node[2]


class _ClassFacts:
    """Lock attributes + method ownership for one class."""

    __slots__ = ("qual", "node", "lock_attrs", "async_attrs", "methods")

    def __init__(self, qual, cnode, lock_attrs, async_attrs):
        self.qual = qual
        self.node = cnode
        self.lock_attrs = lock_attrs  # attr name -> lock node tuple
        self.async_attrs = async_attrs  # attrs holding asyncio primitives
        self.methods = {
            child.name: child
            for child in cnode.body
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef))
        }


def _class_lock_attrs(fctx, cqual, cnode) -> "tuple[dict, set]":
    """(attr name -> lock node, asyncio-primitive attrs) for locks this
    class owns. Constructor-based (threading/lockutils ctors) plus
    ``*lock*``-named attrs, EXCLUDING anything assigned an asyncio
    primitive (holding those across awaits is their design)."""
    out: dict = {}
    async_attrs: set = set()
    for child in cnode.body:
        if not isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for node in scope_nodes(fctx, child):
            if not (isinstance(node, ast.Assign) and isinstance(node.value, ast.Call)):
                continue
            ctor = fctx.resolve(node.value.func)
            for t in node.targets:
                if not (
                    isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"
                ):
                    continue
                if ctor in _ASYNC_CTORS:
                    async_attrs.add(t.attr)
                elif ctor in _LOCK_CTORS or "lock" in t.attr.lower():
                    out[t.attr] = ("C", fctx.relpath, f"{cqual}.{t.attr}")
    for attr in async_attrs:
        out.pop(attr, None)
    return out, async_attrs


def _module_locks(fctx) -> dict:
    """name -> lock node for module-global ``_x = threading.Lock()``."""
    out: dict = {}
    for stmt in fctx.tree.body:
        if not (isinstance(stmt, ast.Assign) and isinstance(stmt.value, ast.Call)):
            continue
        ctor = fctx.resolve(stmt.value.func)
        if ctor not in _LOCK_CTORS:
            continue
        for t in stmt.targets:
            if isinstance(t, ast.Name):
                mod = fctx.relpath.rsplit("/", 1)[-1]
                out[t.id] = ("M", fctx.relpath, f"{mod}:{t.id}")
    return out


def _is_unbounded_loop(while_node: ast.While) -> bool:
    """``while True`` (or constant-truthy) with no break/return/raise —
    and no yield: a generator loop suspends at every iteration, handing
    control back to the consumer — anywhere in its body: structurally
    unable to terminate or relinquish the thread."""
    test = while_node.test
    if not (isinstance(test, ast.Constant) and bool(test.value)):
        return False
    stack = list(while_node.body)
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        if isinstance(n, (ast.Break, ast.Return, ast.Raise, ast.Yield,
                          ast.YieldFrom)):
            return False
        stack.extend(ast.iter_child_nodes(n))
    return True


class _FnLockFacts:
    """Everything the three checkers need from one function body."""

    __slots__ = ("acquisitions", "order_edges", "events", "held_at_line",
                 "blocking_fact", "attr_accesses")

    def __init__(self):
        # [(lock node, line)] — every direct acquisition (any held state)
        self.acquisitions = []
        # [(held node, held line, acquired node, line)] — nested acquisitions
        self.order_edges = []
        # [(line, cause, held node, held line)] — blocking while held
        self.events = []
        # call-site line -> tuple of held (node, line): for interprocedural
        # propagation against the shared call-graph edges
        self.held_at_line = {}
        # (line, cause) | None — first direct blocking call, held or not
        # (feeds the transitive blocks() fact)
        self.blocking_fact = None
        # [(attr, line, is_write, frozenset of held lock-node tuples)]
        self.attr_accesses = []


class _FnVisitor:
    """One pass over a function body threading the held-lock list through
    statement sequence, ``with`` nesting, and branch bodies."""

    def __init__(self, fctx, cfacts: "_ClassFacts | None", module_locks: dict):
        self.fctx = fctx
        self.cfacts = cfacts
        self.module_locks = module_locks
        self.facts = _FnLockFacts()

    # -- lock resolution ----------------------------------------------------
    def lock_of(self, expr: ast.AST) -> "tuple | None":
        """Lock node acquired by a with-item / acquire receiver: strips
        call layers (``self._lock.read()``), then matches ``self.<attr>``
        chains against the class's lock attrs and bare names against the
        module's lock globals."""
        e = expr
        while isinstance(e, ast.Call):
            e = e.func
        parts = []
        while isinstance(e, ast.Attribute):
            parts.append(e.attr)
            e = e.value
        if not isinstance(e, ast.Name):
            return None
        if e.id == "self" and self.cfacts is not None:
            for p in parts:
                node = self.cfacts.lock_attrs.get(p)
                if node is not None:
                    return node
            return None
        if e.id in self.module_locks:
            # bare name or used through a handle: _rw_lock.read()
            return self.module_locks[e.id]
        return None

    def anon_lock_of(self, expr: ast.AST) -> "tuple | None":
        """A lock-ish expression that resolves to NO class/module node (a
        lock on ANOTHER object, a lock parameter): tracked as an anonymous
        node so blocking-under-lock still sees the held region, but kept
        out of the order graph — textual identity across call sites is not
        sound enough to call two anonymous mentions the same lock."""
        e = expr
        while isinstance(e, ast.Call):
            e = e.func
        parts = _recv_parts(e)
        if not parts or not any("lock" in p.lower() for p in parts):
            return None
        if self.cfacts is not None and set(parts) & self.cfacts.async_attrs:
            return None
        display = ast.unparse(e) if parts else "lock"
        return ("A", self.fctx.relpath, display)

    # -- events -------------------------------------------------------------
    def _on_acquire(self, node, line, held):
        self.facts.acquisitions.append((node, line))
        for h, hline in held:
            if h != node and h[0] != "A":
                self.facts.order_edges.append((h, hline, node, line))

    def _on_event(self, line, cause, held):
        h, hline = held[-1]
        self.facts.events.append((line, cause, h, hline))

    # -- walk ---------------------------------------------------------------
    def visit_function(self, fn) -> _FnLockFacts:
        self._visit_body(fn.body, [])
        return self.facts

    def _visit_body(self, stmts, held):
        held = list(held)  # branch-local acquires stay branch-local
        for stmt in stmts:
            self._visit_stmt(stmt, held)

    def _visit_stmt(self, stmt, held):
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            return  # nested scopes are separate functions
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            acquired = []
            for item in stmt.items:
                self._scan_expr(item.context_expr, held)
                # asyncio primitives never make lock nodes, so an
                # ``async with`` that reaches here is a thread lock used
                # from a coroutine — track it like any other region
                node = self.lock_of(item.context_expr)
                if node is not None:
                    self._on_acquire(node, stmt.lineno, held + acquired)
                    acquired.append((node, stmt.lineno))
                else:
                    anon = self.anon_lock_of(item.context_expr)
                    if anon is not None:
                        acquired.append((anon, stmt.lineno))
            self._visit_body(stmt.body, held + acquired)
            return
        if isinstance(stmt, ast.While):
            self._scan_expr(stmt.test, held)
            if _is_unbounded_loop(stmt):
                cause = ("`while True` loop with no break/return/raise can "
                         "spin forever")
                # a blocking FACT either way: a caller holding a lock around
                # a call into this spin is the PR-9 tombstone-probe shape
                if self.facts.blocking_fact is None:
                    self.facts.blocking_fact = (stmt.lineno, cause)
                if held:
                    self._on_event(stmt.lineno, cause, held)
            self._visit_body(stmt.body, held)
            self._visit_body(stmt.orelse, held)
            return
        if isinstance(stmt, ast.If):
            self._scan_expr(stmt.test, held)
            self._visit_body(stmt.body, held)
            self._visit_body(stmt.orelse, held)
            return
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._scan_expr(stmt.iter, held)
            self._scan_expr(stmt.target, held)
            self._visit_body(stmt.body, held)
            self._visit_body(stmt.orelse, held)
            return
        if isinstance(stmt, ast.Try) or stmt.__class__.__name__ == "TryStar":
            self._visit_body(stmt.body, held)
            for handler in stmt.handlers:
                self._visit_body(handler.body, held)
            self._visit_body(stmt.orelse, held)
            # the finally body runs UNCONDITIONALLY in the same scope, so
            # its acquire/release effects flow into the statements after
            # the try — `lock.acquire(); try: ... finally: lock.release()`
            # must leave the lock un-held for the rest of the function
            # (branch bodies above keep their copies: their effects are
            # conditional)
            for s in stmt.finalbody:
                self._visit_stmt(s, held)
            return
        # simple statement: a bare acquire()/release() mutates the held list
        # for the REST of this body (the non-with acquisition style)
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call):
            call = stmt.value
            if isinstance(call.func, ast.Attribute):
                node = self.lock_of(call.func.value)
                if node is None and call.func.attr in ("acquire", "release"):
                    node = self.anon_lock_of(call.func.value)
                if node is not None and call.func.attr == "acquire":
                    for a in [*call.args, *[k.value for k in call.keywords]]:
                        self._scan_expr(a, held)
                    if node[0] != "A":
                        self._on_acquire(node, stmt.lineno, held)
                    held.append((node, stmt.lineno))
                    return
                if node is not None and call.func.attr == "release":
                    for i in range(len(held) - 1, -1, -1):
                        if held[i][0] == node:
                            del held[i]
                            break
                    return
        self._scan_expr(stmt, held)

    def _scan_expr(self, root, held):
        """Events inside one statement/expression: awaits, blocking calls,
        expression-position acquires, attribute accesses, call-site held
        sets. Does not descend into nested function/lambda bodies."""
        # guard identity for shared-state-escape: full lock-node tuples, so
        # class locks AND module-global locks both count as a common guard
        # (anonymous nodes excluded — textual identity is not sound)
        held_names = frozenset(h for h, _ in held if h[0] in ("C", "M"))
        stack = [root]
        while stack:
            n = stack.pop()
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                continue
            if isinstance(n, ast.Await) and held:
                self._on_event(
                    n.lineno,
                    "`await` parks the coroutine with the lock held (every "
                    "other waiter convoys behind it)",
                    held,
                )
            elif isinstance(n, ast.Call):
                self._scan_call(n, held)
            elif (
                isinstance(n, ast.Attribute)
                and isinstance(n.value, ast.Name)
                and n.value.id == "self"
                and self.cfacts is not None
                and n.attr not in self.cfacts.lock_attrs
                and n.attr not in self.cfacts.methods
            ):
                self.facts.attr_accesses.append((
                    n.attr, n.lineno,
                    isinstance(n.ctx, (ast.Store, ast.Del)), held_names,
                ))
            stack.extend(ast.iter_child_nodes(n))

    def _scan_call(self, n: ast.Call, held):
        if held:
            self.facts.held_at_line.setdefault(
                n.lineno, tuple(held)
            )
        cause = self._blocking_cause(n)
        if cause is not None:
            if self.facts.blocking_fact is None:
                self.facts.blocking_fact = (n.lineno, cause)
            if held:
                self._on_event(n.lineno, cause, held)
        if isinstance(n.func, ast.Attribute):
            if n.func.attr == "acquire":
                node = self.lock_of(n.func.value)
                if node is not None:
                    self._on_acquire(node, n.lineno, held)
            elif n.func.attr == "wait" and len(held) > 1:
                # cond.wait() releases ITS lock but keeps every outer one —
                # a wait under a second lock convoys that lock's waiters
                node = self.lock_of(n.func.value)
                if node is not None and any(h != node for h, _ in held):
                    outer = next((hl for hl in held if hl[0] != node), None)
                    if outer is not None and held[-1][0] == node:
                        self.facts.events.append((
                            n.lineno,
                            f"`{ast.unparse(n.func)}()` waits while "
                            f"`{_fmt_lock(outer[0])}` stays held",
                            outer[0], outer[1],
                        ))

    def _blocking_cause(self, n: ast.Call) -> "str | None":
        resolved = self.fctx.resolve(n.func)
        if resolved in _BLOCKING_RESOLVED:
            return _BLOCKING_RESOLVED[resolved]
        if isinstance(n.func, ast.Attribute):
            attr = n.func.attr
            if attr in _BLOCKING_ATTRS:
                return _BLOCKING_ATTRS[attr]
            if attr in _SOCKET_METHODS:
                recv = [s.lower() for s in _recv_parts(n.func.value)]
                if any("sock" in s for s in recv):
                    return (
                        f"`{ast.unparse(n.func)}()` does synchronous socket "
                        "I/O with the lock held"
                    )
        return None


class _ProjectConcurrency:
    """The shared whole-program pass: per-function lock facts + the
    interprocedural held-set/acquisition-set propagation, computed once and
    read by all three checkers (memoized on the ProjectContext)."""

    def __init__(self, project):
        self.project = project
        self.graph = project.call_graph()
        self.fn_facts: dict = {}       # key -> _FnLockFacts
        self.fn_cfacts: dict = {}      # key -> _ClassFacts | None
        self.class_facts: dict = {}    # (relpath, cqual) -> _ClassFacts
        # calls to these keys BUILD something instead of running the body
        # (async defs -> coroutine, generators -> generator object): their
        # acquisitions and blocking facts never execute at the call site
        self.deferred_keys: set = set(self.graph.async_keys)
        self._analyze_all()
        # acq*: key -> {lock node: (line, path string)}
        self.acq = self._propagate_acquisitions()
        # blocks*: key -> (line, cause), through sync calls only
        self.blocks = self._propagate_blocking()

    # -- per-function facts -------------------------------------------------
    def _analyze_all(self) -> None:
        for fctx in self.project.files:
            mlocks = _module_locks(fctx)
            for cqual, cnode in fctx.classes:
                lock_attrs, async_attrs = _class_lock_attrs(fctx, cqual, cnode)
                self.class_facts[(fctx.relpath, cqual)] = _ClassFacts(
                    cqual, cnode, lock_attrs, async_attrs
                )
            cls_of_method: dict = {}
            for (relpath, cqual), cf in self.class_facts.items():
                if relpath != fctx.relpath:
                    continue
                for m in cf.methods.values():
                    cls_of_method[m] = cf
            for qual, fn in fctx.functions:
                key = (fctx.relpath, qual)
                cfacts = cls_of_method.get(fn)
                visitor = _FnVisitor(fctx, cfacts, mlocks)
                self.fn_facts[key] = visitor.visit_function(fn)
                self.fn_cfacts[key] = cfacts
                if any(
                    isinstance(n, (ast.Yield, ast.YieldFrom))
                    for n in scope_nodes(fctx, fn)
                ):
                    self.deferred_keys.add(key)

    # -- interprocedural propagation ----------------------------------------
    def _propagate_acquisitions(self) -> dict:
        acq: dict = {}
        for key, facts in self.fn_facts.items():
            if facts.acquisitions:
                acq[key] = {}
                for node, line in facts.acquisitions:
                    if node not in acq[key]:
                        acq[key][node] = (
                            line,
                            f"`{key[1]}` acquires `{_fmt_lock(node)}` "
                            f"({key[0]}:{line})",
                        )
        changed = True
        while changed:
            changed = False
            for key, outs in self.graph.edges.items():
                for line, callee, label in outs:
                    # calling an async def or a generator only BUILDS a
                    # coroutine/generator — its acquisitions do not happen
                    # at the call site (the same rule _propagate_blocking
                    # applies); a lock held across the await that
                    # eventually runs a coroutine is already a
                    # blocking-under-lock finding
                    if callee in self.deferred_keys:
                        continue
                    sub = acq.get(callee)
                    if not sub:
                        continue
                    mine = acq.setdefault(key, {})
                    for node, (_, path) in sub.items():
                        if node not in mine:
                            mine[node] = (line, f"{label} ({key[0]}:{line}) -> {path}")
                            changed = True
        return acq

    def _propagate_blocking(self) -> dict:
        direct = {
            key: facts.blocking_fact
            for key, facts in self.fn_facts.items()
            if facts.blocking_fact is not None
        }
        # the shared closure over edges with deferred callees dropped: a
        # call to an async def / generator only builds the object — the
        # await (or iteration) that runs it is charged separately
        edges = {
            key: [e for e in outs if e[1] not in self.deferred_keys]
            for key, outs in self.graph.edges.items()
        }
        return self.graph.propagate(direct, edges=edges)


def _project_concurrency(project) -> _ProjectConcurrency:
    cached = getattr(project, "_concurrency_pass", None)
    if cached is None:
        cached = _ProjectConcurrency(project)
        project._concurrency_pass = cached
    return cached


# ---------------------------------------------------------------------------
# lock-order-cycle
# ---------------------------------------------------------------------------


class LockOrderCycleChecker:
    id = ORDER_ID

    def check(self, project) -> list:
        cp = _project_concurrency(project)
        # edge (a, b) -> (finding location, human path)
        edges: dict = {}

        def add_edge(a, b, where, path):
            if a != b and (a, b) not in edges:
                edges[(a, b)] = (where, path)

        for key, facts in cp.fn_facts.items():
            relpath, qual = key
            for h, hline, node, line in facts.order_edges:
                add_edge(
                    h, node, (relpath, line),
                    f"`{qual}` holds `{_fmt_lock(h)}` (line {hline}) and "
                    f"acquires `{_fmt_lock(node)}` ({relpath}:{line})",
                )
            # calls made with a lock held pull the callee's transitive
            # acquisition set into the order graph (async/generator callees
            # excluded: the call site only builds the object)
            for line, callee, label in cp.graph.edges.get(key, ()):
                held = facts.held_at_line.get(line)
                sub = cp.acq.get(callee)
                if not held or not sub or callee in cp.deferred_keys:
                    continue
                for node, (_, path) in sub.items():
                    for h, hline in held:
                        add_edge(
                            h, node, (relpath, line),
                            f"`{qual}` holds `{_fmt_lock(h)}` (line {hline}) "
                            f"and calls {label} ({relpath}:{line}) -> {path}",
                        )

        return self._report_cycles(project, edges)

    @staticmethod
    def _report_cycles(project, edges: dict) -> list:
        adj: dict = {}
        for a, b in edges:
            adj.setdefault(a, set()).add(b)
            adj.setdefault(b, set())
        # shortest cycle through each edge; one finding per node set
        out = []
        seen_cycles = set()
        for (a, b), (where, path_ab) in sorted(
            edges.items(), key=lambda kv: (kv[1][0], str(kv[0]))
        ):
            back = bfs_path(adj, b, a)
            if back is None:
                continue
            cycle_nodes = frozenset([a, b, *back])
            if cycle_nodes in seen_cycles:
                continue
            seen_cycles.add(cycle_nodes)
            # render the return path b -> ... -> a edge by edge
            hops = [path_ab]
            chain = [b, *back, a]
            for x, y in zip(chain, chain[1:]):
                hop = edges.get((x, y))
                if hop is not None:
                    hops.append(hop[1])
            relpath, line = where
            fctx = project.by_relpath.get(relpath)
            names = " -> ".join(
                f"`{_fmt_lock(n)}`" for n in [a, b, *back, a]
            )
            message = (
                f"lock acquisition order cycle {names}: two threads "
                "interleaving these paths deadlock. Path A: "
                + "; Path B: ".join(hops)
            )
            symbol = "cycle:" + "<->".join(sorted(_fmt_lock(n) for n in cycle_nodes))
            if fctx is not None:
                out.append(fctx.finding(ORDER_ID, line, message, symbol=symbol))
        return out




# ---------------------------------------------------------------------------
# blocking-under-lock
# ---------------------------------------------------------------------------


class BlockingUnderLockChecker:
    id = BLOCKING_ID

    def check(self, project) -> list:
        cp = _project_concurrency(project)
        out = []
        for key, facts in cp.fn_facts.items():
            relpath, qual = key
            fctx = project.by_relpath.get(relpath)
            if fctx is None:
                continue
            reported_lines = set()
            for line, cause, h, hline in facts.events:
                if line in reported_lines:
                    continue
                reported_lines.add(line)
                out.append(fctx.finding(
                    BLOCKING_ID, line,
                    f"`{qual}` blocks while holding `{_fmt_lock(h)}` "
                    f"(acquired line {hline}): {cause} — shrink the lock "
                    "region or move the slow work outside it",
                    symbol=f"{qual}:{_fmt_lock(h)}",
                ))
            # transitive: a call made under a lock to a function that
            # (transitively) blocks
            for line, callee, label in cp.graph.edges.get(key, ()):
                held = facts.held_at_line.get(line)
                if not held or line in reported_lines:
                    continue
                sub = cp.blocks.get(callee)
                if sub is None or callee in cp.deferred_keys:
                    continue
                _, cause = sub
                h, hline = held[-1]
                reported_lines.add(line)
                out.append(fctx.finding(
                    BLOCKING_ID, line,
                    f"`{qual}` calls {label} while holding "
                    f"`{_fmt_lock(h)}` (acquired line {hline}), and it "
                    f"blocks: {cause} — shrink the lock region or move the "
                    "call outside it",
                    symbol=f"{qual}->{callee[1]}:{_fmt_lock(h)}",
                ))
        return out


# ---------------------------------------------------------------------------
# shared-state-escape
# ---------------------------------------------------------------------------

_ESCAPE_EXEMPT = {"__init__", "__post_init__", "__repr__", "__str__", "close"}


class SharedStateEscapeChecker:
    id = ESCAPE_ID

    def check(self, project) -> list:
        cp = _project_concurrency(project)
        out = []
        for (relpath, cqual), cf in sorted(cp.class_facts.items()):
            fctx = project.by_relpath.get(relpath)
            if fctx is None:
                continue
            thread_methods = self._thread_context_methods(fctx, cf)
            loop_methods = self._loop_context_methods(cf)
            # a method in BOTH contexts races with itself; classify it as
            # thread-context (the stricter report)
            loop_methods -= thread_methods
            if not thread_methods or not loop_methods:
                continue
            out.extend(self._check_class(
                fctx, relpath, cqual, cf, cp, thread_methods, loop_methods
            ))
        return out

    # -- context inference ---------------------------------------------------
    def _thread_context_methods(self, fctx, cf: _ClassFacts) -> set:
        """Methods with EVIDENCE of running on a thread: ``run`` of a
        ``threading.Thread`` subclass, methods handed to
        ``Thread(target=...)``/``to_thread``/``run_in_executor``, closed
        over ``self.method`` call edges."""
        roots: set = set()
        for base in cf.node.bases:
            if fctx.resolve(base) == "threading.Thread" and "run" in cf.methods:
                roots.add("run")
        for method in cf.methods.values():
            for node in scope_nodes(fctx, method):
                if not isinstance(node, ast.Call):
                    continue
                target_exprs = []
                resolved = fctx.resolve(node.func)
                if resolved == "threading.Thread":
                    for kw in node.keywords:
                        if kw.arg == "target":
                            target_exprs.append(kw.value)
                elif resolved == "asyncio.to_thread" and node.args:
                    target_exprs.append(node.args[0])
                elif (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr == "run_in_executor"
                    and len(node.args) >= 2
                ):
                    target_exprs.append(node.args[1])
                for te in target_exprs:
                    if (
                        isinstance(te, ast.Attribute)
                        and isinstance(te.value, ast.Name)
                        and te.value.id == "self"
                        and te.attr in cf.methods
                    ):
                        roots.add(te.attr)
        return self._close_over_self_calls(fctx, cf, roots)

    def _loop_context_methods(self, cf: _ClassFacts) -> set:
        roots = {
            name for name, m in cf.methods.items()
            if isinstance(m, ast.AsyncFunctionDef)
        }
        return self._close_over_self_calls(None, cf, roots)

    @staticmethod
    def _close_over_self_calls(fctx, cf: _ClassFacts, roots: set) -> set:
        result = set(roots)
        frontier = list(roots)
        while frontier:
            name = frontier.pop()
            method = cf.methods.get(name)
            if method is None:
                continue
            for node in ast.walk(method):
                if (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id == "self"
                    and node.func.attr in cf.methods
                    and node.func.attr not in result
                ):
                    result.add(node.func.attr)
                    frontier.append(node.func.attr)
        return result

    # -- the race check ------------------------------------------------------
    def _check_class(self, fctx, relpath, cqual, cf, cp,
                     thread_methods: set, loop_methods: set) -> list:
        # attr -> context -> [(method, line, held names frozenset)]
        writes: dict = {}
        for name, method in cf.methods.items():
            if name in _ESCAPE_EXEMPT:
                continue
            ctx = (
                "thread" if name in thread_methods
                else "loop" if name in loop_methods
                else None
            )
            if ctx is None:
                continue
            qual = fctx.qualname_of.get(method)
            facts = cp.fn_facts.get((relpath, qual))
            if facts is None:
                continue
            for attr, line, is_write, held in facts.attr_accesses:
                if not is_write:
                    continue
                writes.setdefault(attr, {}).setdefault(ctx, []).append(
                    (name, line, held)
                )
        out = []
        for attr in sorted(writes):
            per_ctx = writes[attr]
            if "thread" not in per_ctx or "loop" not in per_ctx:
                continue
            # a common lock across EVERY cross-context write makes it safe
            common = None
            for accesses in per_ctx.values():
                for _, _, held in accesses:
                    common = set(held) if common is None else common & held
            if common:
                continue
            t_m, t_line, _ = per_ctx["thread"][0]
            l_m, l_line, _ = per_ctx["loop"][0]
            out.append(fctx.finding(
                ESCAPE_ID, t_line,
                f"`self.{attr}` is written from thread context "
                f"`{cqual}.{t_m}` (line {t_line}) AND from event-loop "
                f"context `{cqual}.{l_m}` (line {l_line}) with no common "
                "guarding lock — cross-context writes race; guard both "
                "sides with one lock or confine the attribute to one "
                "context",
                symbol=f"{cqual}.{attr}",
            ))
        return out
