"""tracer-leak: host concretization of traced values inside jitted scopes.

``float()``/``int()``/``bool()``, ``np.asarray``/any host-numpy call,
``.item()``/``.tolist()``, and ``jax.device_get`` applied to a traced value
inside a jit scope either throw ``TracerArrayConversionError`` at trace time
or — worse — silently bake one concretized value into the compiled program
(correct on the first call, wrong forever after). The clean near-misses
(same calls on static values, or outside jit) are legal and not flagged.
"""

from __future__ import annotations

import ast

from oryx_tpu.tools.analyze.core import walk_scope

ID = "tracer-leak"

_CONCRETIZING_BUILTINS = {"float", "int", "bool", "complex"}
_CONCRETIZING_METHODS = {"item", "tolist", "__array__"}


class TracerLeakChecker:
    id = ID

    def check(self, project) -> list:
        out = []
        for fctx in project.files:
            for scope in fctx.jit_scopes.values():
                out.extend(self._check_scope(fctx, scope))
        return out

    def _check_scope(self, fctx, scope) -> list:
        out = []
        traced = fctx.traced_names(scope)
        for node in walk_scope(scope.node):
            if not isinstance(node, ast.Call):
                continue
            resolved = fctx.resolve(node.func)
            args_traced = any(fctx.is_traced(a, traced) for a in node.args)
            if (
                isinstance(node.func, ast.Name)
                and node.func.id in _CONCRETIZING_BUILTINS
                and args_traced
            ):
                out.append(fctx.finding(
                    ID, node,
                    f"`{node.func.id}()` of a traced value inside jitted "
                    f"`{scope.qualname}` — concretizes the tracer (move it "
                    "outside jit or keep the value on device)",
                    symbol=f"{scope.qualname}:{node.func.id}",
                ))
            elif resolved and resolved.split(".")[0] == "numpy" and args_traced:
                out.append(fctx.finding(
                    ID, node,
                    f"host numpy call `{ast.unparse(node.func)}` on a traced "
                    f"value inside jitted `{scope.qualname}` — forces a device "
                    "sync / tracer leak (use jnp)",
                    symbol=f"{scope.qualname}:numpy",
                ))
            elif resolved == "jax.device_get" and args_traced:
                out.append(fctx.finding(
                    ID, node,
                    f"jax.device_get of a traced value inside jitted "
                    f"`{scope.qualname}` — tracers cannot be fetched",
                    symbol=f"{scope.qualname}:device_get",
                ))
            elif (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in _CONCRETIZING_METHODS
                and fctx.is_traced(node.func.value, traced)
            ):
                out.append(fctx.finding(
                    ID, node,
                    f"`.{node.func.attr}()` on a traced value inside jitted "
                    f"`{scope.qualname}` — concretizes the tracer",
                    symbol=f"{scope.qualname}:{node.func.attr}",
                ))
            elif (
                isinstance(node.func, ast.Attribute)
                and node.func.attr == "block_until_ready"
                and fctx.is_traced(node.func.value, traced)
            ):
                out.append(fctx.finding(
                    ID, node,
                    f"`.block_until_ready()` inside jitted `{scope.qualname}` "
                    "— tracers have no device buffer to wait on",
                    symbol=f"{scope.qualname}:block_until_ready",
                ))
        return out
