"""The ``pallas`` checker family: static verification of Pallas kernels.

Hand-written TPU kernels fail in ways no other layer does: a BlockSpec that
walks past its operand reads garbage on chip while interpret mode (how the
CPU test suite runs every kernel) bounds-checks and hides it; an output
block revisited across grid steps without first-visit init accumulates into
whatever VMEM held before; a VMEM footprint past the per-core budget fails
to compile — or worse, the hand-derived gate guarding it drifts from the
kernel it guards. These five checks ride the parsed kernel models
(tools/analyze/kernelmodel.py):

  * ``kernel-vmem-budget`` — resident footprint (padded blocks ×2 when
    pipelined + scratch) against the per-core VMEM limit, naming the
    dominant buffer. Symbolic kernels render in ``analyze --cost`` and are
    pinned to their runtime gates by tests/test_kernel_differential.py.
  * ``kernel-tile-alignment`` — concrete block tails against the
    dtype-native tiling ((8,128) f32, (16,128) bf16, (32,128) int8):
    pad-waste when the hardware rounds a dim up, hard misalignment when a
    grid-varying map makes later blocks start mid-tile.
  * ``kernel-index-bounds`` — index map × block shape against operand
    extents over the grid: flags what it can PROVE out of bounds (concrete
    arithmetic, or a positive constant offset past a proven-exact cover),
    stays silent on what it cannot.
  * ``kernel-alias-discipline`` — ``input_output_aliases`` shape/dtype
    mismatches, and output blocks revisited across grid steps with neither
    a donated alias input nor in-kernel zero-init (the accumulator-race
    class: deterministic garbage on chip, zeros under interpret).
  * ``kernel-interpret-default`` — wrappers whose ``interpret`` defaults
    ``True`` (or hard-coded ``interpret=True`` calls): on TPU they silently
    EMULATE the kernel instead of compiling it — the PR 6
    ``spd_solve_batched`` fix class. ``None``-defaulted backend dispatch
    and caller-threaded flags are the sanctioned shapes.
"""

from __future__ import annotations

import ast
import re

from oryx_tpu.tools.analyze.kernelmodel import (
    LANE,
    SUBLANE,
    budgets,
    kernel_models,
    kernel_param_name,
    kernel_zeroes_param,
    _dim_value,
    _operand_dtype,
)

VMEM_ID = "kernel-vmem-budget"
TILE_ID = "kernel-tile-alignment"
BOUNDS_ID = "kernel-index-bounds"
ALIAS_ID = "kernel-alias-discipline"
INTERPRET_ID = "kernel-interpret-default"


class KernelVmemBudgetChecker:
    id = VMEM_ID
    version = 1

    def check(self, project) -> list:
        out = []
        limit = budgets()["vmem_limit_bytes"]
        for model in kernel_models(project):
            total = model.vmem_bytes({})
            if total is None or total <= limit:
                continue
            worst, worst_bytes = None, 0.0
            for b in model.vmem_buffers():
                size = (b.padded_bytes({}) or 0.0) * (2.0 if b.pipelined
                                                      else 1.0)
                if size > worst_bytes:
                    worst, worst_bytes = b, size
            detail = ""
            if worst is not None:
                shape = "×".join(str(d) for d in worst.shape)
                detail = (f" — dominated by the ({shape}) "
                          f"{worst.dtype or 'float32'} {worst.kind} block "
                          f"({worst_bytes / 1024.0:.0f} KiB"
                          + (" double-buffered)" if worst.pipelined else ")"))
            out.append(model.fctx.finding(
                VMEM_ID, model.call,
                f"kernel `{model.name}` needs {total / (1 << 20):.1f} MiB of "
                f"VMEM resident per grid step, past the {limit >> 20} MiB "
                f"per-core limit{detail} — shrink the block tile or spill "
                "to HBM (pltpu.ANY + manual DMA)",
                symbol=f"{model.name}:vmem",
            ))
        return out


class KernelTileAlignmentChecker:
    id = TILE_ID
    version = 1

    def check(self, project) -> list:
        out = []
        for model in kernel_models(project):
            for b in model.vmem_buffers():
                if not b.shape:
                    continue
                dims = [_dim_value(d, {}) for d in b.shape]
                sub = SUBLANE.get(b.dtype or "float32", 8)
                # (dim position from the end, required multiple, axis name)
                checks = [(1, LANE, "lane")]
                if len(dims) >= 2:
                    checks.append((2, sub, "sublane"))
                for back, mult, axis in checks:
                    d = dims[-back]
                    # size-1 dims are the per-step row-select idiom (the
                    # hardware broadcasts them); symbolic dims are the
                    # wrapper-padded case — neither is checkable here
                    if d is None or d <= 1 or d % mult == 0:
                        continue
                    padded = ((d + mult - 1) // mult) * mult
                    waste = 100.0 * (padded - d) / padded
                    varies = bool(
                        b.index_map
                        and len(b.index_map) >= back
                        and b.index_map[-back][0] != "const"
                    )
                    if varies:
                        msg = (
                            f"kernel `{model.name}`: the {axis} dim of the "
                            f"({'×'.join(str(x) for x in b.shape)}) "
                            f"{b.kind} block is {d}, not a multiple of the "
                            f"{b.dtype or 'float32'} tile ({mult}), and its "
                            "index map varies along that dim — every block "
                            "past the first starts mid-tile (Mosaic "
                            "hard-misalignment); pad the block to the tile"
                        )
                    else:
                        msg = (
                            f"kernel `{model.name}`: the {axis} dim of the "
                            f"({'×'.join(str(x) for x in b.shape)}) "
                            f"{b.kind} block is {d}; the "
                            f"{b.dtype or 'float32'} tile rounds it up to "
                            f"{padded} ({waste:.0f}% of the block's VMEM "
                            "and bandwidth is padding) — pad the dim in the "
                            "wrapper or fold it into a tiled axis"
                        )
                    out.append(model.fctx.finding(
                        TILE_ID, b.spec_node, msg,
                        symbol=f"{model.name}:{b.kind}{b.index}:{axis}",
                    ))
        return out


_FLOORDIV_RE = re.compile(r"^(.+?)\s*//\s*(.+)$")


def _covered_extent(comp, block_dim, grid):
    """The extent a map component × block dim provably covers, as
    ``(kind, value)``: ("int", n) when concrete, ("sym", expr) when the
    ``(A // B) · B`` pattern telescopes to exactly ``A`` or the block covers
    one symbolic stride, plus a ("sym_over", expr) variant for a positive
    constant offset PAST that proven-exact cover. None = unprovable."""
    bd_int = _dim_value(block_dim, {}) if not isinstance(block_dim, int) \
        else block_dim

    def scaled(grid_extent, offset_blocks):
        g_int = grid_extent if isinstance(grid_extent, int) else None
        if g_int is not None and bd_int is not None:
            return ("int", (g_int + offset_blocks) * bd_int)
        if isinstance(grid_extent, str):
            m = _FLOORDIV_RE.match(grid_extent)
            if m:
                a, b_expr = m.group(1).strip(), m.group(2).strip()
                if str(block_dim) == b_expr:
                    # (A // B) blocks of B rows cover at most A rows
                    if offset_blocks == 0:
                        return ("sym", a)
                    return ("sym_over", a)
            if bd_int == 1 and offset_blocks == 0:
                return ("sym", grid_extent)
        return None

    if comp[0] == "const":
        if bd_int is not None:
            return ("int", (comp[1] + 1) * bd_int)
        if comp[1] == 0:
            return ("sym", str(block_dim))
        return None
    if comp[0] == "grid" and comp[1] < len(grid):
        return scaled(grid[comp[1]], 0)
    if comp[0] == "grid+" and comp[1] < len(grid):
        res = scaled(grid[comp[1]], comp[2])
        if res and res[0] == "int":
            return res
        if res and res[0] == "sym":
            return ("sym_over", res[1])
        return res
    return None


class KernelIndexBoundsChecker:
    id = BOUNDS_ID
    version = 1

    def check(self, project) -> list:
        out = []
        for model in kernel_models(project):
            shape_of = model.senv.get("__shape_of__")
            for b in (*model.inputs, *model.outputs):
                if not (b.shape and b.index_map):
                    continue
                operand_shape = None
                if b.kind == "out":
                    if b.index < len(model.out_shapes):
                        operand_shape = model.out_shapes[b.index][0]
                else:
                    pos = model.num_prefetch + b.index
                    if shape_of and pos < len(model.operands):
                        operand_shape = shape_of(model.operands[pos])
                if operand_shape is None:
                    continue
                for d, comp in enumerate(b.index_map):
                    if d >= len(b.shape) or d >= len(operand_shape):
                        break
                    cover = _covered_extent(comp, b.shape[d], model.grid)
                    if cover is None:
                        continue
                    od = operand_shape[d]
                    od_int = od if isinstance(od, int) else _dim_value(od, {})
                    kind, val = cover
                    oob = None
                    if kind == "int" and od_int is not None:
                        if val > od_int:
                            oob = f"{val} > {od_int}"
                    elif kind == "sym_over" and str(od) == str(val):
                        oob = (f"at least one block past the `{val}` extent "
                               "(positive index-map offset)")
                    if oob:
                        out.append(model.fctx.finding(
                            BOUNDS_ID, b.spec_node,
                            f"kernel `{model.name}`: dim {d} of the "
                            f"{b.kind}[{b.index}] block reaches "
                            f"{oob} past operand `{b.label}` over the grid "
                            f"({'×'.join(str(g) for g in model.grid)}) — an "
                            "out-of-bounds read/write that interpret mode "
                            "clamps but real hardware does not",
                            symbol=f"{model.name}:{b.kind}{b.index}:d{d}",
                        ))
        return out


class KernelAliasDisciplineChecker:
    id = ALIAS_ID
    version = 1

    def check(self, project) -> list:
        out = []
        for model in kernel_models(project):
            shape_of = model.senv.get("__shape_of__")
            aliased_outs = set(model.aliases.values())
            # -- alias shape/dtype agreement -------------------------------
            for in_pos, out_idx in model.aliases.items():
                if out_idx >= len(model.out_shapes):
                    continue
                o_shape, o_dtype = model.out_shapes[out_idx]
                if in_pos >= len(model.operands):
                    continue
                operand = model.operands[in_pos]
                i_shape = shape_of(operand) if shape_of else None
                label = ast.unparse(operand)[:40]
                if (i_shape is not None and o_shape is not None
                        and tuple(map(str, i_shape)) != tuple(map(str, o_shape))):
                    out.append(model.fctx.finding(
                        ALIAS_ID, model.call,
                        f"kernel `{model.name}`: input_output_aliases donates "
                        f"`{label}` ({'×'.join(map(str, i_shape))}) to output "
                        f"{out_idx} ({'×'.join(map(str, o_shape))}) — aliased "
                        "buffers must agree exactly; a mismatch is silent "
                        "memory corruption on chip",
                        symbol=f"{model.name}:alias{in_pos}:shape",
                    ))
                i_dtype = _operand_dtype(model.fctx, model.enclosing, operand)
                if i_dtype and o_dtype and i_dtype != o_dtype:
                    out.append(model.fctx.finding(
                        ALIAS_ID, model.call,
                        f"kernel `{model.name}`: input_output_aliases donates "
                        f"`{label}` ({i_dtype}) to output {out_idx} "
                        f"({o_dtype}) — dtype-mismatched aliasing "
                        "reinterprets bytes",
                        symbol=f"{model.name}:alias{in_pos}:dtype",
                    ))
            # -- revisited outputs need donated or in-kernel init ----------
            for b in model.outputs:
                if b.space != "vmem" or not b.revisits_across_grid(model.grid):
                    continue
                if b.index in aliased_outs:
                    continue
                pname = kernel_param_name(model, "out", b.index)
                if kernel_zeroes_param(model, pname):
                    continue
                out.append(model.fctx.finding(
                    ALIAS_ID, b.spec_node,
                    f"kernel `{model.name}`: output {b.index}'s block is "
                    "revisited across grid steps but is neither "
                    "alias-donated (input_output_aliases) nor zero-"
                    "initialized inside the kernel (pl.when first-visit "
                    "store) — on chip the first accumulation reads whatever "
                    "VMEM held, while interpret mode shows clean zeros (the "
                    "accumulator-race class)",
                    symbol=f"{model.name}:out{b.index}:init",
                ))
        return out


class KernelInterpretDefaultChecker:
    id = INTERPRET_ID
    version = 1

    def check(self, project) -> list:
        out = []
        # functions that thread a caller-decided interpret-carrying
        # parameter (whatever it is NAMED) into a pallas_call — directly,
        # or through another threading function — mapped key -> that
        # parameter's name. A default of True anywhere on the chain
        # silently emulates on TPU.
        threading: dict = {}
        for model in kernel_models(project):
            if model.interpret is None:
                continue
            kind, val = model.interpret
            if kind == "literal" and val is True:
                out.append(model.fctx.finding(
                    INTERPRET_ID, model.call,
                    f"kernel `{model.name}`: hard-coded interpret=True — on "
                    "TPU this silently EMULATES the kernel at Python speed "
                    "instead of compiling it; thread the caller's platform "
                    "decision (interpret=<param>) or resolve None via "
                    "jax.default_backend()",
                    symbol=f"{model.name}:interpret:literal",
                ))
            elif kind == "param" and model.enclosing is not None:
                key = (model.fctx.relpath,
                       model.fctx.qualname_of.get(model.enclosing))
                threading[key] = val

        def param_default(fn, name):
            a = fn.args
            pos = a.posonlyargs + a.args
            defaults = [None] * (len(pos) - len(a.defaults)) + list(a.defaults)
            for p, d in zip(pos, defaults):
                if p.arg == name:
                    return d
            for p, d in zip(a.kwonlyargs, a.kw_defaults):
                if p.arg == name:
                    return d
            return None

        graph = project.call_graph()
        # the FileContext walk already indexed every keyword-bearing call
        # under each enclosing function; the fixpoint rounds below then
        # only touch calls whose callee name matches a known threading
        # function
        kwcalls_by_fn: "dict | None" = None
        params_by_fn: dict = {}

        def _index_calls():
            calls_by_fn: dict = {}
            for key, (fctx, fn) in graph.functions.items():
                a = fn.args
                params = {p.arg for p in a.posonlyargs + a.args + a.kwonlyargs}
                if not params:
                    continue
                entries = []
                for node in fctx.kw_calls_by_qual.get(key[1], ()):
                    if isinstance(node.func, ast.Name):
                        entries.append((node.func.id, node))
                    elif isinstance(node.func, ast.Attribute):
                        entries.append((node.func.attr, node))
                if entries:
                    params_by_fn[key] = params
                    calls_by_fn[key] = entries
            return calls_by_fn

        for _ in range(3):  # close over wrapper-of-wrapper chains
            grew = False
            # the callee's threading param arrives as the kwarg of the
            # same name; whichever of MY params feeds it makes me a
            # threading function under MY param's name
            tp_by_name: dict = {}
            for (_, qual), pname in threading.items():
                if qual:
                    tp_by_name.setdefault(qual.split(".")[-1], set()).add(pname)
            if not tp_by_name:
                break
            if kwcalls_by_fn is None:
                kwcalls_by_fn = _index_calls()
            for key, entries in kwcalls_by_fn.items():
                if key in threading:
                    continue
                params = params_by_fn[key]
                for callee_name, node in entries:
                    tp_names = tp_by_name.get(callee_name)
                    if not tp_names:
                        continue
                    mine = None
                    for kw in node.keywords:
                        if kw.arg not in tp_names:
                            continue
                        fed = sorted(
                            x.id for x in ast.walk(kw.value)
                            if isinstance(x, ast.Name) and x.id in params
                        )
                        if fed:
                            # prefer a same-named param; else deterministic
                            mine = kw.arg if kw.arg in fed else fed[0]
                            break
                    if mine is not None:
                        threading[key] = mine
                        grew = True
                        break
            if not grew:
                break

        for key, pname in threading.items():
            fctx, fn = graph.functions.get(key, (None, None))
            if fn is None:
                continue
            default = param_default(fn, pname)
            if (isinstance(default, ast.Constant) and default.value is True):
                out.append(fctx.finding(
                    INTERPRET_ID, fn,
                    f"`{key[1]}` threads `{pname}` into a Pallas kernel's "
                    "interpret flag but DEFAULTS it to True — every caller "
                    "that forgets the flag emulates the kernel on TPU at "
                    "Python speed, silently; default to None and resolve "
                    "from jax.default_backend(), or make the flag required",
                    symbol=f"{key[1]}:interpret:default",
                ))
        return out
