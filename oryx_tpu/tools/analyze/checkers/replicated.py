"""replicated-collective: model-scaled tables entering a mesh region
replicated.

Distributed-ALS routing (MLlib's block layout, arXiv:1505.06807) treats
per-iteration collective bytes as THE scaling budget: a ``shard_map``/
``pjit`` input spec'd ``P()`` (or all-``None``) all-gathers the full operand
to every device on every call. For batch-shaped operands (queries, masks)
that is the design; for a factor TABLE whose size scales with a model
dimension (N·k) it is the classic scaling bug — ROADMAP item 5(a)'s
``train.py`` replicated-``y`` all-gather, invisible to every control-flow
checker.

The decision rides the dataflow pass (tools/analyze/dataflow.py): an operand
is *model-scaled* when the wrapped function (or a one-positional-hop callee)
gathers it by data indices (``y[cs]``, ``jnp.take``) or forms its
self-Gramian (``y.T @ y``) — the factor-table signature that batch operands
never show. Closure-captured device arrays enter the region exactly like a
``P()`` in_spec and are checked the same way. Findings carry the estimated
per-call all-gather byte polynomial (``y.d0·y.d1·4``), the same expression
``analyze --cost`` evaluates under ``--bind``.
"""

from __future__ import annotations

from oryx_tpu.tools.analyze.dataflow import (
    model_scaled_params,
    replicated_bytes,
    replicated_capture_names,
    shard_regions,
    _direct_gather_evidence,
)

ID = "replicated-collective"


class ReplicatedCollectiveChecker:
    id = ID
    version = 1

    def check(self, project) -> list:
        out = []
        for region in shard_regions(project):
            fctx = region.fctx
            scaled = model_scaled_params(project, fctx, region.wrapped_node)
            for param in region.replicated:
                if param not in scaled:
                    continue
                est = replicated_bytes(param).render()
                out.append(fctx.finding(
                    ID, region.call,
                    f"replicated `{param}` enters shard_map region "
                    f"`{region.wrapped_qual}` via an unsharded in_spec: the "
                    f"full table all-gathers to every device each call "
                    f"(~{est} B) — ship only the rows each shard needs "
                    "(routing table) or shard the table",
                    symbol=f"{region.wrapped_qual}:{param}",
                ))
            for name in replicated_capture_names(project, region):
                if not _direct_gather_evidence(fctx, region.wrapped_node, name):
                    continue
                est = replicated_bytes(name).render()
                out.append(fctx.finding(
                    ID, region.call,
                    f"device array `{name}` is closure-captured by shard_map "
                    f"region `{region.wrapped_qual}`: it enters the traced "
                    f"program replicated (~{est} B all-gathered per call) "
                    "with no in_spec line to review — pass it as a sharded "
                    "argument instead",
                    symbol=f"{region.wrapped_qual}:capture:{name}",
                ))
        return out
