"""compile-on-hot-path: XLA compiles reachable from serving request handlers.

The compile-lifecycle subsystem (common/compilecache.py) exists so that NO
steady-state XLA compile happens on the request path: batch buckets are
AOT-precompiled by the warmup ladder, and model-generation swaps prewarm
off-path before flipping. This checker holds that invariant statically —
the dynamic counterpart is the ``oryx_jit_compiles_total`` counter the
bench asserts on. Flagged when reachable from an ``async def`` handler:

  * constructing a ``jax.jit`` / ``jax.pjit`` wrapper (a compile on first
    call, and a fresh compile cache per wrapper);
  * ``<jitted>.lower(...)`` with arguments — the explicit trace+compile
    entry point. Zero-argument ``.lower()`` is string case-folding and
    stays silent.

Reachability reuses the blocking-async checker's project call graph
(core.call_edges). The sanctioned route is exempt: anything defined in, or
called through, ``oryx_tpu.common.compilecache`` (``aot_compile`` et al.)
is the warmup subsystem itself — by construction it runs off-path (batch
warmer thread, startup) and its whole point is taking the compile."""

from __future__ import annotations

import ast

from oryx_tpu.tools.analyze.core import scope_nodes

ID = "compile-on-hot-path"

_JIT_CTORS = ("jax.jit", "jax.pjit", "jax.experimental.pjit.pjit")

#: the warmup subsystem: facts inside it are its job, and edges into it are
#: the sanctioned way for everyone else to compile
_EXEMPT_MODULE = "oryx_tpu.common.compilecache"


class HotPathCompileChecker:
    id = ID

    def check(self, project) -> list:
        # the SHARED project call graph (core.CallGraph, built once per run)
        # with this checker's exemption applied at use time: edges into (and
        # facts inside) the warmup subsystem are dropped, never mutated on
        # the shared structure
        graph = project.call_graph()
        async_keys = graph.async_keys

        facts = {}   # key -> (line, cause) | None
        edges = {}   # key -> [(line, callee_key, label)]
        for key, (fctx, fn) in graph.functions.items():
            exempt_file = fctx.relpath.endswith("common/compilecache.py")
            facts[key] = None if exempt_file else self._direct_fact(fctx, fn)
            edges[key] = [] if exempt_file else [
                e for e in graph.edges[key]
                if not e[1][0].endswith("common/compilecache.py")
            ]

        # propagate "compiles" through the shared closure, over THIS
        # checker's filtered edges
        compiling = graph.propagate(
            {k: v for k, v in facts.items() if v is not None}, edges=edges
        )

        out = []
        for fctx in project.files:
            for qual, fn in fctx.functions:
                key = (fctx.relpath, qual)
                if key not in async_keys:
                    continue
                direct = facts.get(key)
                if direct is not None:
                    line, cause = direct
                    out.append(fctx.finding(
                        ID, line,
                        f"async `{qual}` compiles on the request path: {cause} "
                        "(route it through the warmup subsystem — "
                        "compilecache.aot_compile / the batch warmer)",
                        symbol=qual,
                    ))
                    continue
                for line, callee, label in edges[key]:
                    if callee in compiling and callee not in async_keys:
                        _, cause = compiling[callee]
                        out.append(fctx.finding(
                            ID, line,
                            f"async `{qual}` calls {label} which compiles on "
                            f"the request path ({cause}) — precompile it via "
                            "the warmup subsystem (compilecache)",
                            symbol=f"{qual}->{callee[1]}",
                        ))
                        break  # one finding per handler keeps the report readable
        return out

    @staticmethod
    def _direct_fact(fctx, fn):
        for node in scope_nodes(fctx, fn):
            if not isinstance(node, ast.Call):
                continue
            resolved = fctx.resolve(node.func)
            if resolved in _JIT_CTORS:
                return (
                    node.lineno,
                    "constructs a jax.jit wrapper (XLA compile on first call)",
                )
            if resolved and resolved.startswith(_EXEMPT_MODULE + "."):
                continue  # the sanctioned AOT route
            if (
                isinstance(node.func, ast.Attribute)
                and node.func.attr == "lower"
                and (node.args or node.keywords)
            ):
                # .lower(shapes) — jax's explicit trace entry point; the
                # zero-arg form is str.lower() and stays silent
                return (
                    node.lineno,
                    f"`{ast.unparse(node.func)}(...)` lowers/compiles an XLA "
                    "program",
                )
        return None
