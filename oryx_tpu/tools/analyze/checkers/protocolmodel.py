"""protocol-model-drift: the protocol models must stay glued to the code.

The model checker under ``tools/analyze/protocol/`` verifies the
exactly-once state machines *as modelled*. That is only worth anything
while the model and the implementation agree, so this checker fails the
build in both drift directions:

* **stale annotation** — every transition's :class:`Site` annotation
  (``path``, dotted ``qual``, ``line``, optional ``contains`` fragment)
  must still resolve: the function exists, the line falls inside it,
  and the fragment still appears in its body. When a refactor moves
  ``_assigned`` or the token dedup, the model's claim to verify that
  code dies loudly instead of silently verifying a fiction.
* **unmodelled guard-relevant site** — transport functions that
  participate in the exactly-once story (offset commit via
  ``set_offset``/``_op_set_offset``, assignment computation via
  ``partitions_for_member``, idempotence-token mint/dedup via
  ``uuid4``/``_applied_tokens``, torn-tail recovery via
  ``ftruncate``/``_recover_tail``/``_ensure_recovered``) must each be
  covered by at least one model transition. New protocol surface cannot
  land without a decision about how the model represents it (or an
  explicit baseline suppression recording why it needs none).

Both directions skip files outside the current analysis scope, so
fixture projects that do not ship the transport layer stay clean.
"""

from __future__ import annotations

import ast

ID = "protocol-model-drift"

_TRANSPORT_PREFIX = "oryx_tpu/transport/"

#: function names that ARE guard-relevant by name alone
_NAMED = {"set_offset", "_op_set_offset", "_recover_tail", "_ensure_recovered"}

#: resolved call targets that make the calling function guard-relevant
_CALL_MARKERS = {"uuid.uuid4", "os.ftruncate"}

#: attribute whose mere mention marks the idempotence dedup path
_ATTR_MARKER = "_applied_tokens"

#: bare callee names that mark assignment computation
_ASSIGN_MARKER = "partitions_for_member"


def _site_catalog():
    """[(model_module_relpath, site_key, Site)] for every model site.

    Imported lazily so an analyze run over a project that does not ship
    the protocol package still works (and so fixture tests can override
    the catalog wholesale via ``_catalog_override``)."""
    from oryx_tpu.tools.analyze.protocol import broker_model, ckpt_model, group_model

    base = "oryx_tpu/tools/analyze/protocol/"
    out = []
    for mod, rel in (
        (group_model, base + "group_model.py"),
        (broker_model, base + "broker_model.py"),
        (ckpt_model, base + "ckpt_model.py"),
    ):
        for key, site in sorted(mod.SITES.items()):
            out.append((rel, key, site))
    return out


def _anchor_line(fctx, key: str) -> int:
    """Line of the ``"<key>": Site(`` entry in the model module."""
    needle = f'"{key}": Site('
    for i, text in enumerate(fctx.lines, start=1):
        if needle in text:
            return i
    return 1


class ProtocolModelDriftChecker:
    id = ID
    version = 1

    #: tests inject a replacement catalog: [(module_relpath, key, Site)]
    _catalog_override = None
    #: tests point the coverage scan at fixture files
    _transport_prefix_override = None

    def check(self, project) -> list:
        out: list = []
        catalog = (
            self._catalog_override
            if self._catalog_override is not None
            else _site_catalog()
        )
        prefix = self._transport_prefix_override or _TRANSPORT_PREFIX

        covered: set = set()  # (relpath, qualname) with a model transition
        for anchor_rel, key, site in catalog:
            covered.add((site.path, site.qual))
            target = project.by_relpath.get(site.path)
            if target is None:
                continue  # outside this run's scope (fixture projects)
            anchor = project.by_relpath.get(anchor_rel) or target
            line = (
                _anchor_line(anchor, key)
                if anchor is not target
                else site.line
            )
            fn = dict(target.functions).get(site.qual)
            if fn is None:
                out.append(anchor.finding(
                    ID, line,
                    f"model site {key!r} annotates {site.path}:{site.line} "
                    f"({site.qual}) but no such function exists — the "
                    "implementation moved out from under the model",
                    symbol=f"{key}:{site.qual}",
                ))
                continue
            end = getattr(fn, "end_lineno", fn.lineno)
            if not (fn.lineno <= site.line <= end):
                out.append(anchor.finding(
                    ID, line,
                    f"model site {key!r} points at {site.path}:{site.line} "
                    f"but {site.qual} now spans lines {fn.lineno}-{end} — "
                    "re-anchor the annotation",
                    symbol=f"{key}:{site.qual}",
                ))
                continue
            if site.contains:
                body = "\n".join(target.lines[fn.lineno - 1:end])
                if site.contains not in body:
                    out.append(anchor.finding(
                        ID, line,
                        f"model site {key!r} expects {site.contains!r} "
                        f"inside {site.qual} ({site.path}) but the fragment "
                        "is gone — the modelled behaviour may have changed",
                        symbol=f"{key}:{site.qual}",
                    ))

        out.extend(self._coverage(project, covered, prefix))
        return out

    # -- direction 2: guard-relevant sites must be modelled -----------------

    def _coverage(self, project, covered: set, prefix: str) -> list:
        out: list = []
        for fctx in project.files:
            if not fctx.relpath.startswith(prefix):
                continue
            for qual, fn in fctx.functions:
                name = fn.name
                if name.startswith("__") and name.endswith("__"):
                    continue
                why = self._guard_relevance(fctx, fn, name)
                if why and (fctx.relpath, qual) not in covered:
                    out.append(fctx.finding(
                        ID, fn.lineno,
                        f"{qual} is guard-relevant to the exactly-once "
                        f"protocols ({why}) but no protocol model "
                        "transition covers it — model it or record a "
                        "baseline justification",
                        symbol=qual,
                    ))
        return out

    def _guard_relevance(self, fctx, fn, name: str) -> "str | None":
        if name in _NAMED:
            return f"named {name}"
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                callee = fctx.resolve(node.func)
                if callee in _CALL_MARKERS:
                    return f"calls {callee}"
                tail = callee.rsplit(".", 1)[-1] if callee else ""
                if tail == _ASSIGN_MARKER:
                    return f"calls {_ASSIGN_MARKER}"
            elif isinstance(node, ast.Attribute) and node.attr == _ATTR_MARKER:
                return f"touches {_ATTR_MARKER}"
        return None
