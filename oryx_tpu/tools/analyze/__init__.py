"""oryx-analyze: AST-based static analysis for JAX/asyncio correctness.

The reference leaned on the JVM ecosystem (javac's type system, FindBugs-era
bytecode analysis, maven enforcer rules) for whole classes of assurance that a
dynamic TPU-native Python framework loses by default. This package rebuilds
that layer for the failure modes this codebase actually has (VERDICT r5):

  * ``jit-recompile``      — compile-churn hazards inside jitted scopes
  * ``tracer-leak``        — host concretization of traced values
  * ``blocking-async``     — event-loop stalls in serving handlers
  * ``lock-discipline``    — shared state written under a lock, read without
  * ``lock-order-cycle``   — interprocedural lock-acquisition-order cycles
                             (potential deadlocks, both paths reported)
  * ``blocking-under-lock``— await/sleep/executor/socket work (or an
                             unbounded spin) while a threading lock is held
  * ``shared-state-escape``— attributes written from both thread and
                             event-loop context with no common lock
  * ``config-key-drift``   — oryx.* keys read but undeclared, or declared but
                             never read
  * ``float64-promotion``  — float64 constants flowing into jitted numerics
  * ``replicated-collective`` — model-scaled tables entering shard_map/pjit
                             regions replicated (per-call all-gather priced
                             in shape symbols)
  * ``host-device-transfer`` — silent device→host syncs reachable from async
                             handlers, inside trainer loops, or per-element
  * ``dtype-widening``     — bf16/int8 values silently promoted to f32 in
                             jit outside sanctioned rescore/solve sites

The last three ride a shared sharding- and dtype-aware dataflow pass
(``dataflow.py``: abstract shapes, the int8≤bf16≤f32≤f64 lattice, device
placement, PartitionSpec parsing), which also powers ``analyze --cost`` —
a per-jit-program static roofline (FLOPs / HBM bytes / collective bytes as
shape-symbol polynomials, ``--bind`` to price concrete model shapes).

Run it as ``python -m oryx_tpu.cli analyze [--format json|text|sarif]``;
suppress a finding inline with ``# analyze: ignore[<checker-id>] --
justification`` or in the committed baseline
(``conf/analyze-baseline.json``), both of which require a justification
string (baseline entries also pin the checker version they were judged
against).
"""

from oryx_tpu.tools.analyze.core import (  # noqa: F401
    AnalysisResult,
    Finding,
    analyze_project,
    analyze_source,
)
